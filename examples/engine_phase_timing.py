"""Instrumented engine run on the chip: per-phase wall times at a given
batch size, to find where large-slot configs lose their time.

  BENCH_SLOTS=16 python examples/engine_phase_timing.py
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.engine.engine import EngineConfig, EngineCore
from runbookai_tpu.engine.request import EngineRequest, SamplingParams
from runbookai_tpu.models.llama import CONFIGS, init_params_quantized
from runbookai_tpu.utils.tokens import ByteTokenizer


def main():
    slots = int(os.environ.get("BENCH_SLOTS", 16))
    pages = int(os.environ.get("BENCH_PAGES", 1536))
    prompt_len = int(os.environ.get("BENCH_PROMPT", 128))
    new_tokens = int(os.environ.get("BENCH_NEW", 64))

    t0 = time.perf_counter()
    print("backend:", jax.default_backend(), jax.devices()[0].device_kind,
          flush=True)
    cfg = CONFIGS["llama3-8b-instruct"]
    params = init_params_quantized(jax.random.PRNGKey(0), cfg,
                                   dtype=jnp.bfloat16)
    jax.block_until_ready(params["layers"]["wq"]["q"])
    print(f"init_params: {time.perf_counter()-t0:.1f}s", flush=True)

    t0 = time.perf_counter()
    ecfg = EngineConfig(
        page_size=16, num_pages=pages, max_batch_slots=slots,
        prefill_chunk=128, max_seq_len=2048, kv_dtype=jnp.bfloat16,
        block_pages=16, attn_impl="pallas", prefill_batch=slots,
    )
    core = EngineCore(cfg, params, ByteTokenizer(), ecfg)
    print(f"engine init: {time.perf_counter()-t0:.1f}s", flush=True)

    rng = np.random.default_rng(0)

    def make_req(max_new):
        return EngineRequest(
            prompt_ids=rng.integers(0, 256, size=prompt_len).tolist(),
            sampling=SamplingParams(temperature=0.0, max_new_tokens=max_new,
                                    stop_token_ids=()),
        )

    for r in [make_req(new_tokens) for _ in range(slots)]:
        core.submit(r)
    steps = 0
    while core.has_work():
        t0 = time.perf_counter()
        pre_pref = len(core.prefilling)
        pre_dec = len(core.decoding)
        core.step()
        steps += 1
        print(f"step {steps:3d}: {time.perf_counter()-t0:7.2f}s "
              f"(prefilling={pre_pref}, decoding={pre_dec})", flush=True)
        if steps > 200:
            break
    m = core.metrics
    print("metrics:", {k: round(v, 3) if isinstance(v, float) else v
                       for k, v in m.items()}, flush=True)
    print("decode tok/s:", round(m["decode_tokens"] / max(m["decode_time_s"], 1e-9), 2))


if __name__ == "__main__":
    main()
