"""Microbench: is the int8->bf16 convert fused into the decode matmul?

Times qmm (weight-only int8) vs a bf16 matmul at decode shapes and reports
effective HBM bandwidth. If the convert fuses into the dot's operand read,
int8 should move ~half the bytes of bf16 and run ~2x faster; if XLA
materializes a bf16 copy of the weight, int8 is *slower* (read int8 + write
bf16 + read bf16).

Run on the chip:  python examples/microbench_qmm.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.models.llama import qmm
from runbookai_tpu.models.quant import quantize_tensor


def timeit(fn, *args, iters=50):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else fn(*args).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    print("backend:", jax.default_backend(), jax.devices()[0].device_kind)
    d_in, d_out = 4096, 14336
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (d_in, d_out), jnp.bfloat16)
    wq = quantize_tensor(w)
    wq = {"q": wq["q"], "s": wq["s"]}

    from runbookai_tpu.ops.qmm_pallas import qmm_pallas, qmm_pallas_eligible

    bf16_mm = jax.jit(lambda x, w: x @ w)
    q_mm = jax.jit(qmm)
    interp = jax.default_backend() == "cpu"

    for b in (8, 16, 32):
        x = jax.random.normal(key, (b, d_in), jnp.bfloat16)
        t_bf = timeit(bf16_mm, x, w)
        t_q = timeit(q_mm, x, wq)
        bytes_bf = d_in * d_out * 2
        bytes_q = d_in * d_out * 1
        assert qmm_pallas_eligible(b, d_in, d_out)
        t_p = timeit(lambda x, q, s: qmm_pallas(x, q, s, interpret=interp),
                     x, wq["q"], wq["s"].reshape(1, d_out),
                     iters=5 if interp else 50)
        print(f"b={b:3d}  bf16 {t_bf*1e3:7.3f} ms ({bytes_bf/t_bf/1e9:6.1f} GB/s)"
              f"   int8-xla {t_q*1e3:7.3f} ms ({bytes_q/t_q/1e9:6.1f} GB/s eff)"
              f"   int8-pallas {t_p*1e3:7.3f} ms ({bytes_q/t_p/1e9:6.1f} GB/s eff)"
              f"   pallas-vs-bf16 {t_bf/t_p:4.2f}x")

    # Scan-stacked variant: weights indexed per layer inside lax.scan, the
    # exact access pattern of the decode forward.
    L = 8
    wq_l = {"q": jnp.broadcast_to(wq["q"], (L,) + wq["q"].shape),
            "s": jnp.broadcast_to(wq["s"], (L,) + wq["s"].shape)}

    from functools import partial

    @partial(jax.jit, static_argnames=("impl",))
    def scan_qmm(x, wq_l, impl="xla"):
        def step(h, lw):
            # Feed the matmul back into the carry so the dot stays live
            # (a *0 trick would let XLA dead-code-eliminate the compute).
            out = qmm(h, {"q": lw["q"], "s": lw["s"]}, impl=impl)
            return h + 1e-6 * out[:, :h.shape[1]], None
        h, _ = jax.lax.scan(step, x, wq_l)
        return h

    x = jax.random.normal(key, (8, d_in), jnp.bfloat16)
    for impl in ("xla", "pallas"):
        iters = 20 if not (interp and impl == "pallas") else 2
        t = timeit(lambda a, b: scan_qmm(a, b, impl=impl), x, wq_l,
                   iters=iters)
        print(f"scan({L} layers) int8-{impl:6s}  {t*1e3:7.3f} ms "
              f"({L*bytes_q/t/1e9:6.1f} GB/s eff)")


if __name__ == "__main__":
    main()
