"""LoRA fine-tuning: train low-rank adapters with the base model frozen.

The trainable tree IS the serving tree: adapters live in the same stacked
``{leaf: {"A": [L, N, in, r], "B": [L, N, r, out]}}`` layout that
:mod:`runbookai_tpu.models.lora` serves from, so a tuned adapter drops
straight into a :class:`LoraRegistry` (or exports to HF PEFT format) with
no conversion. Gradients flow ONLY into the selected adapter row — the
base params are a closed-over constant of the compiled step, never updated
and never carrying optimizer state (the memory point of LoRA: Adam moments
for rank-r factors instead of the full model).

Memory note for big models: the base forward runs exactly as serving does
(bf16/int8 weights usable as-is), activations rematerialize under
``jax.checkpoint``, and the optimizer state is ~2 × rank-r bytes.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from runbookai_tpu.models.llama import LlamaConfig, forward_train
from runbookai_tpu.models.lora import LoraRegistry
from runbookai_tpu.train.trainer import masked_cross_entropy


class LoraTrainer:
    """Compiled LoRA fine-tuning step over a frozen base model.

    ``adapter_name`` selects which registry row trains; the rest of the
    stacked tree (including the reserved zero row) receives zero gradients
    through the gather and is bit-unchanged by Adam (zero grads -> zero
    moments -> zero updates).
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        base_params: Any,
        registry: LoraRegistry,
        adapter_name: str,
        learning_rate: float = 1e-4,
        pad_id: int = 0,
        remat: bool = True,
    ):
        self.cfg = cfg
        self.registry = registry
        self.adapter_name = adapter_name
        self.adapter_idx = registry.index_of(adapter_name)
        # Float32 MASTER copy (fresh buffers): the registry may hold bf16
        # for serving, where ~1e-4 Adam updates round to zero ulp and
        # training silently stalls; and the compiled step DONATES the tree
        # each update, so training on the registry's cached stacked()
        # arrays would delete buffers live serving engines still hold.
        self.lora_tree = jax.tree.map(
            # jnp.array COPIES (asarray would alias same-dtype buffers and
            # the donation would delete the registry's cache).
            lambda x: jnp.array(x, jnp.float32), registry.stacked())
        # A freshly registered adapter is all-zero — a saddle point (with
        # A=0 AND B=0 every LoRA gradient vanishes). Standard LoRA init:
        # A ~ N(0, 1/in), B = 0 — output starts at exactly zero (base
        # behavior) but dB is nonzero from step one.
        key = jax.random.PRNGKey(0)
        for t, leaves in self.lora_tree.items():
            a_row = leaves["A"][:, self.adapter_idx]
            b_row = leaves["B"][:, self.adapter_idx]
            if not (jnp.any(a_row) or jnp.any(b_row)):
                key, sub = jax.random.split(key)
                init = (jax.random.normal(sub, a_row.shape, jnp.float32)
                        / jnp.sqrt(jnp.float32(a_row.shape[1])))
                leaves["A"] = leaves["A"].at[:, self.adapter_idx].set(init)
        self.tx = optax.adam(learning_rate)
        self.opt_state = self.tx.init(self.lora_tree)
        base = {k: v for k, v in base_params.items() if k != "lora"}

        def loss_fn(lora_tree, tokens, adapter_ids):
            p = dict(base)
            p["lora"] = lora_tree
            logits = forward_train(p, cfg, tokens[:, :-1],
                                   adapter_ids=adapter_ids)
            return masked_cross_entropy(logits, tokens[:, 1:], pad_id)

        if remat:
            loss_fn = jax.checkpoint(loss_fn)

        def step_fn(lora_tree, opt_state, tokens, adapter_ids):
            loss, grads = jax.value_and_grad(loss_fn)(lora_tree, tokens,
                                                      adapter_ids)
            updates, opt_state = self.tx.update(grads, opt_state)
            lora_tree = optax.apply_updates(lora_tree, updates)
            return lora_tree, opt_state, loss

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

    def train_step(self, tokens) -> float:
        tokens = jnp.asarray(np.asarray(tokens), jnp.int32)
        adapter_ids = jnp.full((tokens.shape[0],), self.adapter_idx,
                               jnp.int32)
        self.lora_tree, self.opt_state, loss = self._step(
            self.lora_tree, self.opt_state, tokens, adapter_ids)
        return float(loss)

    def publish(self) -> None:
        """Push ONLY the trained adapter's row back into the registry so
        live engines can ``refresh_lora()`` and serve it — other rows (and
        adapters registered after this trainer was built) are untouched."""
        self.registry.update_adapter(self.adapter_name, {
            t: {"A": np.asarray(self.lora_tree[t]["A"][:, self.adapter_idx],
                                np.float32),
                "B": np.asarray(self.lora_tree[t]["B"][:, self.adapter_idx],
                                np.float32)}
            for t in self.registry.targets})

    def export_peft(self, out_dir, alpha: Optional[float] = None) -> None:
        """Write the trained adapter as an HF PEFT directory.

        The registry folds ``alpha/r`` into B at load; export divides it
        back out (default alpha = r, i.e. scale 1.0)."""
        import json
        from pathlib import Path

        from safetensors.numpy import save_file

        out = Path(out_dir)
        out.mkdir(parents=True, exist_ok=True)
        alpha = float(alpha if alpha is not None else self.registry.rank)
        inv_scale = self.registry.rank / alpha
        host = jax.tree.map(np.asarray, self.lora_tree)
        peft_of = {"wq": "q_proj", "wk": "k_proj", "wv": "v_proj",
                   "wo": "o_proj"}
        tensors = {}
        for t in self.registry.targets:
            a = host[t]["A"][:, self.adapter_idx]  # [L, in, r]
            b = host[t]["B"][:, self.adapter_idx]  # [L, r, out]
            for i in range(self.cfg.n_layers):
                base = (f"base_model.model.model.layers.{i}."
                        f"self_attn.{peft_of[t]}")
                tensors[f"{base}.lora_A.weight"] = np.ascontiguousarray(
                    a[i].T.astype(np.float32))  # [r, in]
                tensors[f"{base}.lora_B.weight"] = np.ascontiguousarray(
                    (b[i] * inv_scale).T.astype(np.float32))  # [out, r]
        save_file(tensors, str(out / "adapter_model.safetensors"))
        (out / "adapter_config.json").write_text(json.dumps({
            "r": self.registry.rank, "lora_alpha": alpha,
            "target_modules": sorted(peft_of[t]
                                     for t in self.registry.targets),
            "peft_type": "LORA",
        }, indent=2))
