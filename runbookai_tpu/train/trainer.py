"""Sharded training step: next-token fine-tuning under DP×TP pjit.

No reference counterpart (RunbookAI trains nothing); this exists so the
framework can fine-tune its served models (e.g. adapt Llama-3 to incident
vocabularies) and is the multi-chip dry-run surface: one compiled step with
the batch sharded over ``data`` and parameters Megatron-sharded over
``model``, gradients psum'd by XLA across both axes as placement dictates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbookai_tpu.models.llama import LlamaConfig, forward_train, init_params
from runbookai_tpu.parallel.mesh import DATA_AXIS
from runbookai_tpu.parallel.sharding import param_shardings


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


def masked_cross_entropy(logits: jnp.ndarray, targets: jnp.ndarray,
                         pad_id: int) -> jnp.ndarray:
    """Mean next-token cross-entropy over non-pad targets — THE loss
    definition, shared by the dense and pipeline forwards so the two
    cannot drift."""
    mask = (targets != pad_id).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def loss_fn(params, cfg: LlamaConfig, tokens: jnp.ndarray, pad_id: int) -> jnp.ndarray:
    """Mean next-token cross-entropy, ignoring pad targets."""
    logits = forward_train(params, cfg, tokens[:, :-1])
    return masked_cross_entropy(logits, tokens[:, 1:], pad_id)


class Trainer:
    """Builds sharded params/optimizer and the compiled train step."""

    def __init__(
        self,
        cfg: LlamaConfig,
        mesh: Mesh,
        learning_rate: float = 1e-5,
        weight_decay: float = 0.01,
        pad_id: int = 0,
        dtype=jnp.float32,
        remat: bool = True,
        seed: int = 0,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.pad_id = pad_id
        self.tx = optax.adamw(learning_rate, weight_decay=weight_decay)

        # Pipeline mode: with a pipe axis > 1, layers shard stage-wise and
        # the GPipe forward/backward runs the schedule (VERDICT r2 #9 —
        # "don't call it pipeline parallelism until a train step runs on a
        # pipe mesh"). DP/TP mode otherwise (Megatron shardings).
        from runbookai_tpu.parallel.mesh import PIPE_AXIS, SEQ_AXIS

        self.pipeline = mesh.shape.get(PIPE_AXIS, 1) > 1
        self.sequence_parallel = mesh.shape.get(SEQ_AXIS, 1) > 1
        if self.pipeline:
            from runbookai_tpu.parallel.pipeline import (
                loss_fn_pp,
                pp_param_shardings,
            )

            if cfg.n_layers % mesh.shape[PIPE_AXIS]:
                raise ValueError(
                    f"{cfg.n_layers} layers not divisible by "
                    f"{mesh.shape[PIPE_AXIS]} pipeline stages")
            p_shard = pp_param_shardings(cfg, mesh)
            self.n_microbatches = max(2, mesh.shape[PIPE_AXIS])

            def fwd(params, cfg_, tokens, pad):
                return loss_fn_pp(params, cfg_, tokens, pad, mesh,
                                  n_microbatches=self.n_microbatches)
        elif self.sequence_parallel:
            # SP mode: ring attention shards the SEQUENCE over the seq
            # axis (long-context training — the scale-out lever SURVEY
            # §5.7 names); params replicate, grads are exact (ppermute's
            # transpose is the reverse rotation; verified against dense
            # in tests). tokens [B, T-1] must have T-1 % seq == 0.
            from runbookai_tpu.parallel.sequence_parallel import forward_train_sp

            p_shard = param_shardings(cfg, mesh)

            def fwd(params, cfg_, tokens, pad):
                logits = forward_train_sp(params, cfg_, tokens[:, :-1], mesh)
                return masked_cross_entropy(logits, tokens[:, 1:], pad)
        else:
            p_shard = param_shardings(cfg, mesh)
            fwd = loss_fn
        params = init_params(jax.random.PRNGKey(seed), cfg, dtype=dtype)
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, p_shard,
            is_leaf=lambda x: x is None,
        )
        opt_state = self.tx.init(params)
        self.state = TrainState(params=params, opt_state=opt_state)
        batch_spec = (P() if self.pipeline or self.sequence_parallel
                      else P(DATA_AXIS, None))
        self.batch_sharding = NamedSharding(mesh, batch_spec)

        if remat:
            # Rematerialize the forward to trade FLOPs for HBM (activation
            # memory is the training bottleneck on 16GB v5e chips).
            fwd = jax.checkpoint(fwd, static_argnums=(1,))

        def step_fn(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(fwd)(params, cfg, tokens, pad_id)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = jax.jit(step_fn, donate_argnums=(0, 1))

    def train_step(self, tokens) -> float:
        tokens = jax.device_put(jnp.asarray(tokens, jnp.int32), self.batch_sharding)
        params, opt_state, loss = self._step(
            self.state.params, self.state.opt_state, tokens
        )
        self.state = TrainState(params=params, opt_state=opt_state,
                                step=self.state.step + 1)
        return float(loss)
