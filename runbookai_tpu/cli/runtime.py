"""Runtime assembly: config → LLM client + gated tools + knowledge + safety.

Parity target: reference ``createRuntimeAgent`` (cli.tsx:88-110) and the
structured-investigation driver (cli.tsx:586-660): one place that builds the
full stack for either reasoning path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from runbookai_tpu.agent.agent import Agent
from runbookai_tpu.agent.orchestrator import InvestigationOrchestrator, ToolExecutor
from runbookai_tpu.agent.safety import (
    SafetyManager,
    make_cli_approval,  # noqa: F401 — re-exported for callers/tests
    make_raced_approval,
)
from runbookai_tpu.agent.state_machine import InvestigationStateMachine
from runbookai_tpu.model.client import create_llm_client
from runbookai_tpu.tools.registry import get_runtime_tools
from runbookai_tpu.utils.config import Config


@dataclass
class Runtime:
    config: Config
    llm: Any
    tools: list[Any]
    knowledge: Optional[Any]
    safety: SafetyManager


def build_runtime(config: Config, interactive: bool = True,
                  with_knowledge: bool = True) -> Runtime:
    llm = create_llm_client(config)
    knowledge = None
    if with_knowledge and (config.knowledge.sources or _db_exists(config)):
        from runbookai_tpu.knowledge.retriever import create_retriever

        knowledge = create_retriever(config)
    # Approvals RACE the CLI prompt against Slack buttons: the webhook
    # server (runbook webhook) writes response files into the shared
    # approvals store, so an operator can answer from either surface
    # (reference approval.ts:347-547 requestApprovalWithOptions).
    # Non-interactive runs (--yes / gateway) drop the CLI racer but keep
    # the Slack leg when configured; with neither surface the SafetyManager
    # falls back to deny-all (fail-safe).
    from runbookai_tpu.server.webhook import ApprovalFileStore

    notify = _slack_approval_notify(config)
    approval = None
    if interactive or notify is not None:
        approval = make_raced_approval(
            ApprovalFileStore(f"{config.runbook_dir}/approvals"),
            input_fn=input if interactive else None,
            notify=notify,
            timeout_s=config.safety.approval_timeout_seconds,
        )
    safety = SafetyManager(
        require_approval=tuple(config.safety.require_approval),
        auto_approve_low_risk=config.safety.auto_approve_low_risk,
        max_mutations_per_session=config.safety.max_mutations_per_session,
        cooldown_seconds=config.safety.cooldown_seconds,
        audit_dir=f"{config.runbook_dir}/audit",
        approval_callback=approval,
    )
    tools = get_runtime_tools(config, knowledge=knowledge, safety=safety, llm=llm)
    return Runtime(config=config, llm=llm, tools=tools, knowledge=knowledge,
                   safety=safety)


def _slack_approval_notify(config: Config):
    """Approve/Reject Block Kit message for the raced approval (reference
    approval.ts posts buttons whose action values carry the approval id;
    the webhook server writes the clicked decision back to the store).
    Returns None when Slack isn't configured — the CLI races alone."""
    inc = config.incident
    if not (inc.slack.enabled and inc.slack.bot_token
            and inc.slack.default_channel):
        return None
    from runbookai_tpu.tools.incident import SlackClient

    slack = SlackClient(inc.slack.bot_token)
    channel = inc.slack.default_channel

    async def notify(approval_id: str, req) -> None:
        blocks = [
            {"type": "section", "text": {"type": "mrkdwn", "text": (
                f"*APPROVAL REQUIRED* [{req.risk.value.upper()}] "
                f"`{req.operation}`\n{req.description}")}},
            {"type": "actions", "elements": [
                {"type": "button", "action_id": "approve",
                 "style": "primary", "value": approval_id,
                 "text": {"type": "plain_text", "text": "Approve"}},
                {"type": "button", "action_id": "reject", "style": "danger",
                 "value": approval_id,
                 "text": {"type": "plain_text", "text": "Reject"}},
            ]},
        ]
        await slack.post_message(channel, req.description, blocks=blocks)

    return notify


def _db_exists(config: Config) -> bool:
    from pathlib import Path

    return Path(config.knowledge.db_path).is_file()


def _context_managers(runtime: Runtime) -> list:
    """Knowledge/Service/Infra context managers for the free-form loop
    (reference agent.ts:293-340 wires all three into the system prompt)."""
    managers: list = []
    if runtime.knowledge is not None:
        from runbookai_tpu.agent.knowledge_context import KnowledgeContextManager

        managers.append(KnowledgeContextManager(runtime.knowledge))
    graph_path = f"{runtime.config.runbook_dir}/service-graph.json"
    if _file_exists(graph_path):
        from runbookai_tpu.agent.service_context import ServiceContextManager
        from runbookai_tpu.knowledge.store.graph import ServiceGraph

        managers.append(ServiceContextManager(ServiceGraph.load(graph_path)))
    if runtime.config.agent.infra_context:
        from runbookai_tpu.agent.infra_context import InfraContextManager
        from runbookai_tpu.agent.orchestrator import ToolExecutor

        executor = ToolExecutor({t.name: t for t in runtime.tools})
        managers.append(InfraContextManager(executor))
    return managers


def _file_exists(path: str) -> bool:
    from pathlib import Path

    return Path(path).is_file()


def build_agent(runtime: Runtime) -> Agent:
    acfg = runtime.config.agent
    return Agent(
        runtime.llm,
        runtime.tools,
        knowledge=runtime.knowledge,
        max_iterations=acfg.max_iterations,
        context_threshold_tokens=acfg.context_threshold_tokens,
        explain_mode=acfg.explain_mode,
        parallel_tools=acfg.parallel_tool_calls,
        scratchpad_root=f"{runtime.config.runbook_dir}/scratchpad",
        cache_ttl_seconds=acfg.tool_cache_ttl_seconds,
        cache_size=acfg.tool_cache_size,
        # Real tokenizer when the engine is in-tree: compaction thresholds
        # then count actual tokens, not the chars/4 estimate (VERDICT r2
        # weak #6). Hosted/mock clients leave it None.
        tokenizer=getattr(runtime.llm, "tokenizer", None),
        context_managers=_context_managers(runtime),
    )


def build_orchestrator(runtime: Runtime, incident_id: str = "",
                       execute_remediation: bool = False,
                       approval_callback=None) -> InvestigationOrchestrator:
    acfg = runtime.config.agent
    machine = InvestigationStateMachine(
        incident_id=incident_id,
        max_hypotheses=acfg.max_hypotheses,
        max_depth=acfg.max_hypothesis_depth,
        max_iterations=acfg.max_investigation_iterations,
    )
    executor = ToolExecutor({t.name: t for t in runtime.tools})
    return InvestigationOrchestrator(
        runtime.llm, executor, machine=machine, knowledge=runtime.knowledge,
        approval_callback=approval_callback,
        execute_remediation=execute_remediation,
    )
