"""Live investigation view: the hypothesis tree repaints DURING the run.

Reference parity: the Ink CLI streams AgentEvents into a live hypothesis
tree while the investigation runs (``src/cli.tsx:116``,
``src/cli/components/hypothesis-tree.tsx:332``); r3 printed events as
lines and the tree only at the end (VERDICT missing #3).

Sticky-footer pattern over plain ANSI (no TUI framework in the image):
every event erases the painted tree block (cursor-up + clear-to-end),
prints the event line through the normal renderer, then repaints the
tree from the orchestrator machine's CURRENT hypothesis state below the
stream. Non-TTY outputs (pipes, CI logs) fall back to pure line events —
exactly what the r3 behavior was.
"""

from __future__ import annotations

import sys
from typing import Any, Callable


class LiveTreeSink:
    """Orchestrator ``event_sink`` that keeps a live tree footer."""

    def __init__(self, machine: Any,
                 fallback: Callable[[Any], None],
                 out=None, enabled: bool | None = None):
        self.machine = machine
        self.fallback = fallback
        self.out = out or sys.stdout
        self.enabled = (self.out.isatty() if enabled is None else enabled)
        self._tree_lines = 0

    # ----------------------------------------------------------- painting

    def _erase_tree(self) -> None:
        if self._tree_lines:
            # Cursor to the start of the block, clear to end of screen.
            self.out.write(f"\x1b[{self._tree_lines}F\x1b[0J")
            self._tree_lines = 0

    def _paint_tree(self) -> None:
        hyps = list(getattr(self.machine, "hypotheses", {}).values())
        if not hyps:
            return
        import shutil

        from runbookai_tpu.cli.hypothesis_view import render_tree

        # Plain (no ANSI color) + truncated to the terminal width: the
        # erase sequence counts PHYSICAL rows, so a wrapped line would
        # make cursor-up undershoot and leave stale fragments behind.
        # The final full-color tree prints after the run (cmd_investigate).
        cols, rows = shutil.get_terminal_size((100, 24))
        width = max(20, cols - 1)
        text = render_tree(hyps, color=False)
        lines = [ln[:width] for ln in text.splitlines()]
        # Height clamp: a footer taller than the screen would scroll its
        # top off and the cursor-up erase could no longer reach it —
        # keep the most recent tail on screen.
        max_rows = max(4, rows - 2)
        if len(lines) > max_rows:
            lines = lines[-max_rows:]
        self.out.write("\n".join(lines) + "\n")
        self._tree_lines = len(lines)

    # -------------------------------------------------------------- sink

    def __call__(self, ev: Any) -> None:
        if not self.enabled:
            self.fallback(ev)
            return
        if getattr(ev, "kind", "") == "token":
            # Streaming deltas: erase the tree once and let tokens paint
            # inline; repainting per token would flicker. The tree comes
            # back on the next structural event.
            self._erase_tree()
            self.fallback(ev)
            self.out.flush()
            return
        self._erase_tree()
        self.fallback(ev)
        self._paint_tree()
        self.out.flush()

    def finish(self) -> None:
        """Leave the last tree in place and stop managing the footer."""
        self._tree_lines = 0
