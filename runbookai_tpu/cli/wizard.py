"""Interactive onboarding wizard (``runbook init --interactive``).

Parity target: reference ``src/cli/setup-wizard.tsx`` +
``src/config/onboarding.ts`` — the answers model (:20-52), config generation
(`generateConfig` :57), dual-file save (services.yaml + config.yaml,
`saveConfig` :107-227), re-edit **hydration** of an existing config
(`loadServiceConfig` :229), and the quick-setup templates
(``config/services.ts`` ``EXAMPLE_CONFIGS`` :193).

The Ink select/multiselect UI becomes a prompt-driven flow with an
injectable ``ask`` callable so tests can script it; the provider enum gains
the ``jax-tpu`` backend (the north-star default).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from runbookai_tpu.utils.config import (
    Config,
    ServiceEntry,
    ServicesConfig,
    load_config,
    load_services,
    save_config,
)

Ask = Callable[[str, str], str]  # (question, default) -> answer


@dataclass
class OnboardingAnswers:
    llm_provider: str = "jax-tpu"  # jax-tpu | mock (hosted providers are replaced by the TPU backend)
    llm_model: str = "llama3-8b-instruct"
    account_setup: str = "single"  # single | multi | skip
    account_names: list[str] = field(default_factory=lambda: ["production"])
    regions: list[str] = field(default_factory=lambda: ["us-east-1"])
    compute_services: list[str] = field(default_factory=list)
    database_services: list[str] = field(default_factory=list)
    use_cloudwatch: bool = True
    use_kubernetes: bool = False
    incident_provider: str = "none"  # pagerduty | opsgenie | none
    use_slack_gateway: bool = False
    slack_mode: str = "socket"
    knowledge_path: str = "./docs/runbooks"
    simulated: bool = False


QUICK_TEMPLATES: dict[str, OnboardingAnswers] = {
    # EXAMPLE_CONFIGS parity: minimal web app / serverless / multi-account.
    "web-app": OnboardingAnswers(
        compute_services=["ecs", "ec2"], database_services=["rds"],
        incident_provider="pagerduty"),
    "serverless": OnboardingAnswers(
        compute_services=["lambda", "apprunner"],
        database_services=["dynamodb"]),
    "kubernetes": OnboardingAnswers(
        compute_services=["eks"], use_kubernetes=True,
        incident_provider="pagerduty"),
    "multi-account": OnboardingAnswers(
        account_setup="multi", account_names=["production", "staging"],
        compute_services=["ecs"], database_services=["rds", "elasticache"]),
    "simulated": OnboardingAnswers(llm_provider="mock", simulated=True,
                                   compute_services=["ecs"],
                                   incident_provider="pagerduty"),
}


def generate_configs(answers: OnboardingAnswers) -> tuple[Config, ServicesConfig]:
    """Answers → (config.yaml model, services.yaml model) (onboarding.ts:57)."""
    accounts = [
        {"name": name, "regions": answers.regions, "isDefault": i == 0}
        for i, name in enumerate(answers.account_names)
    ] if answers.account_setup != "skip" else []

    services = [
        ServiceEntry(name=f"{svc}-workloads", type=svc,
                     tags=["compute"], aws={"service": svc})
        for svc in answers.compute_services if svc != "none"
    ] + [
        ServiceEntry(name=f"{db}-primary", type=db, tags=["database"],
                     aws={"service": db})
        for db in answers.database_services if db != "none"
    ]
    services_config = ServicesConfig(accounts=accounts, services=services)

    kubernetes_enabled = answers.use_kubernetes or (
        "eks" in answers.compute_services)
    config = Config.model_validate({
        "llm": {"provider": answers.llm_provider, "model": answers.llm_model},
        "providers": {
            "aws": {"enabled": bool(accounts) or answers.simulated,
                    "simulated": answers.simulated,
                    "regions": answers.regions},
            "kubernetes": {"enabled": kubernetes_enabled or answers.simulated,
                           "simulated": answers.simulated},
        },
        "observability": {
            "datadog": {"enabled": False},
            "prometheus": {"enabled": False},
        },
        "incident": {
            "pagerduty": {"enabled": answers.incident_provider == "pagerduty",
                          "simulated": answers.simulated},
            "opsgenie": {"enabled": answers.incident_provider == "opsgenie"},
            "slack": {"enabled": answers.use_slack_gateway,
                      "mode": answers.slack_mode},
        },
        "knowledge": {"sources": [
            {"type": "filesystem", "name": "runbooks",
             "path": answers.knowledge_path},
        ]},
    })
    return config, services_config


def hydrate_answers(config_dir: str | Path = ".runbook") -> OnboardingAnswers:
    """Pre-fill the wizard from an existing config (re-edit flow, :229)."""
    answers = OnboardingAnswers()
    config_dir = Path(config_dir)
    try:
        config = load_config(config_dir / "config.yaml")
    except FileNotFoundError:
        return answers
    answers.llm_provider = config.llm.provider
    answers.llm_model = config.llm.model
    answers.use_kubernetes = config.providers.kubernetes.enabled
    answers.simulated = config.providers.aws.simulated
    if config.incident.pagerduty.enabled:
        answers.incident_provider = "pagerduty"
    elif config.incident.opsgenie.enabled:
        answers.incident_provider = "opsgenie"
    answers.use_slack_gateway = config.incident.slack.enabled
    answers.slack_mode = config.incident.slack.mode
    for src in config.knowledge.sources:
        if src.type == "filesystem" and src.path:
            answers.knowledge_path = src.path
            break
    try:
        services = load_services(config_dir / "services.yaml")
        if services.accounts:
            answers.account_names = [str(a.get("name", "account"))
                                     for a in services.accounts]
            answers.account_setup = ("multi" if len(services.accounts) > 1
                                     else "single")
            answers.regions = list(services.accounts[0].get(
                "regions", answers.regions))
        answers.compute_services = sorted({
            s.type for s in services.services if "compute" in s.tags})
        answers.database_services = sorted({
            s.type for s in services.services if "database" in s.tags})
    except FileNotFoundError:
        pass
    return answers


def _default_ask(question: str, default: str) -> str:
    suffix = f" [{default}]" if default else ""
    reply = input(f"{question}{suffix}: ").strip()
    return reply or default


def run_wizard(ask: Ask = _default_ask,
               base: Optional[OnboardingAnswers] = None) -> OnboardingAnswers:
    """Prompt-driven flow mirroring the Ink wizard's question order."""
    answers = base or OnboardingAnswers()
    template = ask("Quick template (web-app/serverless/kubernetes/"
                   "multi-account/simulated/custom)", "custom")
    if template in QUICK_TEMPLATES:
        return QUICK_TEMPLATES[template]

    answers.llm_provider = ask("LLM provider (jax-tpu/mock)", answers.llm_provider)
    answers.llm_model = ask("Model", answers.llm_model)
    answers.account_setup = ask("AWS accounts (single/multi/skip)",
                                answers.account_setup)
    if answers.account_setup == "multi":
        names = ask("Account names (comma-separated)",
                    ",".join(answers.account_names))
        answers.account_names = [n.strip() for n in names.split(",") if n.strip()]
    elif answers.account_setup == "skip":
        answers.account_names = []
    regions = ask("Regions (comma-separated)", ",".join(answers.regions))
    answers.regions = [r.strip() for r in regions.split(",") if r.strip()]
    compute = ask("Compute services (ecs,ec2,lambda,eks,apprunner,amplify or none)",
                  ",".join(answers.compute_services) or "none")
    answers.compute_services = [c.strip() for c in compute.split(",")
                                if c.strip() and c.strip() != "none"]
    databases = ask("Databases (rds,dynamodb,elasticache,documentdb or none)",
                    ",".join(answers.database_services) or "none")
    answers.database_services = [d.strip() for d in databases.split(",")
                                 if d.strip() and d.strip() != "none"]
    answers.use_kubernetes = ask("Use Kubernetes? (y/n)",
                                 "y" if answers.use_kubernetes else "n") == "y"
    answers.incident_provider = ask("Incident provider (pagerduty/opsgenie/none)",
                                    answers.incident_provider)
    answers.use_slack_gateway = ask("Enable Slack gateway? (y/n)",
                                    "y" if answers.use_slack_gateway else "n") == "y"
    if answers.use_slack_gateway:
        answers.slack_mode = ask("Slack mode (socket/http)", answers.slack_mode)
    answers.knowledge_path = ask("Runbooks directory", answers.knowledge_path)
    return answers


def save_wizard_configs(answers: OnboardingAnswers,
                        config_dir: str | Path = ".runbook") -> tuple[Path, Path]:
    """Write both YAMLs (onboarding.ts saveConfig :107-227)."""
    import yaml

    config_dir = Path(config_dir)
    config_dir.mkdir(parents=True, exist_ok=True)
    config, services = generate_configs(answers)
    config_path = config_dir / "config.yaml"
    save_config(config, config_path)
    services_path = config_dir / "services.yaml"
    services_path.write_text(yaml.safe_dump(
        services.model_dump(mode="json"), sort_keys=False))
    return config_path, services_path
