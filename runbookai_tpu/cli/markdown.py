"""ANSI terminal markdown renderer.

Parity target: reference ``src/cli/components/markdown.tsx`` — block parser
(:51: fenced code, headers, blockquotes, tables, lists, hr, paragraphs) and
per-block renderers (:195-241) that the Ink UI uses to print agent answers.
Here the render target is a plain string with ANSI escapes (no React), which
both the CLI and the chat loop print directly.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

RESET = "\x1b[0m"
BOLD = "\x1b[1m"
DIM = "\x1b[2m"
ITALIC = "\x1b[3m"
UNDERLINE = "\x1b[4m"
CYAN = "\x1b[36m"
YELLOW = "\x1b[33m"
GREEN = "\x1b[32m"
MAGENTA = "\x1b[35m"

_HEADER_RE = re.compile(r"^(#{1,6})\s+(.+)$")
_LIST_RE = re.compile(r"^(\s*)([-*]|\d+\.)\s+(.*)$")
_HR_RE = re.compile(r"^\s*(-{3,}|\*{3,}|_{3,})\s*$")


@dataclass
class Block:
    kind: str  # header | code | table | blockquote | hr | list | paragraph
    content: str = ""
    level: int = 0
    language: str = ""
    items: list[tuple[int, str, str]] | None = None  # (indent, marker, text)
    rows: list[list[str]] | None = None


def parse_blocks(content: str) -> list[Block]:
    blocks: list[Block] = []
    lines = content.split("\n")
    i = 0
    while i < len(lines):
        line = lines[i]

        if line.startswith("```"):
            language = line[3:].strip()
            code: list[str] = []
            i += 1
            while i < len(lines) and not lines[i].startswith("```"):
                code.append(lines[i])
                i += 1
            i += 1  # closing fence
            blocks.append(Block("code", "\n".join(code), language=language))
            continue

        header = _HEADER_RE.match(line)
        if header:
            blocks.append(Block("header", header.group(2),
                                level=len(header.group(1))))
            i += 1
            continue

        if line.lstrip().startswith(">"):
            quote: list[str] = []
            while i < len(lines) and lines[i].lstrip().startswith(">"):
                quote.append(lines[i].lstrip()[1:].lstrip())
                i += 1
            blocks.append(Block("blockquote", "\n".join(quote)))
            continue

        if line.lstrip().startswith("|"):
            table: list[str] = []
            while i < len(lines) and lines[i].lstrip().startswith("|"):
                table.append(lines[i].strip())
                i += 1
            rows = []
            for raw in table:
                cells = [c.strip() for c in raw.strip().strip("|").split("|")]
                if all(re.fullmatch(r":?-{2,}:?", c) for c in cells if c):
                    continue  # separator row
                rows.append(cells)
            blocks.append(Block("table", rows=rows))
            continue

        if _HR_RE.match(line) and not _LIST_RE.match(line):
            blocks.append(Block("hr"))
            i += 1
            continue

        if _LIST_RE.match(line):
            items: list[tuple[int, str, str]] = []
            while i < len(lines):
                m = _LIST_RE.match(lines[i])
                if not m:
                    break
                items.append((len(m.group(1)), m.group(2), m.group(3)))
                i += 1
            blocks.append(Block("list", items=items))
            continue

        if not line.strip():
            i += 1
            continue

        paragraph: list[str] = []
        while i < len(lines) and lines[i].strip() and not (
            lines[i].startswith("```") or _HEADER_RE.match(lines[i])
            or lines[i].lstrip().startswith((">", "|")) or _LIST_RE.match(lines[i])
        ):
            paragraph.append(lines[i].strip())
            i += 1
        blocks.append(Block("paragraph", " ".join(paragraph)))
    return blocks


def render_inline(text: str, color: bool = True) -> str:
    """Bold / italic / inline-code / links → ANSI."""
    if not color:
        text = re.sub(r"\*\*([^*]+)\*\*", r"\1", text)
        text = re.sub(r"(?<!\*)\*([^*]+)\*(?!\*)", r"\1", text)
        text = re.sub(r"`([^`]+)`", r"\1", text)
        text = re.sub(r"\[([^\]]+)\]\(([^)]+)\)", r"\1 <\2>", text)
        return text
    text = re.sub(r"\*\*([^*]+)\*\*", BOLD + r"\1" + RESET, text)
    text = re.sub(r"(?<!\*)\*([^*]+)\*(?!\*)", ITALIC + r"\1" + RESET, text)
    text = re.sub(r"`([^`]+)`", CYAN + r"\1" + RESET, text)
    text = re.sub(r"\[([^\]]+)\]\(([^)]+)\)",
                  UNDERLINE + r"\1" + RESET + DIM + r" (\2)" + RESET, text)
    return text


def _wrap(text: str, width: int) -> list[str]:
    words = text.split()
    lines: list[str] = []
    cur = ""
    for word in words:
        visible = re.sub(r"\x1b\[[0-9;]*m", "", cur)
        if visible and len(visible) + 1 + len(re.sub(r"\x1b\[[0-9;]*m", "", word)) > width:
            lines.append(cur)
            cur = word
        else:
            cur = f"{cur} {word}" if cur else word
    if cur:
        lines.append(cur)
    return lines or [""]


def render_markdown(content: str, width: int = 88, color: bool = True) -> str:
    out: list[str] = []
    for block in parse_blocks(content):
        if block.kind == "header":
            text = render_inline(block.content, color)
            if color:
                prefix = {1: BOLD + MAGENTA, 2: BOLD + CYAN}.get(
                    block.level, BOLD)
                out.append(f"{prefix}{'#' * block.level} {text}{RESET}")
            else:
                out.append(f"{'#' * block.level} {block.content}")
            out.append("")
        elif block.kind == "code":
            body = block.content.split("\n")
            lang = f" {block.language}" if block.language else ""
            if color:
                out.append(DIM + "┌──" + lang + RESET)
                out += [DIM + "│ " + RESET + GREEN + ln + RESET for ln in body]
                out.append(DIM + "└──" + RESET)
            else:
                out.append(f"┌──{lang}")
                out += ["│ " + ln for ln in body]
                out.append("└──")
            out.append("")
        elif block.kind == "blockquote":
            for ln in block.content.split("\n"):
                rendered = render_inline(ln, color)
                out.append((DIM if color else "") + "▌ " + rendered
                           + (RESET if color else ""))
            out.append("")
        elif block.kind == "table" and block.rows:
            widths = [0] * max(len(r) for r in block.rows)
            plain = [[render_inline(c, False) for c in r] for r in block.rows]
            for row in plain:
                for j, cell in enumerate(row):
                    widths[j] = max(widths[j], len(cell))
            for idx, row in enumerate(plain):
                padded = [cell.ljust(widths[j]) for j, cell in enumerate(row)]
                line = "│ " + " │ ".join(padded) + " │"
                if idx == 0 and color:
                    line = BOLD + line + RESET
                out.append(line)
                if idx == 0:
                    out.append("├" + "┼".join("─" * (w + 2) for w in widths) + "┤")
            out.append("")
        elif block.kind == "hr":
            out.append(("─" * width))
            out.append("")
        elif block.kind == "list" and block.items:
            number = 0
            for indent, marker, text in block.items:
                pad = " " * indent
                if marker in ("-", "*"):
                    bullet = "•"
                else:
                    number += 1
                    bullet = f"{number}."
                for k, ln in enumerate(_wrap(render_inline(text, color),
                                             width - indent - 2)):
                    out.append(f"{pad}{bullet if k == 0 else ' ' * len(bullet)} {ln}")
            out.append("")
        elif block.kind == "paragraph":
            out += _wrap(render_inline(block.content, color), width)
            out.append("")
    while out and out[-1] == "":
        out.pop()
    return "\n".join(out)
