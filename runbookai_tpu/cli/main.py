"""``runbook`` CLI — argparse command surface.

Parity target: reference ``src/cli.tsx`` (commander + Ink): ask :1104, chat
:1119, investigate :1133, status :1193, init :1208, demo :1240, knowledge
:1250-1471, config :1587, webhook :1999, slack-gateway :2057, mcp :2182,
checkpoint :2353, plus the eval runners. Rendering is plain-text streaming of
the shared AgentEvent vocabulary (runbookai_tpu.demo.runner.render_event)
instead of a React terminal UI.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

from runbookai_tpu.utils.config import (
    Config,
    load_config,
    save_config,
    set_config_value,
    validate_config,
)


_token_line_open = False


def _print_event(ev) -> None:
    from runbookai_tpu.demo.runner import render_event

    global _token_line_open
    if ev.kind == "token":
        # Live token deltas paint inline (raw model output — tool-call
        # markup included); the parsed answer still renders afterwards.
        print(ev.data.get("delta", ""), end="", flush=True)
        _token_line_open = True
        return
    if _token_line_open:
        print(flush=True)  # close the streamed line before a normal event
        _token_line_open = False
    print(render_event(ev), flush=True)


def _load(args) -> Config:
    return load_config(path=getattr(args, "config", None))


# --------------------------------------------------------------------------- #
# commands                                                                    #
# --------------------------------------------------------------------------- #


def cmd_ask(args) -> int:
    from runbookai_tpu.cli.runtime import build_agent, build_runtime

    config = _load(args)
    runtime = build_runtime(config, interactive=not args.yes)
    agent = build_agent(runtime)

    async def run() -> None:
        async for ev in agent.run(args.query, session_id=args.session):
            _print_event(ev)

    asyncio.run(run())
    return 0


def cmd_deploy(args) -> int:
    """Deploy via the deploy-service skill through the agent loop (reference
    cli.tsx:1556) — pre-deployment checks first; mutations route through the
    safety/approval gate like any other remediation."""
    from runbookai_tpu.cli.runtime import build_agent, build_runtime

    config = _load(args)
    runtime = build_runtime(config, interactive=not args.yes)
    agent = build_agent(runtime)
    version = f" version {args.version}" if args.version else ""
    if args.dry_run:
        query = (f"Show me what would happen if I deploy {args.service} to "
                 f"{args.environment}{version}. Do not execute, just explain "
                 "the steps.")
    else:
        query = (f"Deploy {args.service} to {args.environment}{version} using "
                 "the deploy-service skill. Perform all pre-deployment checks "
                 "first.")
    print(f"Deploying {args.service} to {args.environment}..."
          + (" (dry run)" if args.dry_run else ""))

    async def run() -> None:
        async for ev in agent.run(query):
            _print_event(ev)

    asyncio.run(run())
    return 0


def cmd_chat(args) -> int:
    from runbookai_tpu.agent.memory import ConversationMemory
    from runbookai_tpu.cli.runtime import build_agent, build_runtime

    config = _load(args)
    runtime = build_runtime(config)
    if getattr(args, "raw", False):
        return _chat_raw(runtime)
    agent = build_agent(runtime)
    memory = ConversationMemory(summarize_after_messages=16)
    print("runbook chat — empty line or 'exit' to quit")

    async def turn(text: str) -> None:
        memory.add("user", text)
        answer = ""
        query = text
        context = memory.context_block()
        if context:
            query = f"{context}\n\n# Current question\n{text}"
        async for ev in agent.run(query):
            if ev.kind == "answer":
                answer = ev.data["text"]
            _print_event(ev)
        memory.add("assistant", answer)
        if memory.needs_summarization:
            await memory.summarize(runtime.llm)

    while True:
        try:
            line = input("\nyou> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not line or line in ("exit", "quit"):
            break
        asyncio.run(turn(line))
    return 0


def _chat_raw(runtime) -> int:
    """Direct model chat (no agent loop): tokens print as they stream —
    the human-facing path for eyeballing model behavior and latency."""
    history: list[tuple[str, str]] = []
    llm = runtime.llm
    print("runbook chat --raw — streaming model chat; empty line to quit")

    async def turn(text: str) -> None:
        pieces = []
        # Prior turns ride in the prompt (the agentless path has no
        # ConversationMemory; without this every turn would be stateless).
        if history:
            transcript = "\n".join(f"{role}: {msg}" for role, msg in history)
            prompt = (f"# Conversation so far\n{transcript}\n\n"
                      f"# Current message\n{text}")
        else:
            prompt = text
        # Event-dict stream protocol (LLMClient.chat_stream): true token
        # streaming on the engine client, chunked fallback on mocks.
        async for ev in llm.chat_stream("You are a concise SRE assistant.",
                                        prompt):
            if ev.get("type") == "text":
                pieces.append(ev["delta"])
                print(ev["delta"], end="", flush=True)
        print()
        history.append(("user", text))
        history.append(("assistant", "".join(pieces)))

    while True:
        try:
            line = input("\nyou> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if not line or line in ("exit", "quit"):
            break
        asyncio.run(turn(line))
    if hasattr(llm, "shutdown"):
        asyncio.run(llm.shutdown())
    return 0


def cmd_investigate(args) -> int:
    from runbookai_tpu.cli.runtime import build_orchestrator, build_runtime
    from runbookai_tpu.session.checkpoint import CheckpointStore

    config = _load(args)
    runtime = build_runtime(config, interactive=not args.yes)
    orch = build_orchestrator(runtime, incident_id=args.incident_id,
                              execute_remediation=args.execute)
    # Live hypothesis tree repaints under the event stream on TTYs
    # (reference cli.tsx:116 Ink tree); pipes get plain line events.
    from runbookai_tpu.cli.live_view import LiveTreeSink

    live = LiveTreeSink(orch.machine, fallback=_print_event)
    orch.event_sink = live
    result = asyncio.run(orch.investigate(args.incident_id, args.description or ""))
    live.finish()
    store = CheckpointStore(f"{config.runbook_dir}/checkpoints")
    store.save_machine(orch.machine, label="final")
    hypotheses = list(orch.machine.hypotheses.values())
    if hypotheses:
        import sys

        from runbookai_tpu.cli.hypothesis_view import render_summary, render_tree

        color = sys.stdout.isatty()
        print("\n" + render_tree(hypotheses, color=color))
        print(render_summary(hypotheses, color=color))
    print(f"\nroot cause: {result.root_cause}")
    print(f"confidence: {result.confidence}")
    print(f"services:   {', '.join(result.affected_services)}")
    if args.learn:
        from runbookai_tpu.learning.loop import run_learning_loop

        artifacts = asyncio.run(run_learning_loop(
            runtime.llm, result, out_dir=f"{config.runbook_dir}/learning",
            base_dir=config.runbook_dir,
            apply_updates=getattr(args, "apply_learnings", False)))
        print(f"learning artifacts: {artifacts}")
    return 0


def cmd_demo(args) -> int:
    from runbookai_tpu.demo.runner import run_demo

    run_demo(emit=_print_event, fast=args.fast)
    return 0


def cmd_status(args) -> int:
    config = _load(args)
    problems = validate_config(config)
    print(f"llm provider: {config.llm.provider} ({config.llm.model})")
    enabled = []
    if config.providers.aws.enabled:
        enabled.append("aws" + (" (simulated)" if config.providers.aws.simulated else ""))
    if config.providers.kubernetes.enabled:
        enabled.append("kubernetes" + (" (simulated)" if config.providers.kubernetes.simulated else ""))
    for name, c in (("datadog", config.observability.datadog),
                    ("prometheus", config.observability.prometheus),
                    ("pagerduty", config.incident.pagerduty),
                    ("opsgenie", config.incident.opsgenie),
                    ("slack", config.incident.slack)):
        if c.enabled:
            enabled.append(name)
    print(f"providers: {', '.join(enabled) or '(none enabled)'}")
    db = Path(config.knowledge.db_path)
    if db.is_file():
        from runbookai_tpu.knowledge.store.sqlite_fts import KnowledgeStore

        stats = KnowledgeStore(db).stats()
        print(f"knowledge: {stats['documents']} docs / {stats['chunks']} chunks")
    else:
        print("knowledge: (no database — run `runbook knowledge sync`)")
    if problems:
        print("config problems:")
        for p in problems:
            print(f"  - {p}")
        return 1
    print("config: ok")
    return 0


def cmd_init(args) -> int:
    target = Path(args.dir or ".") / ".runbook" / "config.yaml"
    if target.exists() and not args.force and not args.interactive:
        print(f"{target} already exists (use --force to overwrite)")
        return 1
    if args.interactive:
        from runbookai_tpu.cli.wizard import (
            hydrate_answers,
            run_wizard,
            save_wizard_configs,
        )

        base = hydrate_answers(target.parent) if target.exists() else None
        answers = run_wizard(base=base)
        config_path, services_path = save_wizard_configs(
            answers, config_dir=target.parent)
        print(f"wrote {config_path} and {services_path}")
        return 0
    config = Config()
    if args.template == "simulated":
        config = Config.model_validate({
            "llm": {"provider": "mock"},
            "providers": {"aws": {"enabled": True, "simulated": True},
                          "kubernetes": {"enabled": True, "simulated": True}},
            "observability": {"datadog": {"enabled": True, "simulated": True},
                              "prometheus": {"enabled": True, "simulated": True}},
            "incident": {"pagerduty": {"enabled": True, "simulated": True}},
        })
    elif args.template == "tpu":
        config = Config.model_validate({
            "llm": {"provider": "jax-tpu", "model": "llama3-8b-instruct",
                    "dtype": "bfloat16"},
            "providers": {"aws": {"enabled": True, "simulated": True},
                          "kubernetes": {"enabled": True, "simulated": True}},
            "incident": {"pagerduty": {"enabled": True, "simulated": True}},
        })
    save_config(config, target)
    print(f"wrote {target} (template: {args.template})")
    return 0


def cmd_config(args) -> int:
    config = _load(args)
    if args.set:
        for assignment in args.set:
            if "=" not in assignment:
                print(f"expected key=value, got {assignment!r}")
                return 1
            key, value = assignment.split("=", 1)
            config = set_config_value(config, key.strip(), value.strip())
        path = args.config or Path(".runbook") / "config.yaml"
        save_config(config, path)
        print(f"updated {path}")
    if args.show or not args.set:
        print(json.dumps(config.model_dump(mode="json"), indent=2))
    return 0


def cmd_knowledge(args) -> int:
    config = _load(args)
    if args.knowledge_cmd == "auth":
        # `runbook knowledge auth google` (reference cli.tsx:1450, google-auth.ts)
        import os

        from runbookai_tpu.knowledge.sources.google_auth import (
            TokenStore,
            authorization_url,
            exchange_code,
        )

        client_id = os.environ.get("GOOGLE_CLIENT_ID", "")
        client_secret = os.environ.get("GOOGLE_CLIENT_SECRET", "")
        if not client_id or not client_secret:
            print("set GOOGLE_CLIENT_ID and GOOGLE_CLIENT_SECRET first")
            return 1
        print("Open this URL, authorize, and paste the code:")
        print(f"  {authorization_url(client_id)}")
        code = input("code> ").strip()
        tokens = exchange_code(client_id, client_secret, code)
        TokenStore().save(tokens)
        print("tokens saved to .runbook/google-tokens.json")
        return 0

    from runbookai_tpu.knowledge.retriever import create_retriever

    retriever = create_retriever(config)
    if args.knowledge_cmd == "sync":
        if not config.knowledge.sources:
            # Silent zero-document syncs are a config-location trap
            # (config lives at .runbook/config.yaml, not ./runbook.yaml).
            print("warning: no knowledge sources configured — add "
                  "knowledge.sources entries to .runbook/config.yaml "
                  "(see docs/CONFIG.md)", file=sys.stderr)
        counts = retriever.sync(force=args.force)
        for name, n in counts.items():
            print(f"{name}: {n} documents synced")
        print(json.dumps(retriever.stats(), indent=2, default=str))
        return 0
    if args.knowledge_cmd == "search":
        hits = retriever.hybrid.search(args.query, limit=args.limit,
                                       knowledge_type=args.type,
                                       service=args.service)
        for h in hits:
            print(f"[{h.score:.4f}] ({h.doc.knowledge_type}) {h.doc.title} "
                  f"§{h.chunk.section or '-'}")
            print(f"    {h.chunk.content[:180]}")
        if not hits:
            print("(no results)")
        return 0
    if args.knowledge_cmd == "stats":
        print(json.dumps(retriever.stats(), indent=2, default=str))
        return 0
    if args.knowledge_cmd == "add":
        from runbookai_tpu.knowledge.chunker import document_from_markdown

        path = Path(args.file)
        doc = document_from_markdown(str(path), path.read_text(),
                                     default_title=path.stem)
        retriever.store.upsert_document(doc)
        if retriever.hybrid.embedder and retriever.hybrid.vectors is not None:
            embs = retriever.hybrid.embedder.embed_texts(
                [c.content for c in doc.chunks])
            retriever.hybrid.vectors.store_many([
                (c.chunk_id, doc.doc_id, embs[i]) for i, c in enumerate(doc.chunks)])
        print(f"added {doc.doc_id}: {doc.title} ({len(doc.chunks)} chunks)")
        return 0
    if args.knowledge_cmd == "validate":
        problems = validate_config(config)
        for p in problems:
            print(f"- {p}")
        print("ok" if not problems else f"{len(problems)} problem(s)")
        return 0 if not problems else 1
    print("unknown knowledge command")
    return 1


def cmd_checkpoint(args) -> int:
    from runbookai_tpu.session.checkpoint import CheckpointStore

    config = _load(args)
    store = CheckpointStore(f"{config.runbook_dir}/checkpoints")
    if args.checkpoint_cmd == "list":
        metas = store.list(args.investigation)
        for m in metas:
            print(f"{m.checkpoint_id}  {m.investigation_id:14} {m.phase:12} {m.label}")
        if not metas:
            print("(no checkpoints)")
        return 0
    if args.checkpoint_cmd == "show":
        data = store.show(args.checkpoint_id)
        if data is None:
            print("not found")
            return 1
        print(json.dumps(data, indent=2, default=str))
        return 0
    if args.checkpoint_cmd == "delete":
        ok = store.delete(args.checkpoint_id)
        print("deleted" if ok else "not found")
        return 0 if ok else 1
    return 1


def _live_eval_report(args, cases, name: str,
                      case_labels: Optional[dict] = None) -> int:
    """Shared run-live-and-report tail for eval and simulate eval.

    ``case_labels`` (case_id -> {label: value}) adds grouped pass rates —
    simulate eval reports per-fault-family and per-adversarial-split
    accuracy with it (VERDICT r4 #4)."""
    from runbookai_tpu.cli.runtime import build_runtime
    from runbookai_tpu.evalsuite.runner import run_live, write_reports

    runtime = build_runtime(_load(args), interactive=False)
    report = asyncio.run(run_live(
        cases, lambda: runtime.llm, name=name,
        concurrency=args.concurrency))
    out = report.to_dict()
    if case_labels:
        out["breakdown"] = _pass_rate_breakdown(report.cases, case_labels)
    summary_path = write_reports([report], args.out)
    out_path = Path(args.out) / f"{name}.json"
    if case_labels and out_path.exists():
        # The per-case file write_reports produced, plus the breakdown.
        out_path.write_text(json.dumps(out, indent=2, default=str))
    print(json.dumps(out | {"summary_path": str(summary_path)},
                     indent=2, default=str))
    return 0 if report.pass_rate >= getattr(args, "min_pass_rate", 0.0) else 1


def _pass_rate_breakdown(case_results: list, case_labels: dict) -> dict:
    """{label_kind: {label_value: {passed, total, pass_rate}}}."""
    out: dict = {}
    for c in case_results:
        labels = case_labels.get(c.get("case_id"), {})
        for kind, value in labels.items():
            bucket = out.setdefault(kind, {}).setdefault(
                str(value), {"passed": 0, "total": 0})
            bucket["total"] += 1
            bucket["passed"] += bool(c.get("passed"))
    for kind in out.values():
        for bucket in kind.values():
            bucket["pass_rate"] = round(
                bucket["passed"] / max(1, bucket["total"]), 4)
    return out


def cmd_eval(args) -> int:
    from runbookai_tpu.evalsuite.runner import (
        load_fixtures_file,
        run_live,
        run_offline,
        write_reports,
    )

    if args.run_all:
        from runbookai_tpu.evalsuite.run_all import parse_shard, run_all_benchmarks

        try:
            shard = (parse_shard(args.shard)
                     if getattr(args, "shard", None) else None)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        runner = None
        if not args.offline:
            from runbookai_tpu.cli.runtime import build_runtime

            runtime = build_runtime(_load(args), interactive=False)
            runner = lambda cases: asyncio.run(run_live(  # noqa: E731
                cases, lambda: runtime.llm, concurrency=args.concurrency))
        aggregate = run_all_benchmarks(
            datasets_root=args.datasets_root, out_dir=args.out,
            runner=runner, min_pass_rate=args.min_pass_rate,
            setup=args.setup_datasets, shard=shard)
        print(json.dumps(aggregate, indent=2, default=str))
        return 0 if aggregate["failed"] == 0 else 1

    cases = load_fixtures_file(args.fixtures)
    if args.offline:
        report = run_offline(cases, name=args.name)
        summary_path = write_reports([report], args.out)
        print(json.dumps(report.to_dict()
                         | {"summary_path": str(summary_path)},
                         indent=2, default=str))
        return 0 if report.pass_rate >= args.min_pass_rate else 1
    return _live_eval_report(args, cases, name=args.name)


def cmd_simulate(args) -> int:
    """Incident simulator: generated fault scenarios against the fixture
    providers (reference scripts/simulate/setup-incidents.sh — here
    credential-free: seeded novel topologies + faults with ground truth)."""
    from runbookai_tpu.simulate import (
        FAULT_TYPES,
        Scenario,
        generate_scenarios,
        to_eval_case,
    )
    from runbookai_tpu.simulate.generator import write_scenarios

    if args.sim_cmd == "faults":
        for name in sorted(FAULT_TYPES):
            print(name)
        return 0

    if getattr(args, "fault", None) and args.fault not in FAULT_TYPES:
        print(f"unknown fault type {args.fault!r}; valid: "
              f"{', '.join(sorted(FAULT_TYPES))}", file=sys.stderr)
        return 1

    models = [m for m in (getattr(args, "models", None) or "").split(",")
              if m] or None

    if args.sim_cmd == "generate":
        scenarios = generate_scenarios(
            args.n, seed=args.seed, fault_type=args.fault,
            adversarial=getattr(args, "adversarial", None), models=models)
        paths = write_scenarios(scenarios, args.out)
        for s, p in zip(scenarios, paths):
            line = f"{s.scenario_id}  {s.truth['fault_type']:22s}  {p}"
            if s.model:
                line += f"  model={s.model}"
            if args.reveal:
                line += f"\n    truth: {s.truth['root_cause']}"
            print(line)
        return 0

    if args.sim_cmd == "investigate":
        from runbookai_tpu.cli.runtime import build_agent, build_runtime

        s = Scenario.from_json(Path(args.scenario).read_text())
        config = _load(args)
        # The scenario only exists in its fixtures: force every provider
        # into simulated mode (a real-cloud config here would query live
        # infrastructure while the CLI claims the generated fault is the
        # answer) and route the fixtures through the standard injection
        # seam (providers.aws.fixtures_path -> SimulatedCloud).
        for block in (config.providers.aws, config.providers.kubernetes,
                      config.observability.datadog,
                      config.observability.prometheus,
                      config.incident.pagerduty,
                      config.providers.github):
            block.enabled = True
            block.simulated = True
        # No simulated gitlab twin: a real client here would query live
        # infra for a synthetic incident.
        config.providers.gitlab.enabled = False
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump(s.fixtures, f)
            config.providers.aws.fixtures_path = f.name
        try:
            # SimulatedCloud reads the file eagerly inside build_runtime.
            runtime = build_runtime(config, interactive=not args.yes)
        finally:
            Path(f.name).unlink(missing_ok=True)
        agent = build_agent(runtime)

        async def run() -> None:
            async for ev in agent.run(s.query, incident_id=s.scenario_id):
                _print_event(ev)

        asyncio.run(run())
        print(f"\n── ground truth ({s.scenario_id}) ──")
        print(f"  fault:      {s.truth['fault_type']}")
        print(f"  root cause: {s.truth['root_cause']}")
        return 0

    if args.sim_cmd == "eval":
        scenarios = generate_scenarios(
            args.n, seed=args.seed, fault_type=args.fault,
            adversarial=getattr(args, "adversarial", None), models=models)
        cases = [to_eval_case(s) for s in scenarios]
        # Per-family + adversarial-split accuracy (VERDICT r4 #4): the
        # breakdown is what separates reasoning from keyword overlap.
        # Multi-model runs add a per-served-model split next to them.
        labels = {s.scenario_id: {
            "fault_family": s.truth["fault_type"],
            "adversarial": s.truth.get("adversarial", "none"),
            **({"model": s.model} if s.model else {}),
        } for s in scenarios}
        # Deterministic triage baseline: what timeline+topology analysis
        # alone scores (agent/signal_triage.py) — the floor any LLM-led
        # investigation should beat on root-cause service identification.
        from runbookai_tpu.agent.signal_triage import triage_signals

        hits = 0
        for s in scenarios:
            fx = s.fixtures
            rep = triage_signals(
                alarms=fx["cloudwatch_alarms"], logs=fx["cloudwatch_logs"],
                dd_events=fx["datadog"]["events"],
                pods=fx["kubernetes"]["pods"],
                prom_alerts=fx["prometheus"]["alerts"],
                incident=fx["pagerduty"][0] if fx["pagerduty"] else {},
                known_services=[e["service"] for e in fx["aws"]["ecs"]])
            top = rep.candidates[0]["service"] if rep.candidates else None
            hits += top == s.truth["root_cause_service"]
        print(json.dumps({
            "triage_baseline_top1_service_accuracy":
                round(hits / max(1, len(scenarios)), 4),
            "cases": len(scenarios)}), file=sys.stderr)
        return _live_eval_report(args, cases, name="simulated-incidents",
                                 case_labels=labels)

    if args.sim_cmd == "provision":
        # Real-infrastructure mode (reference setup-incidents.sh). The
        # plan — teardown first — is printed BEFORE any execution, so an
        # interrupted apply always has its undo recipe on screen; apply
        # refuses without credentials or with unresolved operator inputs.
        from runbookai_tpu.simulate.provision import apply_plan, provision_plan

        s = Scenario.from_json(Path(args.scenario).read_text())
        plan = provision_plan(s)
        print(plan.render())
        if not args.apply:
            print("dry-run (pass --apply with AWS credentials to execute)")
            return 0
        status = apply_plan(plan)
        print(status)
        return 0 if status.startswith("applied") else 1

    print("unknown simulate subcommand", file=sys.stderr)
    return 1


def cmd_serve(args) -> int:
    """OpenAI-compatible HTTP endpoint over the serving engine."""
    from runbookai_tpu.model.jax_tpu import JaxTpuClient
    from runbookai_tpu.server.openai_api import OpenAIServer

    config = _load(args)
    if config.llm.provider != "jax-tpu":
        print("serve requires llm.provider: jax-tpu (a real engine to serve)",
              file=sys.stderr)
        return 1
    problems = [p for p in validate_config(config) if "llm." in p]
    if problems:
        for p in problems:
            print(f"config error: {p}", file=sys.stderr)
        return 1
    client = JaxTpuClient.from_config(config.llm)
    # Multi-model fleets serve under the DEFAULT group's name; the
    # request's model field selects any group (GET /v1/models lists all).
    served_name = (config.llm.models[0].name if config.llm.models
                   else config.llm.model)
    if client.multi_model is not None:
        groups = ", ".join(
            f"{g.name} (dp={g.fleet.dp})"
            for g in client.multi_model.groups.values())
        print(f"multi-model fleet: {groups}", file=sys.stderr)
    # Surface the serving memory plan (engine/memory_plan.py) so operators
    # see what their context/batch choice costs before traffic arrives.
    from runbookai_tpu.models.llama import CONFIGS as _MODEL_CONFIGS

    if not config.llm.models and config.llm.model in _MODEL_CONFIGS:
        from runbookai_tpu.engine.memory_plan import plan_serving

        plan = plan_serving(
            _MODEL_CONFIGS[config.llm.model],
            max_seq_len=min(config.llm.max_seq_len,
                            _MODEL_CONFIGS[config.llm.model].max_seq_len),
            batch=config.llm.max_batch_slots,
            tp=max(1, config.llm.mesh.model),
            weights="int8" if config.llm.dtype == "int8" else "bf16",
            # fp8/int8 pools store 1 byte per value; int8 adds one f32
            # absmax scale per (token, kv head) on top.
            kv_dtype_bytes=(1 if config.llm.kv_cache_dtype
                            in ("fp8", "int8") else 2),
            kv_scale_bytes=(4 if config.llm.kv_cache_dtype == "int8"
                            else 0),
        )
        print(f"memory plan: {plan.explain()}", file=sys.stderr)
    embedder = None
    emb_cfg = config.knowledge.embedder
    # Real weights only: with model_path unset, bge random-inits — serving
    # noise labeled as bge embeddings would silently corrupt any vector
    # index built against the endpoint. (Test configs use bge-test.)
    if emb_cfg.enabled and (emb_cfg.model_path
                            or "test" in emb_cfg.model):
        from runbookai_tpu.knowledge.embedder import Embedder

        embedder = Embedder.from_config(emb_cfg)
    elif emb_cfg.enabled:
        print("note: /v1/embeddings disabled — set knowledge.embedder."
              "model_path to serve real bge embeddings", file=sys.stderr)
    server = OpenAIServer(client, model_name=served_name,
                          host=args.host, port=args.port,
                          allow_runtime_adapters=args.allow_adapter_loading,
                          embedder=embedder)
    print(f"serving {served_name} at http://{args.host}:{server.port}/v1 "
          f"(POST /v1/chat/completions"
          + (", /v1/embeddings" if embedder else "")
          + ", GET /v1/models, /healthz, /metrics, /debug/steps)")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()
    return 0


def cmd_metrics(args) -> int:
    """Observability snapshot: scrape a running server's ``/metrics``
    (Prometheus text), or summarize a tracer JSONL into per-span latency
    percentiles. The correlation workflow (docs/observability.md): take a
    response's ``x-request-id``, grep the trace JSONL for it, then compare
    that request against the population summarized here."""
    if args.trace:
        from runbookai_tpu.utils.trace import (
            dispatch_counters,
            read_spans,
            summarize_spans,
        )

        try:
            spans = read_spans(args.trace)
        except (OSError, json.JSONDecodeError) as e:
            print(f"could not read trace {args.trace}: {e}", file=sys.stderr)
            return 1
        summary = summarize_spans(spans)
        if args.span:
            summary = {k: v for k, v in summary.items() if args.span in k}
        else:
            # Dispatch-kind counters (PR 4 attribution) recovered from the
            # trace alone — a tune run's measured refinement (or any bench
            # arm) is sanity-checkable without its Prometheus scrape: zero
            # engine.mixed spans under a mixed-dispatch plan is a lie.
            summary["dispatch_counters"] = dispatch_counters(spans)
            # Queue-wait and router-placement live in EVENT meta (ms=0),
            # so the per-span duration table above drops them; surface
            # them as a lifecycle block alongside the dispatch counters.
            from runbookai_tpu.utils.timeline import lifecycle_summary

            summary["request_lifecycle"] = lifecycle_summary(spans)
        print(json.dumps(summary, indent=2))
        return 0

    import urllib.error
    import urllib.request

    url = args.url.rstrip("/")
    if not url.endswith("/metrics"):
        url += "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as r:
            text = r.read().decode()
    except (urllib.error.URLError, OSError, TimeoutError) as e:
        print(f"could not scrape {url}: {e}", file=sys.stderr)
        return 1
    if args.grep:
        text = "\n".join(line for line in text.splitlines()
                         if args.grep in line)
    print(text)
    return 0


def _fetch_json(url: str, timeout: float) -> dict:
    """GET ``url`` and parse the JSON body — the one scrape used by the
    live-server subcommands (tenants, workload), so their transport and
    error surfaces cannot drift apart. Raises ``OSError``/``ValueError``
    on unreachable/unparseable; callers pick their fallback."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = json.loads(r.read())
    if not isinstance(body, dict):
        raise ValueError(f"{url} returned non-object JSON")
    return body


def _render_tenants(snap: dict) -> str:
    """Table view of a /tenants snapshot (or of configured policies)."""
    if not snap.get("enabled"):
        return "tenant admission control is disabled (llm.tenants)"
    cols = ("tenant", "class", "rpm", "tok/min", "admitted", "throttled",
            "budget left")
    rows = []
    for name, row in sorted(snap.get("tenants", {}).items()):
        throttled = (row.get("throttled_rate", 0)
                     + row.get("throttled_tokens", 0))
        rows.append((
            name, str(row.get("priority", "-")),
            str(row.get("rate_limit_rpm") or "-"),
            str(row.get("token_budget_per_min") or "-"),
            str(row.get("admitted", 0)), str(throttled),
            str(row.get("budget_remaining_tokens", "-"))))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    out = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        out.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(out)


def cmd_tenants(args) -> int:
    """``runbook tenants`` — live tenant-accounting state. Prefers a
    running server's ``GET /tenants`` (live bucket levels + counters);
    with no server reachable, falls back to rendering the CONFIGURED
    ``llm.tenants`` policies so the command is useful pre-deploy too."""
    url = args.url.rstrip("/") + "/tenants"
    snap = None
    try:
        snap = _fetch_json(url, args.timeout)
        source = url
    except (OSError, TimeoutError, ValueError):
        config = _load(args)
        tcfg = config.llm.tenants
        source = "config (no server at %s)" % args.url
        snap = {"enabled": tcfg.enabled, "tenants": {}}
        if tcfg.enabled:
            blocks = dict(tcfg.keys)
            blocks["default"] = tcfg.default
            for name, block in blocks.items():
                snap["tenants"][name] = {
                    "priority": block.priority,
                    "rate_limit_rpm": block.rate_limit_rpm,
                    "token_budget_per_min": block.token_budget_per_min,
                    "admitted": 0, "throttled_rate": 0,
                    "throttled_tokens": 0,
                }
    if args.json:
        print(json.dumps(snap, indent=2))
    else:
        print(f"# {source}")
        print(_render_tenants(snap))
    return 0


def _chaos_blocks(health: dict) -> dict:
    """Extract {scope: {supervisor, chaos}} from a /healthz body —
    top-level for a single fleet, per served model group otherwise."""
    blocks: dict[str, dict] = {}
    if "supervisor" in health or "chaos" in health:
        blocks["(fleet)"] = {"supervisor": health.get("supervisor"),
                             "chaos": health.get("chaos")}
    for name, g in (health.get("models") or {}).items():
        if isinstance(g, dict) and ("supervisor" in g or "chaos" in g):
            blocks[name] = {"supervisor": g.get("supervisor"),
                            "chaos": g.get("chaos")}
    return blocks


def _render_chaos(blocks: dict) -> str:
    out: list[str] = []
    for scope, b in blocks.items():
        sup = b.get("supervisor")
        out.append(f"## {scope}")
        if sup:
            out.append(f"supervision: wedge_timeout={sup['wedge_timeout_s']}s "
                       f"rebuilds={sup['rebuilds_total']} "
                       f"failovers={sup['failovers_total']}")
            for r in sup["replicas"]:
                line = (f"  r{r['replica']}: {r['state']}"
                        f" (rebuilds={r['rebuilds']})")
                if r.get("reason"):
                    line += f" — {r['reason']}"
                out.append(line)
            tail = sup["transitions"][-8:]
            if tail:
                out.append("  recent transitions:")
                out.extend(f"    r{t['replica']}: {t['from']} -> "
                           f"{t['to']} ({t['reason']})" for t in tail)
        else:
            out.append("supervision: not attached "
                       "(llm.fleet.supervisor.enabled)")
        chaos = b.get("chaos")
        if chaos:
            out.append(f"chaos: seed={chaos['seed']} applied="
                       f"{chaos['events_applied']}/"
                       f"{chaos['events_planned']} "
                       f"active={chaos['active'] or '-'}")
            for w in chaos["windows"][-8:]:
                tgt = (f" r{w['replica']}"
                       if w.get("replica") is not None else "")
                out.append(f"  {w['kind']}{tgt} at {w['applied_at_s']}s "
                           f"for {w['duration_s']}s [{w['status']}]")
        else:
            out.append("chaos: no injector attached")
    return "\n".join(out)


def cmd_chaos(args) -> int:
    """``runbook chaos status`` — replica supervision + fault-injection
    state from a running server's ``/healthz`` (the ``supervisor`` and
    ``chaos`` blocks each fleet's health snapshot carries when a
    FleetSupervisor / ChaosInjector is attached)."""
    url = args.url.rstrip("/") + "/healthz"
    try:
        health = _fetch_json(url, args.timeout)
    except (OSError, TimeoutError, ValueError) as e:
        print(f"no server reachable at {args.url} ({e})")
        return 1
    blocks = _chaos_blocks(health)
    if args.json:
        print(json.dumps(blocks, indent=2))
        return 0
    print(f"# {url}")
    if not blocks:
        print("no supervisor or chaos injector attached "
              "(single engine, or llm.fleet.supervisor disabled)")
        return 0
    print(_render_chaos(blocks))
    return 0


def _incident_feed(args) -> tuple[list[dict], str | None, str]:
    """Incident docs + bundle-dir for ``runbook incident``: a running
    server's ``GET /debug/incidents`` when reachable, else the incident
    headers read straight off the on-disk bundle directory (``--dir`` /
    ``llm.obs.incident_dir``) — a dead server's black box is exactly
    when this command matters most."""
    from runbookai_tpu.obs.incident import list_bundles, load_bundle

    url = args.url.rstrip("/") + "/debug/incidents"
    try:
        snap = _fetch_json(url, args.timeout)
    except (OSError, TimeoutError, ValueError):
        snap = None
    if snap is not None and snap.get("enabled"):
        incidents = list(snap.get("open", [])) + list(snap.get("recent", []))
        return incidents, snap.get("bundle_dir"), url
    directory = args.dir
    if directory is None:
        config = _load(args)
        directory = config.llm.obs.incident_dir
    if not directory:
        source = ("incident detection is disabled on this server"
                  if snap is not None else f"no server at {args.url}")
        return [], None, source + " and no bundle dir configured (--dir)"
    incidents = []
    for path in list_bundles(directory):
        try:
            incidents.append(load_bundle(path).get("incident") or {})
        except (OSError, json.JSONDecodeError):
            continue
    return incidents, str(directory), f"bundles in {directory}"


def _render_incidents(incidents: list[dict]) -> str:
    if not incidents:
        return "no incidents"
    cols = ("id", "signal", "severity", "status", "opened", "duration",
            "peak", "bundle")
    rows = []
    for inc in sorted(incidents, key=lambda i: i.get("id", "")):
        dur = inc.get("duration_s")
        rows.append((
            str(inc.get("id", "?")), str(inc.get("signal", "?")),
            str(inc.get("severity", "?")), str(inc.get("status", "?")),
            str(inc.get("opened_ts", "?")),
            "-" if dur is None else f"{dur:.1f}s",
            str(inc.get("peak", "-")), inc.get("bundle") or "-"))
    widths = [max(len(c), *(len(r[i]) for r in rows))
              for i, c in enumerate(cols)]
    out = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    out += ["  ".join(v.ljust(w) for v, w in zip(r, widths)) for r in rows]
    return "\n".join(out)


def cmd_incident(args) -> int:
    """``runbook incident list|show [--bundle]`` — the fleet's incident
    feed (obs/incident.py): detected incidents with their lifecycle
    state, and the captured black-box bundles. ``show <id> --bundle``
    loads the incident's bundle, VERIFIES its content hash, and prints
    the evidence inventory — a bundle that fails verification is not
    evidence."""
    incidents, bundle_dir, source = _incident_feed(args)
    if args.incident_cmd == "list":
        if args.json:
            print(json.dumps(incidents, indent=2))
        else:
            print(f"# {source}")
            print(_render_incidents(incidents))
        return 0
    # show <id>
    inc = next((i for i in incidents if i.get("id") == args.id), None)
    if inc is None:
        print(f"no incident {args.id!r} ({source}); known: "
              f"{sorted(i.get('id', '?') for i in incidents)}",
              file=sys.stderr)
        return 1
    if not args.bundle:
        print(json.dumps(inc, indent=2, sort_keys=True))
        return 0
    from runbookai_tpu.obs.incident import (
        bundle_hash,
        list_bundles,
        load_bundle,
    )

    if not bundle_dir:
        print("no bundle directory (server has no llm.obs.incident_dir; "
              "pass --dir)", file=sys.stderr)
        return 1
    # Bundle names are <captured-ms>-<id>-<signal>.json; ids restart
    # per process, so prefer the NEWEST match for this id.
    matches = [p for p in list_bundles(bundle_dir)
               if f"-{args.id}-" in p.name]
    if inc.get("bundle"):
        matches = [p for p in matches if p.name == inc["bundle"]] or matches
    if not matches:
        print(f"no bundle for {args.id!r} in {bundle_dir}",
              file=sys.stderr)
        return 1
    path = matches[-1]
    # One load serves the hash check AND the rendering below.
    doc = load_bundle(path)
    expected = str(doc.get("content_hash", ""))
    actual = bundle_hash(doc)
    ok = expected == actual
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return 0 if ok else 1
    evidence = doc.get("evidence", {})
    print(f"# {path}")
    print(f"schema_version: {doc.get('schema_version')}")
    print(f"content_hash: {expected} "
          f"[{'verified' if ok else 'MISMATCH — got ' + actual}]")
    print(f"captured_ts: {doc.get('captured_ts')}")
    print("incident:")
    print(json.dumps(doc.get("incident"), indent=2, sort_keys=True))
    print("evidence:")
    for key in sorted(evidence):
        val = evidence[key]
        size = (len(val) if isinstance(val, (list, str))
                else len(json.dumps(val)))
        unit = ("records" if isinstance(val, list)
                else "bytes" if isinstance(val, str) else "json bytes")
        print(f"  {key}: {size} {unit}")
    history = doc.get("history")
    if history is not None:
        # Pre-open lookback from the embedded tsdb (obs/tsdb.py): what
        # each detector input signal was doing BEFORE this opened.
        print(f"history (lookback {history.get('lookback_s')}s, "
              f"schema v{history.get('schema_version')}):")
        signals = history.get("signals") or {}
        if not signals:
            print("  (no signal samples in the lookback window)")
        for signal in sorted(signals):
            points = signals[signal]
            values = [p[1] for p in points]
            print(f"  {signal:16s} {_spark(values)}  "
                  f"{values[0]:.4g} -> {values[-1]:.4g}  "
                  f"({len(values)} samples)")
    return 0 if ok else 1


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _spark(values: list, width: int = 40) -> str:
    """Unicode sparkline of a signal's lookback trend, downsampled to
    ``width`` evenly spaced points. Flat series render mid-block."""
    if not values:
        return ""
    if len(values) > width:
        step = len(values) / width
        values = [values[int(i * step)] for i in range(width)]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[3] * len(values)
    return "".join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * (len(_SPARK_BLOCKS) - 1)))]
        for v in values)


def _render_workload(snap: dict) -> str:
    """Table view of a /debug/workload snapshot."""
    if not snap.get("enabled"):
        return "workload fingerprinting is disabled (llm.obs.enabled)"
    cols = ("model", "reqs", "prompt p50", "out p50", "conc", "guided",
            "spec", "prefix$", "drift", "stale", "reference")
    rows = []
    entries = dict(snap.get("models", {}))
    merged = snap.get("merged")
    if merged is not None and len(entries) > 1:
        entries["(fleet)"] = {"fingerprint": merged,
                              "drift_score": snap.get("drift_score"),
                              "plan_stale": snap.get("plan_stale"),
                              "reference_source": "worst group"}
    for name, m in entries.items():
        fp = m.get("fingerprint")
        if fp is None:
            rows.append((name, "0", "-", "-", "-", "-", "-", "-", "-",
                         "-", m.get("reference_source", "-")))
            continue
        wl = fp["workload"]
        drift = m.get("drift_score")
        stale = m.get("plan_stale")
        rows.append((
            name, str(fp["window"]["samples"]),
            str(wl["prompt_len"]), str(wl["output_len"]),
            str(wl["concurrency"]), f"{wl['guided_share']:.2f}",
            f"{wl['spec_hit_rate']:.2f}",
            f"{fp['prefix_cache_share']:.2f}",
            "-" if drift is None else f"{drift:.3f}",
            "-" if stale is None else ("STALE" if stale else "ok"),
            m.get("reference_source", "-")))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(cols)]
    out = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for r in rows:
        out.append("  ".join(v.ljust(w) for v, w in zip(r, widths)))
    out.append(f"drift threshold: {snap.get('drift_threshold')}")
    return "\n".join(out)


def cmd_workload(args) -> int:
    """``runbook workload`` — live traffic fingerprints + plan drift
    from a running server's ``GET /debug/workload``
    (``runbookai_tpu/obs``). ``--watch`` re-renders every ``--interval``
    seconds; ``--emit-descriptor out.json`` writes the live tuner
    descriptor — JSON that feeds ``runbook tune --workload out.json``
    unchanged (the ROADMAP item 3 hand-off)."""
    import time as _time

    url = args.url.rstrip("/") + "/debug/workload"

    def scrape() -> dict | None:
        try:
            return _fetch_json(url, args.timeout)
        except (OSError, TimeoutError, ValueError) as e:
            print(f"could not scrape {url}: {e}", file=sys.stderr)
            return None

    snap = scrape()
    if snap is None:
        return 1
    if args.emit_descriptor:
        from runbookai_tpu.autotune.cost_model import Workload
        from runbookai_tpu.obs import descriptor_json

        if not snap.get("enabled"):
            print("workload fingerprinting is disabled on this server "
                  "(llm.obs.enabled) — nothing to emit", file=sys.stderr)
            return 1
        models = snap.get("models", {})
        if args.model:
            entry = models.get(args.model)
            if entry is None:
                print(f"model {args.model!r} not served; served: "
                      f"{sorted(models)}", file=sys.stderr)
                return 1
            fp = entry.get("fingerprint")
        else:
            # One served model -> its fingerprint; several -> the merged
            # fleet-wide one (name a group with --model to split them).
            only = (next(iter(models.values()))["fingerprint"]
                    if len(models) == 1 else None)
            fp = only if only is not None else snap.get("merged")
        if fp is None:
            print("fingerprint window is empty (no completed requests "
                  "yet) — nothing to emit", file=sys.stderr)
            return 1
        payload = descriptor_json(fp)
        # Round-trip gate BEFORE writing: the emitted bytes must parse
        # back into the tuner's own schema, or the hand-off is broken.
        Workload.from_dict(json.loads(payload))
        out = Path(args.emit_descriptor)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(payload)
        print(f"wrote {out} (feed it to `runbook tune --workload {out}`)")
        return 0
    while True:
        if args.json:
            print(json.dumps(snap, indent=2))
        else:
            print(f"# {url}")
            print(_render_workload(snap))
        if not args.watch:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        snap = scrape()
        if snap is None:
            return 1


def _render_query_result(doc: dict) -> str:
    """Table view of a /debug/query result: one row per series,
    canonical selector -> value. An empty result prints as absence —
    the store never materializes zeros for missing series."""
    rows = []
    for entry in doc.get("result", []):
        labels = dict(entry.get("metric", {}))
        name = labels.pop("__name__", "")
        body = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        sel = f"{name}{{{body}}}" if body else (name or "{}")
        rows.append((sel, entry.get("value")))
    if not rows:
        return "(empty result — absent series stay absent, never zero)"
    width = max(len(sel) for sel, _ in rows)
    return "\n".join(f"{sel.ljust(width)}  {value}" for sel, value in rows)


def cmd_query(args) -> int:
    """``runbook query EXPR [--range 5m] [--watch]`` — PromQL-lite over
    a running server's embedded metric history (``GET /debug/query``;
    obs/tsdb.py + obs/query.py). The grammar and the mapping to real
    Prometheus are in docs/observability.md "Metric history & query"."""
    import time as _time
    import urllib.parse

    qs = urllib.parse.urlencode({"expr": args.expr, "range": args.range})
    url = f"{args.url.rstrip('/')}/debug/query?{qs}"

    def scrape() -> dict | None:
        import urllib.error

        try:
            return _fetch_json(url, args.timeout)
        except urllib.error.HTTPError as e:
            # A 400 carries the evaluator's parse error — surface it
            # instead of a bare HTTP status.
            try:
                detail = json.loads(e.read()).get("error", {}).get(
                    "message", "")
            except (ValueError, OSError):
                detail = ""
            print(f"query rejected ({e.code}): {detail or e.reason}",
                  file=sys.stderr)
            return None
        except (OSError, TimeoutError, ValueError) as e:
            print(f"could not scrape {url}: {e}", file=sys.stderr)
            return None

    while True:
        doc = scrape()
        if doc is None:
            return 1
        if not doc.get("enabled", True):
            print("metric history is disabled (llm.obs.tsdb.enabled)",
                  file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(doc, indent=2, sort_keys=True))
        else:
            print(f"# {args.expr}  (range {args.range}, "
                  f"now {doc.get('now')})")
            print(_render_query_result(doc))
        if not args.watch:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def cmd_timeline(args) -> int:
    """``runbook timeline <request-id> --trace <file>`` — stitch one
    request's trace JSONL records (enqueue → router placement → admit →
    prefill chunks → decode windows → finish/abort) into a span tree.
    The id may be the caller's ``x-request-id`` or an engine-internal
    ``r{i}-…`` id; a fleet request shows every replica it touched."""
    from runbookai_tpu.utils.timeline import build_timeline, render_timeline
    from runbookai_tpu.utils.trace import read_spans

    try:
        spans = read_spans(args.trace)
    except (OSError, json.JSONDecodeError) as e:
        print(f"could not read trace {args.trace}: {e}", file=sys.stderr)
        return 1
    tl = build_timeline(spans, args.request_id)
    if tl is None:
        print(f"no records for request {args.request_id!r} in {args.trace}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(tl, indent=2))
    else:
        print(render_timeline(tl, max_events=args.max_events))
    return 0


def cmd_profile(args) -> int:
    """``runbook profile`` — on-demand XLA/XProf capture around N engine
    steps of synthetic load on the CONFIGURED engine, written as a
    TensorBoard-readable trace directory (``tensorboard --logdir DIR``,
    or upload to xprof). Probe-gated: an environment without a working
    ``jax.profiler`` capture path reports the skip and exits cleanly."""
    import numpy as _np

    from runbookai_tpu.engine.request import EngineRequest, SamplingParams
    from runbookai_tpu.model.jax_tpu import JaxTpuClient
    from runbookai_tpu.utils.trace import try_device_trace

    config = _load(args)
    if config.llm.provider != "jax-tpu":
        print("profile requires llm.provider: jax-tpu (a real engine to "
              "profile)", file=sys.stderr)
        return 1
    client = JaxTpuClient.from_config(config.llm)
    core = client.core  # replica 0 when fleeted: one engine's device view
    rng = _np.random.default_rng(0)

    def _submit(n: int, max_new: int) -> None:
        for _ in range(n):
            core.submit(EngineRequest(
                prompt_ids=rng.integers(
                    1, min(256, core.cfg.vocab_size - 1),
                    size=args.prompt_len).tolist(),
                sampling=SamplingParams(temperature=0.0,
                                        max_new_tokens=max_new,
                                        stop_token_ids=())))

    # Warmup outside the capture: compile time would drown the N measured
    # steps and the trace would profile Mosaic/XLA, not serving.
    _submit(min(2, max(1, args.concurrency)), 4)
    core.run_until_idle()

    _submit(args.concurrency, args.new_tokens)
    steps = 0
    with try_device_trace(args.out) as captured:
        while core.has_work and steps < args.steps:
            core.step()
            steps += 1
    while core.has_work:  # settle outside the capture
        core.step()
    if captured:
        print(f"captured {steps} engine steps -> {args.out} "
              f"(view: tensorboard --logdir {args.out})")
        return 0
    print(f"profile skipped: jax.profiler capture unavailable on this "
          f"backend (ran {steps} steps uncaptured)", file=sys.stderr)
    return 0


def cmd_tune(args) -> int:
    """``runbook tune`` — serving-plan autotuner sweep (docs/autotune.md):
    analytic cost-model prune over the engine knob space, measured
    refinement of the survivors (baseline always competes, so the emitted
    plan can never regress the hand-picked defaults), versioned plan
    artifact out."""
    import os

    if args.smoke and not os.environ.get("JAX_PLATFORMS"):
        # The smoke path is a CPU contract — don't let a half-up
        # accelerator plugin hang a bounded-time sweep.
        os.environ["JAX_PLATFORMS"] = "cpu"

    from runbookai_tpu.autotune.cost_model import (
        HARDWARE,
        Candidate,
        SearchSpace,
        Workload,
        smoke_space,
    )
    from runbookai_tpu.autotune.search import tune

    # ONE config read serves both defaults (model, out) — or none at all
    # when the flags pin everything.
    config = _load(args) if args.out is None or (
        args.model is None and not args.smoke) else None
    # --workload FILE: a live descriptor emitted by `runbook workload
    # --emit-descriptor` (or any Workload.to_dict JSON) replaces the
    # per-field flags — the obs/ -> autotune hand-off.
    file_workload = None
    if getattr(args, "workload", None):
        try:
            file_workload = Workload.from_dict(
                json.loads(Path(args.workload).read_text()))
        except (OSError, ValueError) as e:
            print(f"could not read workload descriptor "
                  f"{args.workload}: {e}", file=sys.stderr)
            return 1
    if args.smoke:
        model = args.model or "llama3-test"
        space = smoke_space()
        src = file_workload or Workload(
            prompt_len=args.prompt_len, output_len=args.output_len,
            concurrency=args.concurrency,
            guided_share=getattr(args, "guided_share", 0.0),
            spec_hit_rate=getattr(args, "spec_hit_rate", 0.0))
        # The smoke path bounds the sweep to the tiny CPU model's
        # envelope whatever the descriptor says — a live long-context
        # fingerprint must still smoke in seconds.
        workload = Workload(prompt_len=min(src.prompt_len, 48),
                            output_len=min(src.output_len, 16),
                            concurrency=min(src.concurrency, 4),
                            guided_share=src.guided_share,
                            spec_hit_rate=src.spec_hit_rate)
        baseline = Candidate(page_size=4, num_pages=256,
                             max_batch_slots=4, prefill_chunk=32,
                             kv_dtype="auto", max_seq_len=256)
        hw, weights = HARDWARE["cpu"], "bf16"
    else:
        model = args.model or config.llm.model
        workload = file_workload or Workload(
            prompt_len=args.prompt_len, output_len=args.output_len,
            concurrency=args.concurrency, guided_share=args.guided_share,
            spec_hit_rate=args.spec_hit_rate)
        axes = {}
        if args.dp:
            axes["dp_replicas"] = tuple(
                int(v) for v in args.dp.split(","))
        if args.tp:
            axes["tp"] = tuple(int(v) for v in args.tp.split(","))
        space = SearchSpace(**axes)
        baseline = None
        hw_name = args.hw
        if hw_name == "auto":
            import jax

            if jax.default_backend() == "cpu":
                hw_name = "cpu"
            else:
                kind = jax.devices()[0].device_kind.lower()
                hw_name = "v6e" if "v6" in kind else "v5e"
        hw, weights = HARDWARE[hw_name], args.weights
    out = args.out or str(
        Path(config.runbook_dir) / "plans" / f"{model}.{hw.name}.json")
    try:
        result = tune(
            model, workload, hw, space, weights=weights, top_k=args.top_k,
            measure=not args.no_measure, baseline=baseline,
            n_requests=args.requests, new_tokens=args.new_tokens,
            budget_s=args.budget_s, out=out, log=print)
    except ValueError as e:
        # e.g. an all-infeasible sweep — no plan artifact is written.
        print(str(e), file=sys.stderr)
        return 1
    plan = result.plan
    print(json.dumps({
        "plan_id": plan.plan_id, "out": str(out),
        "engine": plan.engine,
        "cost_model": plan.provenance.get("cost_model"),
        "measured": plan.provenance.get("measured"),
    }, indent=2))
    return 0


def cmd_plan(args) -> int:
    """``runbook plan show|validate`` — inspect / gate plan artifacts."""
    from runbookai_tpu.autotune.plan import load_plan, validate_plan

    if args.plan_cmd == "show":
        try:
            plan = load_plan(args.path)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(json.dumps(plan.to_dict(), indent=2))
        return 0
    if args.plan_cmd == "validate":
        failures = 0
        for path in args.paths:
            try:
                data = json.loads(Path(path).read_text())
            except (OSError, json.JSONDecodeError) as e:
                print(f"{path}: unreadable ({e})")
                failures += 1
                continue
            problems = validate_plan(data)
            if problems:
                failures += 1
                print(f"{path}: INVALID")
                for p in problems:
                    print(f"  - {p}")
            else:
                print(f"{path}: ok ({data['plan_id']})")
        return 0 if failures == 0 else 1
    return 1


def cmd_bench(args) -> int:
    import runpy

    runpy.run_path(str(Path(__file__).resolve().parents[2] / "bench.py"),
                   run_name="__main__")
    return 0


def cmd_weights(args) -> int:
    from runbookai_tpu.models.checkpoint import (
        checkpoint_config,
        convert_hf_to_checkpoint,
        is_checkpoint,
    )

    if args.weights_cmd == "convert":
        try:
            out = convert_hf_to_checkpoint(
                args.model_path, args.out, model_name=args.name,
                quantize_int8=args.int8, allow_random_init=args.random_init,
            )
        except FileNotFoundError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        print(f"wrote checkpoint: {out} (int8={args.int8})")
        return 0
    if not is_checkpoint(args.path):
        print(f"not a checkpoint: {args.path}")
        return 1
    cfg = checkpoint_config(args.path)
    print(json.dumps(cfg.__dict__, indent=2))
    return 0


def cmd_lint(args) -> int:
    """``runbook lint`` — the static-analysis gate (docs/lint.md).

    Exit 0 when the tree has no findings beyond the committed baseline,
    non-zero otherwise; ``--update-baseline`` regenerates
    lint-baseline.json. Dependency-free (never imports jax), so it runs
    first and fastest in CI.
    """
    from runbookai_tpu.analysis.cli import run_lint

    return run_lint(args)


def cmd_mcp(args) -> int:
    from runbookai_tpu.server.mcp import MCPServer, run_stdio_server

    config = _load(args)
    server = MCPServer.from_config(config)
    if args.mcp_cmd == "tools":
        for tool in server.list_tools():
            print(f"{tool['name']}: {tool['description']}")
        return 0
    run_stdio_server(server)
    return 0


def cmd_webhook(args) -> int:
    from runbookai_tpu.server.webhook import run_webhook_server

    config = _load(args)
    run_webhook_server(config, port=args.port)
    return 0


def cmd_slack_gateway(args) -> int:
    from runbookai_tpu.server.slack_gateway import run_slack_gateway

    config = _load(args)
    run_slack_gateway(config, mode=args.mode or config.incident.slack.mode,
                      port=args.port)
    return 0


# --------------------------------------------------------------------------- #
# parser                                                                      #
# --------------------------------------------------------------------------- #


def cmd_integrations(args) -> int:
    from runbookai_tpu.integrations.claude_hooks import (
        hooks_status,
        install_hooks,
        uninstall_hooks,
    )

    settings = Path(args.settings).expanduser()
    if args.integrations_cmd == "enable":
        install_hooks(settings)
        print(f"hooks installed into {settings}")
        return 0
    if args.integrations_cmd == "status":
        status = hooks_status(settings)
        for event, on in status.items():
            print(f"{event:18} {'enabled' if on else '-'}")
        return 0
    if args.integrations_cmd == "disable":
        removed = uninstall_hooks(settings)
        print("hooks removed" if removed else "no hooks found")
        return 0
    if args.integrations_cmd == "learn":
        # reference `runbook integrations claude learn` (cli.tsx:1667+)
        from runbookai_tpu.cli.runtime import build_runtime
        from runbookai_tpu.integrations.session_store import create_session_store
        from runbookai_tpu.learning.claude_session import run_learning_from_session

        config = _load(args)
        store = create_session_store(config)
        session_ids = [args.session_id] if args.session_id else store.list_sessions()
        if not session_ids:
            print("no captured sessions")
            return 1
        runtime = build_runtime(config, interactive=False)
        for sid in session_ids:
            out = asyncio.run(run_learning_from_session(
                runtime.llm, sid, store=store,
                out_dir=f"{config.runbook_dir}/learning"))
            print(f"{sid}: artifacts in {out}")
        return 0
    return 1


def cmd_hook(args) -> int:
    """Hidden hook entrypoint (reference cli.tsx:1667-1889 `runbook hook`)."""
    from runbookai_tpu.integrations.claude_hooks import HookHandlers, run_hook_stdin
    from runbookai_tpu.integrations.session_store import create_session_store

    config = _load(args)
    retriever = None
    if Path(config.knowledge.db_path).is_file():
        from runbookai_tpu.knowledge.retriever import create_retriever

        retriever = create_retriever(config)
    handlers = HookHandlers(retriever=retriever,
                            session_store=create_session_store(config))
    return run_hook_stdin(args.event, handlers)


def cmd_operability(args) -> int:
    config = _load(args)
    from runbookai_tpu.integrations.operability_ingestion import IngestionClient
    from runbookai_tpu.integrations.session_store import create_session_store
    from runbookai_tpu.providers.operability import create_adapter

    adapter = create_adapter(config)
    client = IngestionClient(adapter,
                             spool_dir=f"{config.runbook_dir}/operability-spool")
    if args.operability_cmd == "status":
        print(json.dumps(client.status(), indent=2))
        return 0
    if args.operability_cmd == "replay":
        print(json.dumps(asyncio.run(client.replay()), indent=2))
        return 0
    if args.operability_cmd == "ingest":
        store = create_session_store(config)
        events = []
        for session_id in store.list_sessions():
            events.extend(store.read(session_id))
        print(json.dumps(asyncio.run(client.ingest(events)), indent=2))
        return 0
    return 1


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="runbook",
        description="TPU-native AI SRE agent: incident investigation served by "
                    "an in-tree JAX inference engine.",
    )
    p.add_argument("--config", help="explicit config.yaml path")
    sub = p.add_subparsers(dest="cmd", required=True)

    ask = sub.add_parser("ask", help="one-shot question through the agent loop")
    ask.add_argument("query")
    ask.add_argument("--session", default=None)
    ask.add_argument("--yes", action="store_true", help="non-interactive approvals")
    ask.set_defaults(fn=cmd_ask)

    chat = sub.add_parser("chat", help="interactive conversation")
    chat.add_argument("--raw", action="store_true",
                      help="direct streaming model chat (no agent loop)")
    chat.set_defaults(fn=cmd_chat)

    dep = sub.add_parser("deploy", help="deploy a service via the deploy-service skill")
    dep.add_argument("service")
    dep.add_argument("-e", "--environment", default="production")
    dep.add_argument("--version", default=None)
    dep.add_argument("--dry-run", action="store_true")
    dep.add_argument("--yes", action="store_true",
                     help="non-interactive: no CLI prompts; mutations are "
                          "approved via Slack buttons when configured, "
                          "denied otherwise")
    dep.set_defaults(fn=cmd_deploy)

    inv = sub.add_parser("investigate", help="structured incident investigation")
    inv.add_argument("incident_id")
    inv.add_argument("--description", default="")
    inv.add_argument("--execute", action="store_true",
                     help="execute the remediation plan (approval-gated)")
    inv.add_argument("--apply-learnings", action="store_true",
                     help="apply runbook updates to the local library "
                          "instead of writing proposals")
    inv.add_argument("--learn", action="store_true",
                     help="run the learning loop afterwards")
    inv.add_argument("--yes", action="store_true")
    inv.set_defaults(fn=cmd_investigate)

    demo = sub.add_parser("demo", help="scripted demo investigation (no model)")
    demo.add_argument("--fast", action="store_true", help="3x speed")
    demo.set_defaults(fn=cmd_demo)

    status = sub.add_parser("status", help="config + provider status")
    status.set_defaults(fn=cmd_status)

    init = sub.add_parser("init", help="write a starter config")
    init.add_argument("--template", choices=["minimal", "simulated", "tpu"],
                      default="simulated")
    init.add_argument("--dir", default=".")
    init.add_argument("--force", action="store_true")
    init.add_argument("--interactive", "-i", action="store_true",
                      help="guided setup wizard (hydrates an existing config)")
    init.set_defaults(fn=cmd_init)

    cfg = sub.add_parser("config", help="show or set config values")
    cfg.add_argument("--set", action="append", metavar="a.b.c=value")
    cfg.add_argument("--show", action="store_true")
    cfg.set_defaults(fn=cmd_config)

    kn = sub.add_parser("knowledge", help="knowledge base management")
    kn_sub = kn.add_subparsers(dest="knowledge_cmd", required=True)
    kn_sync = kn_sub.add_parser("sync")
    kn_sync.add_argument("--force", action="store_true")
    kn_search = kn_sub.add_parser("search")
    kn_search.add_argument("query")
    kn_search.add_argument("--type", default=None)
    kn_search.add_argument("--service", default=None)
    kn_search.add_argument("--limit", type=int, default=8)
    kn_sub.add_parser("stats")
    kn_add = kn_sub.add_parser("add")
    kn_add.add_argument("file")
    kn_sub.add_parser("validate")
    kn_auth = kn_sub.add_parser("auth")
    kn_auth.add_argument("provider", choices=["google"])
    kn.set_defaults(fn=cmd_knowledge)

    cp = sub.add_parser("checkpoint", help="investigation checkpoints")
    cp_sub = cp.add_subparsers(dest="checkpoint_cmd", required=True)
    cp_list = cp_sub.add_parser("list")
    cp_list.add_argument("--investigation", default=None)
    cp_show = cp_sub.add_parser("show")
    cp_show.add_argument("checkpoint_id")
    cp_del = cp_sub.add_parser("delete")
    cp_del.add_argument("checkpoint_id")
    cp.set_defaults(fn=cmd_checkpoint)

    sim = sub.add_parser("simulate",
                         help="generated fault scenarios (incident simulator)")
    sim_sub = sim.add_subparsers(dest="sim_cmd", required=True)
    sim_gen = sim_sub.add_parser("generate", help="write N scenario files")
    sim_gen.add_argument("--n", type=int, default=5)
    sim_gen.add_argument("--seed", type=int, default=0)
    sim_gen.add_argument("--fault", default=None,
                         help="pin a fault type (see: simulate faults)")
    sim_gen.add_argument("--out", default=".runbook/simulate")
    sim_gen.add_argument("--reveal", action="store_true",
                         help="print ground truth with each scenario")
    sim_gen.add_argument(
        "--adversarial", default=None,
        choices=["misleading_symptom", "two_fault", "signal_dropout", "mix"],
        help="harden scenarios: stale red-herring signals on a non-culprit "
             "service, a concurrent second fault, or a dropped telemetry "
             "modality")
    sim_gen.add_argument(
        "--models", default=None, metavar="A,B",
        help="assign served model groups round-robin (multi-model "
             "fleets, llm.models) so eval load exercises model routing")
    sim_sub.add_parser("faults", help="list fault types")
    sim_inv = sim_sub.add_parser("investigate",
                                 help="run the agent against a scenario")
    sim_inv.add_argument("--scenario", required=True)
    sim_inv.add_argument("--yes", action="store_true")
    sim_eval = sim_sub.add_parser("eval",
                                  help="run + score N generated scenarios")
    sim_eval.add_argument("--n", type=int, default=5)
    sim_eval.add_argument("--seed", type=int, default=0)
    sim_eval.add_argument("--fault", default=None)
    sim_eval.add_argument("--concurrency", type=int, default=4)
    sim_eval.add_argument("--min-pass-rate", type=float, default=0.0)
    sim_eval.add_argument("--out", default=".runbook/eval-reports")
    sim_eval.add_argument(
        "--adversarial", default=None,
        choices=["misleading_symptom", "two_fault", "signal_dropout", "mix"],
        help="run the hardened split (reported separately in breakdown)")
    sim_eval.add_argument(
        "--models", default=None, metavar="A,B",
        help="round-robin cases across served model groups (llm.models); "
             "per-model pass rates land in the breakdown and "
             "summary.json gains model_attribution")
    sim_prov = sim_sub.add_parser(
        "provision",
        help="real-infra mode: map a scenario onto actual AWS breakage "
             "(dry-run plan offline; --apply needs credentials)")
    sim_prov.add_argument("scenario", help="scenario JSON file")
    sim_prov.add_argument("--apply", action="store_true",
                          help="execute the break steps (tagged, reversible)")
    sim.set_defaults(fn=cmd_simulate)

    ev = sub.add_parser("eval", help="run the investigation benchmark")
    ev.add_argument("--fixtures",
                    default="examples/evals/investigation-fixtures.sample.json")
    ev.add_argument("--offline", action="store_true",
                    help="score fixture mock_results without a model")
    ev.add_argument("--name", default="investigation")
    ev.add_argument("--out", default=".runbook/eval-reports")
    ev.add_argument("--concurrency", type=int, default=4)
    ev.add_argument("--min-pass-rate", type=float, default=0.0)
    ev.add_argument("--all", action="store_true", dest="run_all",
                    help="run every public benchmark (rcaeval/rootly/tracerca)")
    ev.add_argument("--datasets-root", default="examples/evals/datasets")
    ev.add_argument("--setup-datasets", action="store_true",
                    help="git-clone missing dataset repos first")
    ev.add_argument("--shard", default=None, metavar="I/N",
                    help="with --all: statically take cases i::n on this "
                         "host ('auto' = this process's multihost rank); "
                         "the engine fleet balances within the shard")
    ev.set_defaults(fn=cmd_eval)

    serve = sub.add_parser(
        "serve", help="OpenAI-compatible HTTP endpoint over the engine")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=8000)
    serve.add_argument("--allow-adapter-loading", action="store_true",
                       help="enable POST /v1/adapters (operator action)")
    serve.set_defaults(fn=cmd_serve)

    bench = sub.add_parser("bench", help="serving benchmark (one JSON line)")
    bench.set_defaults(fn=cmd_bench)

    tune = sub.add_parser(
        "tune", help="serving-plan autotuner: cost-model sweep + measured "
                     "refinement -> plan artifact (docs/autotune.md)")
    tune.add_argument("--model", default=None,
                      help="model config name (default: llm.model; "
                           "--smoke: llama3-test)")
    tune.add_argument("--smoke", action="store_true",
                      help="bounded CPU smoke sweep (tiny model + space)")
    tune.add_argument("--hw", default="auto",
                      choices=["auto", "v5e", "v6e", "v5e-tunnel", "cpu"],
                      help="hardware envelope for the cost model")
    tune.add_argument("--weights", default="int8", choices=["int8", "bf16"])
    tune.add_argument("--prompt-len", type=int, default=512)
    tune.add_argument("--output-len", type=int, default=128)
    tune.add_argument("--concurrency", type=int, default=16)
    tune.add_argument("--guided-share", type=float, default=0.0)
    tune.add_argument("--spec-hit-rate", type=float, default=0.0)
    tune.add_argument("--workload", default=None, metavar="JSON",
                      help="workload descriptor file (Workload.to_dict "
                           "JSON — e.g. from `runbook workload "
                           "--emit-descriptor`); replaces the per-field "
                           "workload flags")
    tune.add_argument("--dp", default=None, metavar="1,2,4",
                      help="dp_replicas axis values (comma-separated)")
    tune.add_argument("--tp", default=None, metavar="1,8,16",
                      help="tp axis values (comma-separated)")
    tune.add_argument("--top-k", type=int, default=3,
                      help="survivors refined with measured runs")
    tune.add_argument("--no-measure", action="store_true",
                      help="analytic only (no engine runs)")
    tune.add_argument("--requests", type=int, default=4,
                      help="measured-run request count")
    tune.add_argument("--new-tokens", type=int, default=16,
                      help="measured-run decode tokens per request")
    tune.add_argument("--budget-s", type=float, default=300.0,
                      help="measured-phase time budget")
    tune.add_argument("--out", default=None,
                      help="plan path (default: "
                           ".runbook/plans/<model>.<hw>.json)")
    tune.set_defaults(fn=cmd_tune)

    plan = sub.add_parser("plan", help="serving-plan artifacts")
    plan_sub = plan.add_subparsers(dest="plan_cmd", required=True)
    plan_show = plan_sub.add_parser("show", help="print a validated plan")
    plan_show.add_argument("path")
    plan_val = plan_sub.add_parser(
        "validate", help="schema + content-hash check (CI gate)")
    plan_val.add_argument("paths", nargs="+")
    plan.set_defaults(fn=cmd_plan)

    wl = sub.add_parser(
        "workload", help="live workload fingerprints + plan drift from "
                         "a running server (GET /debug/workload)")
    wl.add_argument("--url", default="http://127.0.0.1:8000",
                    help="server base URL")
    wl.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table")
    wl.add_argument("--watch", action="store_true",
                    help="re-render every --interval seconds")
    wl.add_argument("--interval", type=float, default=5.0)
    wl.add_argument("--model", default=None,
                    help="with --emit-descriptor: which served model "
                         "group's fingerprint to emit (default: the one "
                         "group, or the merged fleet view)")
    wl.add_argument("--emit-descriptor", default=None, metavar="OUT",
                    help="write the live tuner descriptor as JSON; feeds "
                         "`runbook tune --workload OUT` unchanged")
    wl.add_argument("--timeout", type=float, default=10.0)
    wl.set_defaults(fn=cmd_workload)

    tl = sub.add_parser(
        "timeline", help="render one request's span tree from a trace "
                         "JSONL (enqueue -> route -> admit -> prefill -> "
                         "decode -> finish)")
    tl.add_argument("request_id",
                    help="x-request-id (or engine-internal r{i}-… id)")
    tl.add_argument("--trace", required=True, metavar="JSONL",
                    help="tracer JSONL file (RUNBOOK_TRACE output)")
    tl.add_argument("--json", action="store_true",
                    help="structured timeline instead of the ASCII tree")
    tl.add_argument("--max-events", type=int, default=60,
                    help="tree rows before the middle dispatch windows "
                         "collapse into one summary line")
    tl.set_defaults(fn=cmd_timeline)

    prof = sub.add_parser(
        "profile", help="on-demand XLA/XProf capture around N engine "
                        "steps -> TensorBoard-readable trace dir")
    prof.add_argument("--steps", type=int, default=32,
                      help="engine steps to capture (after warmup)")
    prof.add_argument("--out", default=".runbook/profile",
                      help="trace output directory")
    prof.add_argument("--concurrency", type=int, default=4,
                      help="synthetic requests in flight during capture")
    prof.add_argument("--prompt-len", type=int, default=128)
    prof.add_argument("--new-tokens", type=int, default=32)
    prof.set_defaults(fn=cmd_profile)

    tn = sub.add_parser(
        "tenants", help="tenant accounting state: live /tenants from a "
                        "running server, else the configured llm.tenants "
                        "policies")
    tn.add_argument("--url", default="http://127.0.0.1:8000",
                    help="server base URL (GET <url>/tenants)")
    tn.add_argument("--json", action="store_true",
                    help="raw JSON instead of the table")
    tn.add_argument("--timeout", type=float, default=10.0)
    tn.set_defaults(fn=cmd_tenants)

    ch = sub.add_parser(
        "chaos", help="chaos-hardening state: replica supervision + "
                      "fault-injection windows from a running server")
    ch_sub = ch.add_subparsers(dest="chaos_cmd", required=True)
    ch_status = ch_sub.add_parser(
        "status", help="supervisor replica states, rebuild/failover "
                       "counters, recent transitions and applied fault "
                       "windows (GET <url>/healthz)")
    ch_status.add_argument("--url", default="http://127.0.0.1:8000",
                           help="server base URL (GET <url>/healthz)")
    ch_status.add_argument("--json", action="store_true",
                           help="raw JSON instead of the table")
    ch_status.add_argument("--timeout", type=float, default=10.0)
    ch.set_defaults(fn=cmd_chaos)

    inc = sub.add_parser(
        "incident", help="fleet incident feed + captured black-box "
                         "bundles (obs/incident.py): live from "
                         "GET /debug/incidents, else from the bundle "
                         "directory")
    inc_sub = inc.add_subparsers(dest="incident_cmd", required=True)

    def _incident_args(p) -> None:
        p.add_argument("--url", default="http://127.0.0.1:8000",
                       help="server base URL (GET <url>/debug/incidents)")
        p.add_argument("--dir", default=None,
                       help="bundle directory fallback (default: "
                            "llm.obs.incident_dir)")
        p.add_argument("--json", action="store_true",
                       help="raw JSON instead of the table")
        p.add_argument("--timeout", type=float, default=10.0)

    inc_list = inc_sub.add_parser(
        "list", help="detected incidents: lifecycle state, severity, "
                     "peak, captured bundle")
    _incident_args(inc_list)
    inc_show = inc_sub.add_parser(
        "show", help="one incident in full; --bundle loads + "
                     "hash-verifies its black-box bundle")
    inc_show.add_argument("id", help="incident id (inc-0001)")
    inc_show.add_argument("--bundle", action="store_true",
                          help="load the incident's bundle, verify its "
                               "content hash, print the evidence "
                               "inventory")
    _incident_args(inc_show)
    inc.set_defaults(fn=cmd_incident)

    qy = sub.add_parser(
        "query", help="PromQL-lite over the server's embedded metric "
                      "history (GET /debug/query; obs/query.py grammar)")
    qy.add_argument("expr",
                    help="query expression, e.g. "
                         "'rate(runbook_requests_total[1m])' or "
                         "'histogram_quantile(0.95, "
                         "runbook_ttft_seconds_bucket[5m])'")
    qy.add_argument("--url", default="http://127.0.0.1:8000",
                    help="server base URL (GET <url>/debug/query)")
    qy.add_argument("--range", default="5m",
                    help="default window for selectors without an "
                         "explicit [range] (duration: 30s, 5m, 1h)")
    qy.add_argument("--watch", action="store_true",
                    help="re-evaluate every --interval seconds")
    qy.add_argument("--interval", type=float, default=2.0)
    qy.add_argument("--json", action="store_true",
                    help="raw result JSON instead of the table")
    qy.add_argument("--timeout", type=float, default=10.0)
    qy.set_defaults(fn=cmd_query)

    met = sub.add_parser(
        "metrics", help="scrape a server's /metrics or summarize a trace")
    met.add_argument("--url", default="http://127.0.0.1:8000",
                     help="server base URL (GET <url>/metrics)")
    met.add_argument("--trace", default=None, metavar="JSONL",
                     help="summarize a tracer JSONL (per-span p50/p95/max) "
                          "instead of scraping")
    met.add_argument("--span", default=None,
                     help="with --trace: only span names containing this")
    met.add_argument("--grep", default=None,
                     help="only /metrics lines containing this substring")
    met.add_argument("--timeout", type=float, default=10.0)
    met.set_defaults(fn=cmd_metrics)

    lint = sub.add_parser(
        "lint", help="whole-program AST static analysis for TPU serving "
                     "hazards (RBK001-RBK010; docs/lint.md)")
    from runbookai_tpu.analysis.cli import add_lint_arguments

    add_lint_arguments(lint)
    lint.set_defaults(fn=cmd_lint)

    mcp = sub.add_parser("mcp", help="MCP server over stdio")
    mcp_sub = mcp.add_subparsers(dest="mcp_cmd", required=True)
    mcp_sub.add_parser("serve")
    mcp_sub.add_parser("tools")
    mcp.set_defaults(fn=cmd_mcp)

    wh = sub.add_parser("webhook", help="Slack approval webhook server")
    wh.add_argument("--port", type=int, default=3939)
    wh.set_defaults(fn=cmd_webhook)

    sg = sub.add_parser("slack-gateway", help="Slack gateway (socket|http)")
    sg.add_argument("--mode", choices=["socket", "http"], default=None,
                    help="default: incident.slack.mode from config")
    sg.add_argument("--port", type=int, default=3940)
    sg.set_defaults(fn=cmd_slack_gateway)

    integ = sub.add_parser("integrations", help="editor/agent integrations")
    integ_sub = integ.add_subparsers(dest="integration", required=True)
    claude = integ_sub.add_parser("claude")
    claude_sub = claude.add_subparsers(dest="integrations_cmd", required=True)
    for name in ("enable", "status", "disable"):
        c = claude_sub.add_parser(name)
        c.add_argument("--settings", default="~/.claude/settings.json")
    learn = claude_sub.add_parser("learn")
    learn.add_argument("--session-id", default=None)
    learn.add_argument("--settings", default="~/.claude/settings.json")
    integ.set_defaults(fn=cmd_integrations)

    hook = sub.add_parser("hook")  # hidden hook entrypoint (stdin protocol)
    hook.add_argument("event")
    hook.set_defaults(fn=cmd_hook)

    op = sub.add_parser("operability", help="operability-context ingestion")
    op_sub = op.add_subparsers(dest="operability_cmd", required=True)
    for name in ("ingest", "replay", "status"):
        op_sub.add_parser(name)
    op.set_defaults(fn=cmd_operability)

    w = sub.add_parser("weights", help="model weight checkpoints")
    w_sub = w.add_subparsers(dest="weights_cmd", required=True)
    conv = w_sub.add_parser(
        "convert", help="HF safetensors -> orbax checkpoint (optionally int8)")
    conv.add_argument("model_path", help="HF model dir (safetensors + config)")
    conv.add_argument("out", help="output checkpoint dir")
    conv.add_argument("--int8", action="store_true",
                      help="quantize layer weights to int8 during conversion")
    conv.add_argument("--name", default="hf-model")
    conv.add_argument("--random-init", action="store_true",
                      help="allow a missing model_path (random weights; CI only)")
    info = w_sub.add_parser("info", help="describe a checkpoint")
    info.add_argument("path")
    w.set_defaults(fn=cmd_weights)

    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    try:
        return args.fn(args)
    except KeyboardInterrupt:
        print("\ninterrupted")
        return 130


if __name__ == "__main__":
    sys.exit(main())
