"""Terminal hypothesis-tree views.

Parity target: reference ``src/cli/components/hypothesis-tree.tsx`` — status
icons (:33), box-drawing tree (:67-160) with per-node confidence percentage,
pruned-node toggle, ``HypothesisCompact`` one-liners (:223) and
``HypothesisSummary`` stats footer (:240-300). Renders plain ANSI strings
over the FSM's hypothesis set (``agent/state_machine.py``) so the live
investigate view and the final report share one renderer.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

RESET = "\x1b[0m"
_COLORS = {"green": "\x1b[32m", "yellow": "\x1b[33m", "red": "\x1b[31m",
           "cyan": "\x1b[36m", "dim": "\x1b[2m"}

STATUS_ICONS = {
    "open": ("○", "dim"),
    "investigating": ("◐", "cyan"),
    "confirmed": ("●", "green"),
    "pruned": ("✗", "red"),
}

_BRANCH, _LAST, _VERT = "├─", "└─", "│"


def _paint(text: str, color_name: str, color: bool) -> str:
    if not color:
        return text
    return _COLORS.get(color_name, "") + text + RESET


def _icon(status: str, color: bool) -> str:
    icon, color_name = STATUS_ICONS.get(status, ("?", "dim"))
    return _paint(icon, color_name, color)


def _pct(confidence: float) -> float:
    """The FSM stores the LLM's 0.0-1.0 confidence; display as 0-100%."""
    return confidence * 100.0 if confidence <= 1.0 else confidence


def _node_line(h: Any, color: bool) -> str:
    pct = (f" {_pct(h.confidence):.0f}%" if getattr(h, "confidence", 0) else "")
    evidence = f" [{len(h.evidence)} evidence]" if getattr(h, "evidence", None) else ""
    line = f"{_icon(h.status, color)} {h.statement}{pct}{evidence}"
    if h.status == "pruned":
        line = _paint(line, "dim", color) if color else line + " (pruned)"
    return line


def render_tree(hypotheses: Iterable[Any], show_pruned: bool = True,
                color: bool = True) -> str:
    """Box-drawing tree over FSMHypothesis nodes (parent_id/children links)."""
    nodes = {h.id: h for h in hypotheses}
    roots = [h for h in nodes.values()
             if h.parent_id is None or h.parent_id not in nodes]
    lines: list[str] = []

    def visible_children(h: Any) -> list[Any]:
        kids = [nodes[c] for c in getattr(h, "children", []) if c in nodes]
        if not show_pruned:
            kids = [k for k in kids if k.status != "pruned"]
        return kids

    def walk(h: Any, prefix: str, is_last: bool, is_root: bool) -> None:
        if not show_pruned and h.status == "pruned":
            return
        if is_root:
            lines.append(_node_line(h, color))
            child_prefix = ""
        else:
            connector = _LAST if is_last else _BRANCH
            lines.append(f"{prefix}{connector} {_node_line(h, color)}")
            child_prefix = prefix + ("   " if is_last else f"{_VERT}  ")
        kids = visible_children(h)
        for i, kid in enumerate(kids):
            walk(kid, child_prefix, i == len(kids) - 1, False)

    for i, root in enumerate(roots):
        walk(root, "", i == len(roots) - 1, True)
    return "\n".join(lines)


def render_compact(h: Any, color: bool = True) -> str:
    """One-liner per hypothesis (HypothesisCompact, :223)."""
    return _node_line(h, color)


def count_statuses(hypotheses: Iterable[Any]) -> dict[str, int]:
    counts = {"open": 0, "investigating": 0, "confirmed": 0, "pruned": 0}
    for h in hypotheses:
        counts[h.status] = counts.get(h.status, 0) + 1
    counts["total"] = sum(counts.values())
    return counts


def find_confirmed(hypotheses: Iterable[Any]) -> Optional[Any]:
    best = None
    for h in hypotheses:
        if h.status == "confirmed" and (
                best is None or h.confidence > best.confidence):
            best = h
    return best


def render_summary(hypotheses: Iterable[Any], color: bool = True) -> str:
    """Stats footer + confirmed root cause (HypothesisSummary, :240-300)."""
    items = list(hypotheses)
    counts = count_statuses(items)
    confirmed = find_confirmed(items)
    lines = [
        f"Hypotheses: {counts['total']} total — "
        f"{counts['confirmed']} confirmed, {counts['investigating']} active, "
        f"{counts['open']} open, {counts['pruned']} pruned"
    ]
    if confirmed is not None:
        label = _paint("Root cause:", "green", color)
        pct = (f" ({_pct(confirmed.confidence):.0f}%)"
               if confirmed.confidence else "")
        lines.append(f"{label} {confirmed.statement}{pct}")
    return "\n".join(lines)
