"""Pallas TPU kernel: int8 weight-only quantized matmul for the decode loop.

Decode is HBM-bandwidth-bound on the *weights*: every generated token reads
every layer matrix once, so the floor on step time is weight-bytes / HBM
bandwidth. The XLA path (``llama.qmm``) expresses the int8 matmul as
``(x @ q.astype(bf16)) * s`` and trusts the compiler to fuse the convert
into the dot's operand read; when it instead materializes a bf16 copy the
step moves 3x the bytes (read int8 + write bf16 + read bf16) — the r3
on-chip number (209.9 tok/s, ~27% of roofline) has exactly that signature.

This kernel makes the byte count structural rather than a fusion gamble:
int8 weight tiles stream HBM→VMEM (half the bf16 bytes), are widened
in-register on the way into the MXU, accumulate in f32 scratch, and the
per-output-channel scale is applied once in the epilogue:

    grid = (N/bn, K/bk)           # k innermost: sequential accumulation
    acc[M, bn] += x[M, bk] @ widen(q[bk, bn])
    out[M, bn]  = acc * s[1, bn]  # on the last k step

Math is identical to dequantize-then-matmul because the scale is constant
along the contraction (see models/quant.py). Selected per dispatch by
``EngineConfig.qmm_impl = "pallas"``; the wrapper falls back to the XLA
expression for shapes the kernel does not cover (prefill-sized M, ragged
dims, unquantized leaves), so callers can pass every matmul through it.

No reference counterpart: RunbookAI calls hosted LLM APIs (SURVEY.md §2.2);
this is the TPU-native serving stack underneath the same product surface.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Decode/verify dispatches have M = batch_slots * k_steps rows (<= ~256).
# Larger M means chunked prefill, which is MXU-bound, overlaps the dequant
# with compute, and amortizes any materialized copy over hundreds of
# tokens — the XLA path is the right tool there.
MAX_PALLAS_M = 256

_BK_CANDIDATES = (1024, 512, 256, 128, 64, 32)  # int8 sublane multiple: 32
_BN_CANDIDATES = (512, 256, 128)  # lane multiple: 128


def _pick(cands: tuple[int, ...], dim: int) -> int | None:
    for c in cands:
        if dim % c == 0:
            return c
    return None


def qmm_pallas_eligible(m: int, k: int, n: int) -> bool:
    """Static (trace-time) eligibility for the kernel path."""
    return (m <= MAX_PALLAS_M
            and _pick(_BK_CANDIDATES, k) is not None
            and _pick(_BN_CANDIDATES, n) is not None)


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # int8 tile widens in-register on its way into the MXU; f32 accumulate.
    acc_ref[:] += jax.lax.dot_general(
        x_ref[:], q_ref[:].astype(x_ref.dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == n_k - 1)
    def _epilogue():
        o_ref[:] = (acc_ref[:] * s_ref[:]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def qmm_pallas(x2: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
               *, interpret: bool = False) -> jnp.ndarray:
    """``(x2 @ q) * s`` with int8 ``q`` streamed tile-by-tile from HBM.

    ``x2 [M, K]`` activations, ``q [K, N]`` int8, ``s [1, N]`` f32 per-output
    -channel scales. Returns ``[M, N]`` in ``x2.dtype``. Callers must have
    checked :func:`qmm_pallas_eligible`.
    """
    m, k_dim = x2.shape
    n = q.shape[1]
    bk = _pick(_BK_CANDIDATES, k_dim)
    bn = _pick(_BN_CANDIDATES, n)
    assert bk is not None and bn is not None, (m, k_dim, n)
    # Sublane-align the row block (bf16 tile: 16); padding rows are zeros
    # and sliced off after the call.
    m_pad = max(16, -(-m // 16) * 16)
    if m_pad != m:
        x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
    n_k = k_dim // bk

    out = pl.pallas_call(
        functools.partial(_qmm_kernel, n_k=n_k),
        grid=(n // bn, n_k),
        in_specs=[
            pl.BlockSpec((m_pad, bk), lambda i, j: (0, j)),
            pl.BlockSpec((bk, bn), lambda i, j: (j, i)),
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((m_pad, bn), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x2.dtype),
        scratch_shapes=[pltpu.VMEM((m_pad, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(x2, q, s.astype(jnp.float32))
    return out[:m]
