"""Ragged paged attention — the core serving op.

One code path serves both prefill and decode (decode is T=1): the current
chunk's K/V are scattered into the paged KV pool first, then queries attend
over the pool through the page table with a causal/ragged mask. This mirrors
the semantics of TPU ragged paged attention kernels (PAPERS.md: "Ragged Paged
Attention for TPU") and keeps shapes fully static for XLA.

Two implementations:

- :func:`paged_attention` — portable XLA path: flash-style blockwise
  accumulation (running max / normalizer) over KV-page blocks via ``lax.scan``,
  so HBM traffic per step is O(block) not O(max_seq). Runs on CPU meshes and
  TPU alike.
- A Pallas TPU kernel (``runbookai_tpu.ops.paged_attention_pallas``) selected
  by the engine on real TPU hardware for the decode hot loop.

No reference counterpart — RunbookAI delegates all model execution to hosted
APIs (SURVEY.md §2.9).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30

# int8 KV cache (kv_dtype=int8): pools are (values int8 [..., hd],
# scales f32 [...]) tuples with one absmax scale per (token, kv head) —
# written once per token, never rescaled (no read-modify-write under
# jit). TPUs accelerate int8 natively (fp8 converts through bf16 on
# v5e), and per-token absmax tracks magnitude better than e4m3's fixed
# exponent range at the same pool bytes (+4/head_dim scale overhead).


def quantize_kv(new_kv: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., hd] → (int8 values, f32 absmax-per-vector scales [...])."""
    scale = jnp.max(jnp.abs(new_kv.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(scale, 1e-8) / 127.0
    q = jnp.round(new_kv.astype(jnp.float32) / scale[..., None])
    return jnp.clip(q, -127, 127).astype(jnp.int8), scale


def _dequant_gather(kv_flat, flat_idx):
    """Gather pool rows at ``flat_idx``; dequantize when the pool is an
    (int8 values, f32 scales) tuple."""
    if isinstance(kv_flat, tuple):
        vals, scales = kv_flat
        return vals[flat_idx].astype(jnp.float32) \
            * scales[flat_idx][..., None]
    return kv_flat[flat_idx].astype(jnp.float32)


def write_kv_pages(
    kv_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, head_dim]
    new_kv: jnp.ndarray,  # [T, n_kv, head_dim]
    positions: jnp.ndarray,  # [T] absolute token positions in the sequence
    page_table_row: jnp.ndarray,  # [max_pages] physical page ids for this seq
    page_size: int,
) -> jnp.ndarray:
    """Scatter one sequence's new K or V vectors into the flat page pool."""
    logical_page = positions // page_size
    offset = positions % page_size
    dest = page_table_row[logical_page] * page_size + offset  # [T]
    if isinstance(kv_flat, tuple):
        vals, scales = kv_flat
        q, s = quantize_kv(new_kv)
        return vals.at[dest].set(q), scales.at[dest].set(s)
    return kv_flat.at[dest].set(new_kv.astype(kv_flat.dtype))


def write_kv_pages_batch(
    kv_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, head_dim]
    new_kv: jnp.ndarray,  # [B, T, n_kv, head_dim]
    positions: jnp.ndarray,  # [B, T] absolute positions (pads -> trash column)
    page_tables: jnp.ndarray,  # [B, max_pages(+1)] physical page ids per seq
    page_size: int,
) -> jnp.ndarray:
    """Scatter a whole batch's new K/V in ONE flat scatter.

    Replaces a per-slot Python loop whose program size scaled with
    max_batch_slots (VERDICT r1 weak #6). Sequences own disjoint pages, so
    flattened destinations never collide — except padding rows, whose
    positions resolve through the trailing trash column to the reserved
    null page 0 (PageAllocator.NULL_PAGE), which is never read.
    """
    b, t = positions.shape
    logical_page = positions // page_size
    offset = positions % page_size
    phys = jnp.take_along_axis(page_tables, logical_page, axis=1)  # [B, T]
    dest = (phys * page_size + offset).reshape(b * t)
    flat_new = new_kv.reshape((b * t,) + new_kv.shape[2:])
    if isinstance(kv_flat, tuple):  # int8 pool: values + per-vector scales
        vals, scales = kv_flat
        q, s = quantize_kv(flat_new)
        return vals.at[dest].set(q), scales.at[dest].set(s)
    return kv_flat.at[dest].set(flat_new.astype(kv_flat.dtype))


def paged_attention(
    q: jnp.ndarray,  # [B, T, n_q, head_dim]
    k_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, head_dim]
    v_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, head_dim]
    page_tables: jnp.ndarray,  # [B, max_pages]
    ctx_lens: jnp.ndarray,  # [B] total cached tokens per sequence (incl. chunk)
    q_positions: jnp.ndarray,  # [B, T] absolute positions of the queries
    page_size: int,
    block_pages: int = 32,
) -> jnp.ndarray:
    """Blockwise ragged paged attention. Returns [B, T, n_q, head_dim]."""
    b, t, n_q, d = q.shape
    n_kv = (k_flat[0] if isinstance(k_flat, tuple) else k_flat).shape[1]
    group = n_q // n_kv
    max_pages = page_tables.shape[1]
    n_blocks = max(1, (max_pages + block_pages - 1) // block_pages)
    block_tokens = block_pages * page_size

    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qf = q.astype(jnp.float32) * scale
    # [B, T, n_kv, group, d] so kv heads broadcast over their query group.
    qf = qf.reshape(b, t, n_kv, group, d)

    def block_step(carry, blk):
        m, l, acc = carry  # [B,T,n_kv,group], same, [B,T,n_kv,group,d]
        page_idx = blk * block_pages + jnp.arange(block_pages)  # [block_pages]
        phys = page_tables[:, :]  # [B, max_pages]
        phys_blk = jnp.take_along_axis(
            phys, jnp.broadcast_to(page_idx[None, :], (b, block_pages)) % max_pages, axis=1
        )  # [B, block_pages]
        token_off = jnp.arange(block_tokens)
        flat_idx = (
            phys_blk[:, token_off // page_size] * page_size + token_off % page_size
        )  # [B, block_tokens]
        kb = _dequant_gather(k_flat, flat_idx)  # [B, block_tokens, n_kv, d]
        vb = _dequant_gather(v_flat, flat_idx)

        # Absolute cache positions covered by this block (same for every seq).
        cache_pos = blk * block_tokens + token_off  # [block_tokens]
        # Causal + ragged mask: position visible iff < ctx_len and <= q_position.
        valid = (cache_pos[None, :] < ctx_lens[:, None])[:, None, :]  # [B,1,block]
        causal = cache_pos[None, None, :] <= q_positions[:, :, None]  # [B,T,block]
        mask = (valid & causal)[:, :, None, None, :]  # [B,T,1,1,block]

        scores = jnp.einsum("btkgd,bskd->btkgs", qf, kb)  # [B,T,n_kv,group,block]
        scores = jnp.where(mask, scores, NEG_INF)

        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # Renormalize previous accumulator, add this block's contribution.
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("btkgs,bskd->btkgd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, t, n_kv, group), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, t, n_kv, group), dtype=jnp.float32)
    acc0 = jnp.zeros((b, t, n_kv, group, d), dtype=jnp.float32)
    (m, l, acc), _ = jax.lax.scan(block_step, (m0, l0, acc0), jnp.arange(n_blocks))

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(b, t, n_q, d).astype(q.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,  # [N, n_q, head_dim] — flat ragged token batch
    k_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, head_dim]
    v_flat: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [R, max_pages] per-ROW page tables
    ctx_lens: jnp.ndarray,  # [R] cached tokens per row (incl. this step's)
    q_positions: jnp.ndarray,  # [N] absolute position of each query token
    row_ids: jnp.ndarray,  # [N] row (sequence) owning each token
    page_size: int,
    block_pages: int = 32,
    ragged_block: int = 8,
) -> jnp.ndarray:
    """Portable XLA ragged paged attention over a FLAT mixed token batch.

    The segment-masked layout for the unified mixed prefill+decode dispatch
    (PAPERS.md "Ragged Paged Attention"): decode rows contribute 1 token,
    prefill rows a whole chunk, all flattened into one [N] buffer whose
    per-token ``row_ids`` select the page table / context length to attend
    through. Layout contract — each row's token run is contiguous and
    starts at a multiple of ``ragged_block`` (the engine's mixed-batch
    builder pads rows up to it) — so every ``ragged_block``-sized block
    belongs to exactly one row and the flat batch collapses to a
    [N/ragged_block, ragged_block] chunked call of :func:`paged_attention`
    with per-block gathered tables: page blocks are fetched once per
    ``ragged_block`` queries instead of once per token, and the existing
    causal+ragged mask (position < ctx, position ≤ q_position) does the
    segment masking. Pad tokens (trash positions / null rows with
    ``ctx_len = 0``) produce finite garbage that callers discard.

    This is the STANDALONE op (and the layout-contract reference, pinned
    against per-sequence attention by tests/test_mixed_dispatch.py): the
    serving forward does not call it per layer — ``forward_ragged_impl``
    hoists this exact flat→blocked transform above its layer scan so the
    KV-write gathers share it. Change the layout here and there together.

    Returns [N, n_q, head_dim].
    """
    n, n_q, d = q.shape
    rq = ragged_block
    nb = n // rq
    rows = row_ids.reshape(nb, rq)[:, 0]
    out = paged_attention(
        q.reshape(nb, rq, n_q, d), k_flat, v_flat,
        page_tables[rows], ctx_lens[rows], q_positions.reshape(nb, rq),
        page_size, block_pages=block_pages,
    )
    return out.reshape(n, n_q, d)
