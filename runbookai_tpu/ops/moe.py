"""Mixture-of-Experts FFN with static-shape dispatch (Mixtral-style).

TPU-first design: everything is fixed-shape so the whole layer jits once.
Routing is Mixtral's exactly (softmax over ALL expert logits in float32,
top-k selection, selected weights renormalized) so golden parity against
``transformers.MixtralForCausalLM`` holds. Dispatch is GShard-style
capacity-slotted, but built with a single scatter instead of the classic
``[N, E, C]`` one-hot tensor:

- every (token, k) pair gets a slot index inside its expert's queue via a
  cumulative count; pairs past the capacity drop (contribute zero),
- tokens scatter into a ``[E * C (+1 overflow), D]`` buffer (slot indices
  are unique per expert by construction, so the scatter is collision-free),
- experts run as one batched einsum over the leading E axis,
- outputs gather back by the same indices and combine with the gate weights.

Expert parallelism = shard the leading E axis of the expert weights and the
dispatched ``[E, C, D]`` activations over the mesh's ``model`` axis; XLA
inserts the all-to-alls from the shardings (scaling-book recipe). Capacity
``C = clamp(ceil(capacity_factor * N * top_k / E), 1, N)``, with
``capacity_factor <= 0`` (the config default) meaning dropless ``C = N`` —
exact transformers numerics; perf-tuned serving lowers the factor.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Static per-expert queue length for a dispatch of ``n_tokens``.

    ``capacity_factor <= 0`` means dropless: capacity ``n_tokens`` (the
    worst case — every token routes to the same expert), which reproduces
    transformers' ragged gather exactly."""
    if capacity_factor <= 0:
        return n_tokens
    c = math.ceil(capacity_factor * n_tokens * top_k / n_experts)
    return max(1, min(int(c), n_tokens))


def moe_ffn(
    y: jnp.ndarray,          # [B, T, D] (post-norm hidden)
    router: jnp.ndarray,     # [D, E]
    w_gate: Any,             # [E, D, F] (or int8 dict)
    w_up: Any,               # [E, D, F]
    w_down: Any,             # [E, F, D]
    top_k: int,
    capacity_factor: float,
) -> jnp.ndarray:
    """SwiGLU MoE block output (residual NOT added). Mixtral numerics."""
    b, t, d = y.shape
    e = router.shape[-1]
    n = b * t
    cap = expert_capacity(n, e, top_k, capacity_factor)
    x = y.reshape(n, d)

    from runbookai_tpu.models.llama import qmm  # deferred: models->ops cycle

    logits = (x.astype(jnp.float32) @ router.astype(jnp.float32))  # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)              # [N, K]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # Slot of each (token, k) pair inside its expert's queue: running count
    # of prior assignments to the same expert, in (token, k) order.
    onehot = jax.nn.one_hot(gate_idx.reshape(-1), e, dtype=jnp.int32)  # [N*K, E]
    slot = jnp.sum((jnp.cumsum(onehot, axis=0) - onehot) * onehot, axis=-1)
    keep = slot < cap
    dest = jnp.where(keep, gate_idx.reshape(-1) * cap + slot, e * cap)

    # Collision-free scatter dispatch (row e*cap+c holds that queue entry;
    # the final row is the shared overflow bin, read back as zeros).
    x_rep = jnp.repeat(x, top_k, axis=0)                           # [N*K, D]
    buf = jnp.zeros((e * cap + 1, d), x.dtype).at[dest].set(x_rep)
    xe = buf[: e * cap].reshape(e, cap, d)                         # [E, C, D]

    # qmm batches [E, C, a] @ [E, a, b] (jnp.matmul leading-axis batching;
    # the int8 dict's [E, 1, b] scale broadcasts) — one int8 semantics.
    act = jax.nn.silu(qmm(xe, w_gate)) * qmm(xe, w_up)
    out_e = qmm(act, w_down)                                       # [E, C, D]

    flat = jnp.concatenate(
        [out_e.reshape(e * cap, d), jnp.zeros((1, d), out_e.dtype)])
    back = flat[dest].reshape(n, top_k, d)                         # [N, K, D]
    combined = jnp.sum(back * gate_vals[..., None].astype(back.dtype), axis=1)
    return combined.reshape(b, t, d)
