"""Pallas TPU kernel: ragged paged attention for the decode hot loop.

SURVEY.md §7 names this "the single riskiest piece of device code": the XLA
fallback (:mod:`runbookai_tpu.ops.attention`) re-gathers KV through the page
table every step; this kernel instead drives the page-table indirection with
**scalar prefetch** — the grid's K/V block index_maps read the prefetched page
table, so Mosaic pipelines exactly the pages each sequence owns from HBM into
VMEM (double-buffered) and flash-accumulates in VMEM scratch.

Pattern per PAPERS.md "Ragged Paged Attention" + the pallas guide
(PrefetchScalarGridSpec): grid = (batch, pages); for a fixed sequence the page
axis iterates sequentially, carrying (m, l, acc) scratch; the output block is
written on the sequence's last page step. Decode-shaped (T = 1).

Two kernels share the flash-accumulate pattern:

- :func:`paged_decode_attention` — decode-shaped (T = 1), grid (batch, pages).
- :func:`paged_chunk_attention` — T > 1 (chunked prefill and the speculative
  verify forward), grid (batch, q_blocks, pages) with the page axis innermost
  so scratch carries across a sequence's pages; query positions are scalar-
  prefetched for the causal+ragged mask, and the query dimension is blocked
  to bound VMEM scratch (TQ·n_q accumulator rows per step).

Selected by ``EngineConfig.attn_impl = "pallas"``; interpret mode keeps it
testable on CPU meshes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _flash_page_accumulate(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                           base, ctx, n_kv: int, group: int,
                           page_size: int, ks_ref=None, vs_ref=None) -> None:
    """Shared online-softmax accumulation of one K/V page into the
    (m, l, acc) scratch — the body of ALL decode kernels (full-pool,
    kv-split partial, int8-scaled), kept in one place so masking/numerics
    fixes cannot diverge. Masked positions are explicitly zeroed in p
    (exp underflow handles them too, but the explicit mask keeps l exact
    by construction). With ``ks_ref``/``vs_ref`` the K/V page holds int8
    values and these are their per-(token, head) f32 absmax scales,
    applied on the in-VMEM widen (ops/attention.py quantize_kv)."""
    q = q_ref[0].astype(jnp.float32)  # [n_q, hd]
    hd = q.shape[-1]
    scale = 1.0 / (hd ** 0.5)
    pos = base + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
    valid = pos < ctx  # [1, page_size]

    m_prev = m_ref[:, :1]  # [n_q, 1]
    l_prev = l_ref[:, :1]
    acc_prev = acc_ref[:]

    s_rows = []
    v_heads = []
    for h in range(n_kv):
        k_h = k_ref[0, :, h, :].astype(jnp.float32)  # [ps, hd]
        if ks_ref is not None:
            k_h = k_h * ks_ref[0, :, h][:, None]
        q_h = q[h * group : (h + 1) * group]  # [group, hd]
        s_h = jax.lax.dot_general(
            q_h * scale, k_h, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [group, ps]
        s_rows.append(jnp.where(valid, s_h, NEG_INF))
        v_h = v_ref[0, :, h, :].astype(jnp.float32)  # [ps, hd]
        if vs_ref is not None:
            v_h = v_h * vs_ref[0, :, h][:, None]
        v_heads.append(v_h)
    s = jnp.concatenate(s_rows, axis=0)  # [n_q, ps] (kv-major head order)

    m_blk = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    alpha = jnp.exp(m_prev - m_new)
    p_blk = jnp.where(valid, jnp.exp(s - m_new), 0.0)  # [1,ps] broadcasts
    l_new = l_prev * alpha + jnp.sum(p_blk, axis=1, keepdims=True)

    pv_rows = []
    for h in range(n_kv):
        p_h = p_blk[h * group : (h + 1) * group]
        pv_rows.append(jax.lax.dot_general(
            p_h, v_heads[h], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ))  # [group, hd]
    pv = jnp.concatenate(pv_rows, axis=0)  # [n_q, hd]

    acc_ref[:] = acc_prev * alpha + pv
    m_ref[:, :1] = m_new
    l_ref[:, :1] = l_new


def _decode_kernel(
    # scalar prefetch:
    page_tables_ref,  # [B, P] int32 (SMEM)
    ctx_lens_ref,  # [B] int32 (SMEM)
    # blocks:
    q_ref,  # [1, n_q, hd]
    k_ref,  # [1, page_size, n_kv, hd]
    v_ref,  # [1, page_size, n_kv, hd]
    o_ref,  # [1, n_q, hd]
    # scratch:
    m_ref,  # [n_q, 128] f32
    l_ref,  # [n_q, 128] f32
    acc_ref,  # [n_q, hd] f32
    *,
    page_size: int,
    n_kv: int,
    group: int,
    pages_per_seq: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_lens_ref[b]
    base = p * page_size

    @pl.when(base < ctx)
    def _accumulate():
        _flash_page_accumulate(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                               base, ctx, n_kv, group, page_size)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l_final = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_final).astype(o_ref.dtype)


def _decode_kernel_int8(
    # scalar prefetch:
    page_tables_ref,  # [B, P] int32 (SMEM)
    ctx_lens_ref,  # [B] int32 (SMEM)
    # blocks:
    q_ref,  # [1, n_q, hd]
    k_ref,  # [1, page_size, n_kv, hd] int8
    v_ref,  # [1, page_size, n_kv, hd] int8
    ks_ref,  # [1, page_size, n_kv] f32 absmax scales
    vs_ref,  # [1, page_size, n_kv] f32
    o_ref,  # [1, n_q, hd]
    # scratch:
    m_ref,
    l_ref,
    acc_ref,
    *,
    page_size: int,
    n_kv: int,
    group: int,
    pages_per_seq: int,
):
    """int8-KV decode: identical flash accumulation, values widened and
    scaled in VMEM on load — HBM still moves 1 byte/value."""
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_lens_ref[b]
    base = p * page_size

    @pl.when(base < ctx)
    def _accumulate():
        _flash_page_accumulate(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                               base, ctx, n_kv, group, page_size,
                               ks_ref=ks_ref, vs_ref=vs_ref)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l_final = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_final).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, n_q, hd]
    k_flat,  # [num_pages * page_size, n_kv, hd], or (int8 values, scales)
    v_flat,  # same
    page_tables: jnp.ndarray,  # [B, P] int32 (physical page ids; 0 = null)
    ctx_lens: jnp.ndarray,  # [B] int32
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged attention for decode (one query token per sequence)."""
    if isinstance(k_flat, tuple):
        return _paged_decode_attention_int8(
            q, k_flat, v_flat, page_tables, ctx_lens,
            page_size=page_size, interpret=interpret)
    b, n_q, hd = q.shape
    n_kv = k_flat.shape[1]
    group = n_q // n_kv
    pages_per_seq = page_tables.shape[1]
    k_pages = k_flat.reshape(-1, page_size, n_kv, hd)
    v_pages = v_flat.reshape(-1, page_size, n_kv, hd)

    # Query head order for the kernel is kv-major ([kv0 g0..gN, kv1 g0..], the
    # same grouping the model's reshape uses) — no permutation needed.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl: (b_, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, 128), jnp.float32),  # m
            pltpu.VMEM((n_q, 128), jnp.float32),  # l
            pltpu.VMEM((n_q, hd), jnp.float32),  # acc
        ],
    )
    kernel = functools.partial(
        _decode_kernel, page_size=page_size, n_kv=n_kv, group=group,
        pages_per_seq=pages_per_seq,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_q, hd), q.dtype),
        interpret=interpret,
    )(page_tables, ctx_lens, q, k_pages, v_pages)


def _paged_decode_attention_int8(
    q: jnp.ndarray,  # [B, n_q, hd]
    k_flat: tuple,  # (int8 values [tokens, n_kv, hd], f32 scales [tokens, n_kv])
    v_flat: tuple,
    page_tables: jnp.ndarray,
    ctx_lens: jnp.ndarray,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode over the int8-scaled pool: same grid/prefetch as the raw
    kernel with two extra per-page scale blocks."""
    b, n_q, hd = q.shape
    k_vals, k_scales = k_flat
    v_vals, v_scales = v_flat
    n_kv = k_vals.shape[1]
    group = n_q // n_kv
    pages_per_seq = page_tables.shape[1]
    k_pages = k_vals.reshape(-1, page_size, n_kv, hd)
    v_pages = v_vals.reshape(-1, page_size, n_kv, hd)
    ks_pages = k_scales.reshape(-1, page_size, n_kv)
    vs_pages = v_scales.reshape(-1, page_size, n_kv)

    kv_map = lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0, 0)  # noqa: E731
    s_map = lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0)  # noqa: E731
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl: (b_, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, page_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, page_size, n_kv), s_map),
            pl.BlockSpec((1, page_size, n_kv), s_map),
        ],
        out_specs=pl.BlockSpec((1, n_q, hd),
                               lambda b_, p_, pt, cl: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_int8, page_size=page_size, n_kv=n_kv, group=group,
        pages_per_seq=pages_per_seq,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_q, hd), q.dtype),
        interpret=interpret,
    )(page_tables, ctx_lens, q, k_pages, v_pages, ks_pages, vs_pages)


def _chunk_kernel(
    # scalar prefetch:
    page_tables_ref,  # [B, P] int32 (SMEM)
    ctx_lens_ref,  # [B] int32 (SMEM)
    q_start_ref,  # [B] int32 (SMEM) — absolute position of each row's query 0
    # blocks:
    q_ref,  # [1, TQ, n_q, hd]
    k_ref,  # [1, page_size, n_kv, hd]
    v_ref,  # [1, page_size, n_kv, hd]
    o_ref,  # [1, TQ, n_q, hd]
    # scratch:
    m_ref,  # [TQ*n_q, 128] f32
    l_ref,  # [TQ*n_q, 128] f32
    acc_ref,  # [TQ*n_q, hd] f32
    *,
    page_size: int,
    n_kv: int,
    group: int,
    tq: int,
    pages_per_seq: int,
):
    b = pl.program_id(0)
    qb = pl.program_id(1)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_lens_ref[b]
    base = p * page_size
    # Query positions are contiguous per sequence (wrapper contract), so row
    # positions derive from the scalar start — no vector SMEM reads needed.
    q0 = q_start_ref[b] + qb * tq
    qpos_max = q0 + tq - 1

    @pl.when((base < ctx) & (base <= qpos_max))
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [TQ, n_q, hd]
        hd = q.shape[-1]
        scale = 1.0 / (hd ** 0.5)
        # Row r of a per-kv-head block is query token r // group; mask built
        # entirely from 2D iotas (Mosaic-friendly).
        shape = (tq * group, page_size)
        cache_pos = base + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        qpos_rows = q0 + jax.lax.broadcasted_iota(jnp.int32, shape, 0) // group
        mask = (cache_pos < ctx) & (cache_pos <= qpos_rows)

        m_prev = m_ref[:, :1]  # [TQ*n_q, 1]
        l_prev = l_ref[:, :1]
        acc_prev = acc_ref[:]

        s_rows = []
        v_heads = []
        for h in range(n_kv):
            k_h = k_ref[0, :, h, :].astype(jnp.float32)  # [ps, hd]
            # [TQ, group, hd] -> [TQ*group, hd] rows (t-major within the head)
            q_h = q[:, h * group : (h + 1) * group].reshape(tq * group, hd)
            s_h = jax.lax.dot_general(
                q_h * scale, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TQ*group, ps]
            s_rows.append(jnp.where(mask, s_h, NEG_INF))
            v_heads.append(v_ref[0, :, h, :].astype(jnp.float32))  # [ps, hd]
        s = jnp.concatenate(s_rows, axis=0)  # [TQ*n_q, ps] (kv-major blocks)

        m_blk = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        # Fully-masked rows keep m == NEG_INF; exp(s - m) would be exp(0)=1
        # there, so zero masked probabilities explicitly (keeps l exact and
        # padded rows normalizing to zero).
        p_blk = jnp.where(jnp.concatenate([mask] * n_kv, axis=0),
                          jnp.exp(s - m_new), 0.0)
        l_new = l_prev * alpha + jnp.sum(p_blk, axis=1, keepdims=True)

        pv_rows = []
        for h in range(n_kv):
            p_h = p_blk[h * tq * group : (h + 1) * tq * group]
            pv_rows.append(jax.lax.dot_general(
                p_h, v_heads[h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))  # [TQ*group, hd]
        pv = jnp.concatenate(pv_rows, axis=0)

        acc_ref[:] = acc_prev * alpha + pv
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l_final = jnp.maximum(l_ref[:, :1], 1e-30)
        out = acc_ref[:] / l_final  # [TQ*n_q, hd] in kv-major head blocks
        hd = out.shape[-1]
        # Per-head static slices back to [TQ, group, hd] (no 4D transpose).
        for h in range(n_kv):
            blk = out[h * tq * group : (h + 1) * tq * group]
            o_ref[0, :, h * group : (h + 1) * group, :] = (
                blk.reshape(tq, group, hd).astype(o_ref.dtype))


def paged_chunk_attention(
    q: jnp.ndarray,  # [B, T, n_q, hd]
    k_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, hd]
    v_flat: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [B, P] int32 (physical page ids; 0 = null)
    ctx_lens: jnp.ndarray,  # [B] int32 — cache length AFTER the chunk
    q_positions: jnp.ndarray,  # [B, T] int32 absolute positions of the queries
    page_size: int,
    interpret: bool = False,
    q_block: int | None = None,
) -> jnp.ndarray:
    """Ragged paged attention for T>1 chunks (prefill / speculative verify).

    Matches :func:`runbookai_tpu.ops.attention.paged_attention` semantics —
    causal over absolute positions, ragged over per-sequence context lengths —
    under one contract: each sequence's ``q_positions`` row must be contiguous
    ascending (``q_positions[i, t] == q_positions[i, 0] + t``). Both engine
    chunk paths satisfy this (prefill feeds ``range(pos, pos+chunk)``; the
    speculative verify feeds ``range(ctx-1, ctx-1+k)``); prefill's trash-
    position pad tail violates it, but those rows' outputs are discarded and
    their K/V go to the null page.
    """
    b, t, n_q, hd = q.shape
    n_kv = k_flat.shape[1]
    group = n_q // n_kv
    pages_per_seq = page_tables.shape[1]
    k_pages = k_flat.reshape(-1, page_size, n_kv, hd)
    v_pages = v_flat.reshape(-1, page_size, n_kv, hd)
    q_start = q_positions[:, 0].astype(jnp.int32)

    # Block the query dim so VMEM scratch stays bounded (~1k accumulator rows).
    tq = q_block if q_block is not None else min(t, max(1, 1024 // n_q))
    t_pad = ((t + tq - 1) // tq) * tq
    n_qb = t_pad // tq
    if t_pad != t:
        # Padded rows act like later queries (q0 + t): they attend at most the
        # whole context and their outputs are sliced off on return.
        q = jnp.pad(q, ((0, 0), (0, t_pad - t), (0, 0), (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, n_qb, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, tq, n_q, hd),
                         lambda b_, qb_, p_, pt, cl, qs: (b_, qb_, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, qb_, p_, pt, cl, qs: (pt[b_, p_], 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, qb_, p_, pt, cl, qs: (pt[b_, p_], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tq, n_q, hd),
                               lambda b_, qb_, p_, pt, cl, qs: (b_, qb_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tq * n_q, 128), jnp.float32),  # m
            pltpu.VMEM((tq * n_q, 128), jnp.float32),  # l
            pltpu.VMEM((tq * n_q, hd), jnp.float32),  # acc
        ],
    )
    kernel = functools.partial(
        _chunk_kernel, page_size=page_size, n_kv=n_kv, group=group, tq=tq,
        pages_per_seq=pages_per_seq,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, t_pad, n_q, hd), q.dtype),
        interpret=interpret,
    )(page_tables, ctx_lens, q_start, q, k_pages, v_pages)
    return out[:, :t]


def paged_ragged_attention(
    q: jnp.ndarray,  # [N, n_q, hd] — flat ragged token batch
    k_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, hd]
    v_flat: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [R, P] int32 per-ROW page tables
    ctx_lens: jnp.ndarray,  # [R] int32 cache length incl. this step's tokens
    q_positions: jnp.ndarray,  # [N] int32 absolute query positions
    row_ids: jnp.ndarray,  # [N] int32 row owning each token
    page_size: int,
    ragged_block: int = 8,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged attention over a FLAT mixed prefill+decode batch.

    The per-row-ragged extension of :func:`paged_chunk_attention` for the
    unified mixed dispatch (PAPERS.md "Ragged Paged Attention"): decode
    rows feed 1 token, prefill rows a chunk, flattened into one [N]
    buffer. Layout contract (the engine's mixed builder upholds it): each
    row's token run is contiguous ascending and starts at a multiple of
    ``ragged_block``, so every ``ragged_block``-sized q block belongs to
    exactly one row — the flat batch maps onto the chunk kernel's
    (sequence, q_block, page) grid with the q-block axis re-labelled by a
    per-block row gather. Each grid step still scalar-prefetches the
    owning row's page table and flash-accumulates in VMEM, and K/V pages
    are fetched once per ``ragged_block`` queries rather than once per
    token (the reason this beats running the decode kernel at B = N).
    Per-row raggedness is carried by the per-block ``ctx_lens`` /
    ``q_start`` scalars: a pad block (null row, ``ctx_len = 0``) skips
    every accumulation and finalizes to zeros; pad tokens inside a real
    row's last block act as later queries whose outputs the caller
    discards (their K/V writes go to the null page via trash positions).

    Returns [N, n_q, hd].
    """
    n, n_q, hd = q.shape
    rq = ragged_block
    nb = n // rq
    rows = row_ids.reshape(nb, rq)[:, 0]
    return paged_chunk_attention(
        q.reshape(nb, rq, n_q, hd), k_flat, v_flat,
        page_tables[rows], ctx_lens[rows], q_positions.reshape(nb, rq),
        page_size=page_size, interpret=interpret, q_block=rq,
    ).reshape(n, n_q, hd)


def _decode_kernel_partial(
    # scalar prefetch:
    page_tables_ref,  # [B, P] int32 GLOBAL page ids (SMEM)
    ctx_lens_ref,  # [B] int32 (SMEM)
    shard_ref,  # [1] int32 — this device's page-shard index (SMEM)
    # blocks:
    q_ref,  # [1, n_q, hd]
    k_ref,  # [1, page_size, n_kv, hd]  (LOCAL pool slice)
    v_ref,
    # outputs (un-normalized partials for the cross-shard merge):
    acc_out,  # [1, n_q, hd] f32
    m_out,  # [1, n_q, 128] f32
    l_out,  # [1, n_q, 128] f32
    # scratch:
    m_ref,
    l_ref,
    acc_ref,
    *,
    page_size: int,
    n_kv: int,
    group: int,
    pages_per_seq: int,
    pages_local: int,
):
    """KV page-split variant of :func:`_decode_kernel`: the pool ref is
    this device's page SLICE, pages not owned here are skipped (their
    shard contributes them), and the outputs are the flash partials
    ``(acc, m, l)`` — the shard_map wrapper merges across the ``seq``
    axis (``parallel/kv_split.py`` math) and normalizes."""
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_lens_ref[b]
    base = p * page_size
    owned = (page_tables_ref[b, p] // pages_local) == shard_ref[0]

    @pl.when((base < ctx) & owned)
    def _accumulate():
        _flash_page_accumulate(q_ref, k_ref, v_ref, m_ref, l_ref, acc_ref,
                               base, ctx, n_kv, group, page_size)

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        acc_out[0] = acc_ref[:]
        m_out[0] = m_ref[:]
        l_out[0] = l_ref[:]


def paged_decode_attention_partial(
    q: jnp.ndarray,  # [B, n_q, hd]
    k_local: jnp.ndarray,  # [pages_local * page_size, n_kv, hd]
    v_local: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, P] GLOBAL page ids
    ctx_lens: jnp.ndarray,  # [B]
    my_pg: jnp.ndarray,  # scalar int32 page-shard index
    page_size: int,
    pages_local: int,
    interpret: bool = False,
):
    """Flash partials over a LOCAL page slice; returns (acc, m, l) with
    m/l padded to lane width (column 0 is the value)."""
    b, n_q, hd = q.shape
    n_kv = k_local.shape[1]
    group = n_q // n_kv
    pages_per_seq = page_tables.shape[1]
    k_pages = k_local.reshape(-1, page_size, n_kv, hd)
    v_pages = v_local.reshape(-1, page_size, n_kv, hd)

    def kv_map(b_, p_, pt, cl, sh):
        # Foreign pages clamp to slot 0 — the ownership predicate skips
        # their accumulation, so the fetched block is never read.
        local = pt[b_, p_] - sh[0] * pages_local
        return (jnp.clip(local, 0, pages_local - 1), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl, sh: (b_, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd), kv_map),
            pl.BlockSpec((1, page_size, n_kv, hd), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl, sh: (b_, 0, 0)),
            pl.BlockSpec((1, n_q, 128), lambda b_, p_, pt, cl, sh: (b_, 0, 0)),
            pl.BlockSpec((1, n_q, 128), lambda b_, p_, pt, cl, sh: (b_, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, 128), jnp.float32),
            pltpu.VMEM((n_q, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel_partial, page_size=page_size, n_kv=n_kv, group=group,
        pages_per_seq=pages_per_seq, pages_local=pages_local,
    )
    acc, m, l = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, n_q, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n_q, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, n_q, 128), jnp.float32),
        ],
        interpret=interpret,
    )(page_tables, ctx_lens, my_pg.reshape(1), q, k_pages, v_pages)
    return acc, m[..., 0], l[..., 0]


# --------------------------------------------------------------------- TP ---
#
# Under a TP mesh the KV pool shards its kv-head axis and q its query-head
# axis (Megatron layout, parallel/sharding.py). XLA's SPMD partitioner can't
# see inside a pallas_call, so an unwrapped kernel would force an all-gather
# of the whole page pool every step — the exact failure VERDICT r2 weak #3
# called out. These wrappers run the kernel per model-axis shard via
# shard_map: each shard holds n_q/tp query heads and their matching n_kv/tp
# kv heads (head blocks are contiguous and kv-major, so GQA groups never
# straddle shards), while page tables and context lengths stay replicated.
# Attention mixes only across the context axis, never across heads — no
# collectives are needed inside the wrap.


def _model_tp(mesh) -> int:
    from runbookai_tpu.parallel.mesh import MODEL_AXIS

    return mesh.shape.get(MODEL_AXIS, 1) if mesh is not None else 1


def tp_shardable(mesh, n_kv: int) -> bool:
    """True when the kernels can run per model-axis shard: the kv-head axis
    must split evenly (matches ``kv_pool_sharding``'s shard-vs-replicate
    decision, so the pool layout and the kernel wrap always agree)."""
    tp = _model_tp(mesh)
    return tp > 1 and n_kv % tp == 0


def paged_decode_attention_tp(
    mesh, q, k_flat, v_flat, page_tables, ctx_lens, page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """:func:`paged_decode_attention` over a TP mesh (heads sharded)."""
    from jax.sharding import PartitionSpec as P

    from runbookai_tpu.parallel.mesh import MODEL_AXIS

    heads = P(None, MODEL_AXIS, None)
    fn = functools.partial(paged_decode_attention, page_size=page_size,
                           interpret=interpret)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(heads, heads, heads, P(None, None), P(None)),
        out_specs=heads,
        # pallas_call out_shapes carry no varying-mesh-axes info; the wrap
        # itself is collective-free so the vma check adds nothing here.
        check_vma=False,
    )(q, k_flat, v_flat, page_tables, ctx_lens)


def paged_chunk_attention_tp(
    mesh, q, k_flat, v_flat, page_tables, ctx_lens, q_positions,
    page_size: int, interpret: bool = False,
) -> jnp.ndarray:
    """:func:`paged_chunk_attention` over a TP mesh (heads sharded)."""
    from jax.sharding import PartitionSpec as P

    from runbookai_tpu.parallel.mesh import MODEL_AXIS

    kv_heads = P(None, MODEL_AXIS, None)
    q_heads = P(None, None, MODEL_AXIS, None)
    fn = functools.partial(paged_chunk_attention, page_size=page_size,
                           interpret=interpret)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(q_heads, kv_heads, kv_heads, P(None, None), P(None),
                  P(None, None)),
        out_specs=q_heads,
        check_vma=False,
    )(q, k_flat, v_flat, page_tables, ctx_lens, q_positions)
