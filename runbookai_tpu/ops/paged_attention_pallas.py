"""Pallas TPU kernel: ragged paged attention for the decode hot loop.

SURVEY.md §7 names this "the single riskiest piece of device code": the XLA
fallback (:mod:`runbookai_tpu.ops.attention`) re-gathers KV through the page
table every step; this kernel instead drives the page-table indirection with
**scalar prefetch** — the grid's K/V block index_maps read the prefetched page
table, so Mosaic pipelines exactly the pages each sequence owns from HBM into
VMEM (double-buffered) and flash-accumulates in VMEM scratch.

Pattern per PAPERS.md "Ragged Paged Attention" + the pallas guide
(PrefetchScalarGridSpec): grid = (batch, pages); for a fixed sequence the page
axis iterates sequentially, carrying (m, l, acc) scratch; the output block is
written on the sequence's last page step. Decode-shaped (T = 1).

Selected by ``EngineConfig.attn_impl = "pallas"``; interpret mode keeps it
testable on CPU meshes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(
    # scalar prefetch:
    page_tables_ref,  # [B, P] int32 (SMEM)
    ctx_lens_ref,  # [B] int32 (SMEM)
    # blocks:
    q_ref,  # [1, n_q, hd]
    k_ref,  # [1, page_size, n_kv, hd]
    v_ref,  # [1, page_size, n_kv, hd]
    o_ref,  # [1, n_q, hd]
    # scratch:
    m_ref,  # [n_q, 128] f32
    l_ref,  # [n_q, 128] f32
    acc_ref,  # [n_q, hd] f32
    *,
    page_size: int,
    n_kv: int,
    group: int,
    pages_per_seq: int,
):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    ctx = ctx_lens_ref[b]
    base = p * page_size

    @pl.when(base < ctx)
    def _accumulate():
        q = q_ref[0].astype(jnp.float32)  # [n_q, hd]
        hd = q.shape[-1]
        scale = 1.0 / (hd ** 0.5)
        pos = base + jax.lax.broadcasted_iota(jnp.int32, (1, page_size), 1)
        valid = pos < ctx  # [1, page_size]

        m_prev = m_ref[:, :1]  # [n_q, 1]
        l_prev = l_ref[:, :1]
        acc_prev = acc_ref[:]

        # Per-kv-head score blocks (n_kv is small and static -> unrolled).
        s_rows = []
        v_heads = []
        for h in range(n_kv):
            k_h = k_ref[0, :, h, :].astype(jnp.float32)  # [ps, hd]
            q_h = q[h * group : (h + 1) * group]  # [group, hd]
            s_h = jax.lax.dot_general(
                q_h * scale, k_h, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [group, ps]
            s_rows.append(jnp.where(valid, s_h, NEG_INF))
            v_heads.append(v_ref[0, :, h, :].astype(jnp.float32))  # [ps, hd]
        s = jnp.concatenate(s_rows, axis=0)  # [n_q, ps] (kv-major head order)

        m_blk = jnp.max(s, axis=1, keepdims=True)  # [n_q, 1]
        m_new = jnp.maximum(m_prev, m_blk)
        alpha = jnp.exp(m_prev - m_new)
        p_blk = jnp.exp(s - m_new)  # [n_q, ps]
        l_new = l_prev * alpha + jnp.sum(p_blk, axis=1, keepdims=True)

        pv_rows = []
        for h in range(n_kv):
            p_h = p_blk[h * group : (h + 1) * group]  # [group, ps]
            pv_rows.append(jax.lax.dot_general(
                p_h, v_heads[h], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ))  # [group, hd]
        pv = jnp.concatenate(pv_rows, axis=0)  # [n_q, hd]

        acc_ref[:] = acc_prev * alpha + pv
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    @pl.when(p == pages_per_seq - 1)
    def _finalize():
        l_final = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_final).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, n_q, hd]
    k_flat: jnp.ndarray,  # [num_pages * page_size, n_kv, hd]
    v_flat: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [B, P] int32 (physical page ids; 0 = null)
    ctx_lens: jnp.ndarray,  # [B] int32
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Ragged paged attention for decode (one query token per sequence)."""
    b, n_q, hd = q.shape
    n_kv = k_flat.shape[1]
    group = n_q // n_kv
    pages_per_seq = page_tables.shape[1]
    k_pages = k_flat.reshape(-1, page_size, n_kv, hd)
    v_pages = v_flat.reshape(-1, page_size, n_kv, hd)

    # Query head order for the kernel is kv-major ([kv0 g0..gN, kv1 g0..], the
    # same grouping the model's reshape uses) — no permutation needed.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl: (b_, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0, 0)),
            pl.BlockSpec((1, page_size, n_kv, hd),
                         lambda b_, p_, pt, cl: (pt[b_, p_], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n_q, hd), lambda b_, p_, pt, cl: (b_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n_q, 128), jnp.float32),  # m
            pltpu.VMEM((n_q, 128), jnp.float32),  # l
            pltpu.VMEM((n_q, hd), jnp.float32),  # acc
        ],
    )
    kernel = functools.partial(
        _decode_kernel, page_size=page_size, n_kv=n_kv, group=group,
        pages_per_seq=pages_per_seq,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, n_q, hd), q.dtype),
        interpret=interpret,
    )(page_tables, ctx_lens, q, k_pages, v_pages)
