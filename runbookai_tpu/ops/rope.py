"""Rotary position embeddings (RoPE) for the Llama family.

Pure-functional, jit-friendly: frequencies are computed from a static config
and applied at arbitrary (possibly ragged) positions, which is what the paged
engine needs — decode steps apply RoPE at per-sequence positions.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float,
                     scaling: Optional[tuple] = None) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32.

    ``scaling`` is the Llama-3.1 long-context NTK-by-parts tuple
    ``(factor, low_freq_factor, high_freq_factor, original_max_pos)`` (HF
    ``rope_scaling`` with ``rope_type="llama3"``): wavelengths shorter than
    ``orig/high`` keep their frequency, longer than ``orig/low`` divide by
    ``factor``, and the band between interpolates smoothly — extending 8k
    training context to 128k serving context."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponents)
    if scaling is None:
        return inv_freq
    factor, low, high, orig_max = (float(scaling[0]), float(scaling[1]),
                                   float(scaling[2]), float(scaling[3]))
    wavelen = 2.0 * jnp.pi / inv_freq
    smooth = (orig_max / wavelen - low) / (high - low)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    # smooth==1 (short wavelen) -> unscaled; smooth==0 (long) -> /factor.
    return (1.0 - smooth) * inv_freq / factor + smooth * inv_freq


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float,
               scaling: Optional[tuple] = None) -> jnp.ndarray:
    """Rotate ``x`` of shape [B, T, H, D] at integer ``positions`` [B, T].

    Uses the interleaved-pair convention folded as (first half, second half)
    rotation — the layout used by HF Llama checkpoints — in float32 for
    numerical stability, returning the input dtype.
    """
    b, t, h, d = x.shape
    inv_freq = rope_frequencies(d, theta, scaling)  # [D/2]
    angles = positions.astype(jnp.float32)[:, :, None] * inv_freq[None, None, :]  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
