"""Rotary position embeddings (RoPE) for the Llama family.

Pure-functional, jit-friendly: frequencies are computed from a static config
and applied at arbitrary (possibly ragged) positions, which is what the paged
engine needs — decode steps apply RoPE at per-sequence positions.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies, shape [head_dim // 2], float32."""
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponents)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotate ``x`` of shape [B, T, H, D] at integer ``positions`` [B, T].

    Uses the interleaved-pair convention folded as (first half, second half)
    rotation — the layout used by HF Llama checkpoints — in float32 for
    numerical stability, returning the input dtype.
    """
    b, t, h, d = x.shape
    inv_freq = rope_frequencies(d, theta)  # [D/2]
    angles = positions.astype(jnp.float32)[:, :, None] * inv_freq[None, None, :]  # [B,T,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,T,1,D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
