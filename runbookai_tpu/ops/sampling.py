"""On-device token sampling: greedy / temperature / top-p, plus logit masks.

Runs entirely on device inside the decode step (no host round-trip per token
beyond fetching the sampled ids). Grammar masks from guided decoding are
applied as additive ``-inf`` masks before sampling.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jnp.ndarray,  # [B, vocab] float32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B] float32; 0 -> greedy
    top_p: jnp.ndarray,  # [B] float32 in (0, 1]
    mask: jnp.ndarray | None = None,  # [B, vocab] bool, True = allowed
    top_k: jnp.ndarray | None = None,  # [B] int32; 0 -> disabled
    counts: jnp.ndarray | None = None,  # [B, vocab] int32 token counts
    presence: jnp.ndarray | None = None,  # [B] float32 presence penalty
    frequency: jnp.ndarray | None = None,  # [B] float32 frequency penalty
    seeds: jnp.ndarray | None = None,  # [B] int32; -1 -> batch key
    positions: jnp.ndarray | None = None,  # [B] int32 (seeded-key fold)
    bias: jnp.ndarray | None = None,  # [B, vocab] float32 logit_bias
) -> jnp.ndarray:
    """Sample one token per row. Vectorized top-p via sorted-CDF threshold;
    top-k composes with top-p (a token must survive both filters).

    OpenAI-style penalties (opt-in): ``logits - presence*(count>0) -
    frequency*count`` over the request's token history BEFORE masking and
    greedy selection. Per-request ``seeds`` derive each row's key as
    ``fold_in(PRNGKey(seed), position)`` — reproducible for a given
    (seed, position) regardless of batch composition or engine history;
    rows with seed < 0 keep the dispatch key. ``bias`` ([B, vocab],
    OpenAI logit_bias densified host-side) adds BEFORE penalties, masks,
    and greedy selection."""
    if bias is not None:
        logits = logits + bias
    if counts is not None:
        pen = jnp.zeros_like(logits)
        if presence is not None:
            pen = pen + presence[:, None] * (counts > 0)
        if frequency is not None:
            pen = pen + frequency[:, None] * counts.astype(logits.dtype)
        logits = logits - pen
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    greedy = jnp.argmax(logits, axis=-1)

    # Temperature-scaled distribution (guard t=0 to avoid div-by-zero; those
    # rows take the greedy branch below).
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / safe_t

    # Top-p: sort descending, keep the smallest prefix with cumprob >= top_p.
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(sorted_probs, axis=-1)
    # Number of tokens kept per row: first index where cumprob >= top_p, +1.
    # Clamp to the vocab: with top_p=1.0, float32 rounding can leave every
    # cumprob fractionally below 1.0, and an unclamped keep would gather the
    # cutoff out of bounds (NaN -> the filter drops ALL tokens, including
    # grammar-allowed ones).
    keep = jnp.sum(cumprobs < top_p[:, None], axis=-1) + 1  # [B]
    keep = jnp.minimum(keep, logits.shape[-1])
    cutoff = jnp.take_along_axis(sorted_logits, (keep - 1)[:, None], axis=-1)  # [B,1]
    filtered = jnp.where(scaled >= cutoff, scaled, NEG_INF)

    if top_k is not None:
        # Keep the k highest-scaled tokens (rank cutoff on the same sorted
        # array); rows with top_k <= 0 keep the whole vocab.
        k_eff = jnp.where(top_k > 0, top_k, logits.shape[-1])
        k_idx = jnp.clip(k_eff - 1, 0, logits.shape[-1] - 1)
        cutoff_k = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=-1)
        filtered = jnp.where(scaled >= cutoff_k, filtered, NEG_INF)

    if seeds is None:
        sampled = jax.random.categorical(key, filtered, axis=-1)
    else:
        pos = (positions if positions is not None
               else jnp.zeros_like(seeds))
        rows = jnp.arange(filtered.shape[0], dtype=jnp.uint32)

        def row_key(seed, p, row):
            seeded = jax.random.fold_in(
                jax.random.PRNGKey(jnp.maximum(seed, 0)), p)
            batch = jax.random.fold_in(key, row)
            return jax.lax.select(seed >= 0, seeded, batch)

        keys = jax.vmap(row_key)(seeds, pos, rows)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(keys, filtered)
    return jnp.where(temperature <= 0.0, greedy, sampled).astype(jnp.int32)
