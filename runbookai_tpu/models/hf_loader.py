"""HF checkpoint loading: safetensors → stacked JAX pytrees (+ sharded put).

New construction (SURVEY.md §5.4 — the reference never loads weights). Reads a
HuggingFace Llama directory (``config.json`` + ``*.safetensors``), transposes
``[out, in]`` projection weights to this build's ``[in, out]`` convention,
stacks per-layer weights on a leading axis for the scan-based forward, and —
when a mesh is supplied — ``device_put``s each leaf with its TP/DP
``NamedSharding`` so 70B-class checkpoints stream straight to their shards
without materializing the full model on one host/chip.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.models.llama import CONFIGS, LlamaConfig, init_params

# Our layer-stacked param leaf -> (HF template, transpose?)
_LAYER_MAP = {
    "wq": ("model.layers.{i}.self_attn.q_proj.weight", True),
    "wk": ("model.layers.{i}.self_attn.k_proj.weight", True),
    "wv": ("model.layers.{i}.self_attn.v_proj.weight", True),
    "wo": ("model.layers.{i}.self_attn.o_proj.weight", True),
    "w_gate": ("model.layers.{i}.mlp.gate_proj.weight", True),
    "w_up": ("model.layers.{i}.mlp.up_proj.weight", True),
    "w_down": ("model.layers.{i}.mlp.down_proj.weight", True),
    "attn_norm": ("model.layers.{i}.input_layernorm.weight", False),
    "mlp_norm": ("model.layers.{i}.post_attention_layernorm.weight", False),
}

# Qwen2-only bias leaves (1-D per layer, no transpose).
_BIAS_MAP = {
    "bq": "model.layers.{i}.self_attn.q_proj.bias",
    "bk": "model.layers.{i}.self_attn.k_proj.bias",
    "bv": "model.layers.{i}.self_attn.v_proj.bias",
}


# HF model_type values this loader serves. All share the Llama block
# (pre-norm GQA attention + SwiGLU); qwen2 adds q/k/v projection biases,
# mixtral swaps the dense FFN for an 8-expert top-2 MoE. Mistral
# sliding-window checkpoints load fine and are served with full attention
# (exact for contexts up to the window).
SUPPORTED_MODEL_TYPES = ("llama", "qwen2", "mistral", "mixtral")


def config_from_hf(model_dir: str | Path, name: str = "hf-model") -> LlamaConfig:
    raw = json.loads((Path(model_dir) / "config.json").read_text())
    model_type = raw.get("model_type", "llama")
    if model_type not in SUPPORTED_MODEL_TYPES:
        raise ValueError(
            f"model_type {model_type!r} not supported; known: "
            f"{SUPPORTED_MODEL_TYPES}")
    # Llama-3.1-style long-context rope scaling (rope_type "llama3").
    # Other scaling schemes (linear/dynamic/yarn) would silently produce
    # wrong logits past the original context if dropped — refuse loudly,
    # matching the unsupported-model_type behavior.
    rs = raw.get("rope_scaling") or {}
    rope_scaling = None
    rs_type = rs.get("rope_type", rs.get("type"))
    if rs_type == "llama3":
        rope_scaling = (
            float(rs["factor"]),
            float(rs.get("low_freq_factor", 1.0)),
            float(rs.get("high_freq_factor", 4.0)),
            int(rs.get("original_max_position_embeddings", 8192)),
        )
    elif rs_type not in (None, "default"):
        raise ValueError(
            f"rope_scaling type {rs_type!r} not supported (only 'llama3'); "
            f"loading without it would silently change long-context numerics")
    return LlamaConfig(
        name=name,
        vocab_size=raw["vocab_size"],
        dim=raw["hidden_size"],
        n_layers=raw["num_hidden_layers"],
        n_heads=raw["num_attention_heads"],
        n_kv_heads=raw.get("num_key_value_heads", raw["num_attention_heads"]),
        ffn_dim=raw["intermediate_size"],
        rope_theta=raw.get("rope_theta", 500_000.0),
        rope_scaling=rope_scaling,
        norm_eps=raw.get("rms_norm_eps", 1e-5),
        # Sliding-window checkpoints (Mistral v0.1) are served with full
        # attention — exact only up to the window, so the window clamps the
        # serveable context rather than silently changing semantics past it.
        max_seq_len=min(raw.get("max_position_embeddings", 8192),
                        raw.get("sliding_window") or 1 << 30),
        tie_embeddings=raw.get("tie_word_embeddings", False),
        qkv_bias=model_type == "qwen2",
        family=model_type,
        n_experts=raw.get("num_local_experts", 0) if model_type == "mixtral" else 0,
        top_k_experts=raw.get("num_experts_per_tok", 2),
    )


class _ShardIndex:
    """Maps tensor name -> safetensors file, loading files lazily."""

    def __init__(self, model_dir: Path):
        self.dir = model_dir
        index_file = model_dir / "model.safetensors.index.json"
        self._handles: dict[str, Any] = {}
        if index_file.is_file():
            index = json.loads(index_file.read_text())
            self.weight_map = dict(index["weight_map"])
        else:
            shards = sorted(model_dir.glob("*.safetensors"))
            if not shards:
                raise FileNotFoundError(f"no .safetensors files under {model_dir}")
            from safetensors import safe_open

            self.weight_map = {}
            for shard in shards:
                with safe_open(str(shard), framework="numpy") as f:
                    for key in f.keys():
                        self.weight_map[key] = shard.name

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        fname = self.weight_map[name]
        handle = self._handles.get(fname)
        if handle is None:
            handle = safe_open(str(self.dir / fname), framework="numpy")
            self._handles[fname] = handle
        return handle.get_tensor(name)


def _put(arr: np.ndarray, dtype, sharding=None) -> jax.Array:
    x = jnp.asarray(arr, dtype=dtype)
    if sharding is not None:
        x = jax.device_put(x, sharding)
    return x


def load_params(
    model_dir: str | Path,
    cfg: Optional[LlamaConfig] = None,
    dtype=jnp.bfloat16,
    shardings: Optional[dict[str, Any]] = None,
    quantize_int8: bool = False,
) -> tuple[LlamaConfig, Any]:
    """Load stacked params from an HF Llama directory.

    ``shardings``, when given, is a pytree-shaped dict matching the params
    structure whose leaves are ``NamedSharding``s (see
    :func:`runbookai_tpu.parallel.sharding.param_shardings`; pass it through
    :func:`runbookai_tpu.models.quant.shardings_with_quant` when quantizing).
    ``quantize_int8`` converts the big layer matrices to int8 on the host so
    the bf16 tensors never reach device HBM (70B must load this way on v5e).
    """
    from runbookai_tpu.models.quant import LAYER_QUANT_KEYS, quantize_array_np

    model_dir = Path(model_dir)
    cfg = cfg or config_from_hf(model_dir)
    idx = _ShardIndex(model_dir)
    sh = shardings or {}

    def shard_of(*path):
        node: Any = sh
        for p in path:
            if not isinstance(node, dict) or p not in node:
                return None
            node = node[p]
        return node

    params: dict[str, Any] = {}
    params["embed"] = _put(
        idx.get("model.embed_tokens.weight"), dtype, shard_of("embed")
    )
    layers: dict[str, Any] = {}

    def store(leaf: str, stacked: np.ndarray) -> None:
        """Place one stacked leaf (quantizing the big matrices on request)."""
        if quantize_int8 and leaf in LAYER_QUANT_KEYS:
            q, s = quantize_array_np(stacked)
            leaf_sh = shard_of("layers", leaf)
            if not isinstance(leaf_sh, dict):
                leaf_sh = {"q": leaf_sh, "s": None}
            layers[leaf] = {
                "q": _put(q, jnp.int8, leaf_sh.get("q")),
                "s": _put(s, jnp.float32, leaf_sh.get("s")),
            }
            return
        leaf_dtype = jnp.float32 if leaf.endswith("norm") else dtype
        layers[leaf] = _put(stacked, leaf_dtype, shard_of("layers", leaf))

    layer_map = dict(_LAYER_MAP)
    if cfg.n_experts:
        for k in ("w_gate", "w_up", "w_down"):
            layer_map.pop(k)
    for leaf, (tmpl, transpose) in layer_map.items():
        mats = []
        for i in range(cfg.n_layers):
            w = idx.get(tmpl.format(i=i))
            mats.append(w.T if transpose else w)
        store(leaf, np.stack(mats))
    if cfg.n_experts:
        # Mixtral MoE FFN: experts stacked on a leading E axis per layer
        # (HF w1=gate, w3=up, w2=down, all [out, in] → transposed), plus
        # the router (never quantized — tiny and precision-critical).
        for leaf, part in (("w_gate", "w1"), ("w_up", "w3"), ("w_down", "w2")):
            tmpl = ("model.layers.{i}.block_sparse_moe.experts.{e}."
                    + part + ".weight")
            store(leaf, np.stack([
                np.stack([idx.get(tmpl.format(i=i, e=e)).T
                          for e in range(cfg.n_experts)])
                for i in range(cfg.n_layers)]))
        layers["router"] = _put(
            np.stack([idx.get(
                f"model.layers.{i}.block_sparse_moe.gate.weight").T
                for i in range(cfg.n_layers)]),
            dtype, shard_of("layers", "router"))
    if cfg.qkv_bias:
        for leaf, tmpl in _BIAS_MAP.items():
            stacked = np.stack([idx.get(tmpl.format(i=i))
                                for i in range(cfg.n_layers)])
            layers[leaf] = _put(stacked, dtype, shard_of("layers", leaf))
    params["layers"] = layers
    params["final_norm"] = _put(idx.get("model.norm.weight"), jnp.float32, shard_of("final_norm"))
    if not cfg.tie_embeddings:
        params["lm_head"] = _put(
            idx.get("lm_head.weight").T, dtype, shard_of("lm_head")
        )
    return cfg, params


def load_or_init(
    model_name: str,
    model_path: Optional[str | Path],
    dtype=jnp.bfloat16,
    shardings: Optional[dict[str, Any]] = None,
    seed: int = 0,
    quantize_int8: bool = False,
) -> tuple[LlamaConfig, Any]:
    """Load from ``model_path`` when present, else random-init ``model_name``.

    Random init keeps every serving path exercisable in the no-egress
    environment (BASELINE.md configs run with real weights when provided).
    """
    if model_path and Path(model_path).exists():
        from runbookai_tpu.models.checkpoint import is_checkpoint, load_checkpoint

        if is_checkpoint(model_path):
            # Orbax checkpoint (possibly pre-quantized): restores straight to
            # the sharded placement, no host-side safetensors pass.
            cfg, params = load_checkpoint(model_path, shardings=shardings, dtype=dtype)
            from runbookai_tpu.models.quant import is_quantized, quantize_params

            if quantize_int8 and not any(
                is_quantized(v) for v in params["layers"].values()
            ):
                params = quantize_params(params)
                if shardings:
                    params = jax.tree.map(
                        lambda x, s: jax.device_put(x, s) if s is not None else x,
                        params, shardings, is_leaf=lambda x: x is None)
            return cfg, params
        cfg = config_from_hf(model_path, name=model_name)
        return load_params(model_path, cfg, dtype=dtype, shardings=shardings,
                           quantize_int8=quantize_int8)
    cfg = CONFIGS[model_name] if model_name in CONFIGS else CONFIGS["llama3-test"]
    params = init_params(jax.random.PRNGKey(seed), cfg, dtype=dtype)
    if quantize_int8:
        from runbookai_tpu.models.quant import quantize_params

        params = quantize_params(params)
    if shardings:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s) if s is not None else x,
            params,
            shardings,
            is_leaf=lambda x: x is None,
        )
    return cfg, params
