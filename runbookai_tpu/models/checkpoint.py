"""Sharded model-weight checkpoints (orbax) — fast reload for serving.

The reference has no model weights at all (SURVEY.md §5.4: "model-weights
checkpointing does not exist; the TPU build needs weight loading — new
construction"). Loading 70B from HF safetensors and re-quantizing on every
boot costs minutes of host time; this module converts once and restores
directly to sharded device arrays:

    HF safetensors ──load_or_init(quantize_int8=...)──▶ params pytree
    params pytree  ──save_checkpoint──▶ orbax dir (config.json + pytree/)
    orbax dir      ──load_checkpoint(shardings=...)──▶ sharded device arrays

Quantized ``{"q": int8, "s": f32}`` leaves are plain arrays to orbax, so
int8 checkpoints round-trip unchanged. Restore places each leaf directly on
its TP shard (no full-host materialization) when ``shardings`` is given.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Optional

import jax

from runbookai_tpu.models.llama import CONFIGS, LlamaConfig

_CONFIG_FILE = "config.json"
_TREE_DIR = "pytree"


def save_checkpoint(path: str | Path, cfg: LlamaConfig, params: Any) -> Path:
    """Write ``config.json`` + the params pytree under ``path``."""
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    path.mkdir(parents=True, exist_ok=True)
    (path / _CONFIG_FILE).write_text(json.dumps(dataclasses.asdict(cfg), indent=2))
    ckptr = ocp.StandardCheckpointer()
    tree_path = path / _TREE_DIR
    ckptr.save(tree_path, params, force=True)
    ckptr.wait_until_finished()
    return path


def checkpoint_config(path: str | Path) -> LlamaConfig:
    data = json.loads((Path(path) / _CONFIG_FILE).read_text())
    # JSON round-trips tuples as lists; the config must stay hashable (it
    # is a static jit argument) and ==-comparable with the original.
    if data.get("rope_scaling") is not None:
        data["rope_scaling"] = tuple(data["rope_scaling"])
    return LlamaConfig(**data)


def is_checkpoint(path: Optional[str | Path]) -> bool:
    return bool(path) and (Path(path) / _CONFIG_FILE).is_file() \
        and (Path(path) / _TREE_DIR).exists()


def load_checkpoint(
    path: str | Path,
    shardings: Optional[Any] = None,
    dtype=None,
) -> tuple[LlamaConfig, Any]:
    """Restore ``(cfg, params)``; leaves land on their shards directly.

    ``shardings`` is the (possibly quant-expanded) ``param_shardings`` tree;
    missing/None entries restore unsharded. ``dtype`` optionally casts
    floating-point leaves on restore (int8 payloads are never cast).
    """
    import jax.numpy as jnp
    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    cfg = checkpoint_config(path)
    ckptr = ocp.StandardCheckpointer()
    meta = ckptr.metadata(path / _TREE_DIR).item_metadata.tree

    def spec_for(leaf_meta, sh):
        target_dtype = leaf_meta.dtype
        if (dtype is not None and jnp.issubdtype(target_dtype, jnp.floating)
                and target_dtype != jnp.float32):  # norms stay f32
            target_dtype = dtype
        return jax.ShapeDtypeStruct(leaf_meta.shape, target_dtype, sharding=sh)

    fallback = False
    if shardings is None:
        target = jax.tree.map(lambda m: spec_for(m, None), meta)
    else:
        try:
            target = jax.tree.map(spec_for, meta, shardings,
                                  is_leaf=lambda x: x is None)
        except ValueError:
            # Structure mismatch (e.g. quant-expanded shardings against an
            # unquantized checkpoint). Restoring the whole tree unsharded is
            # an OOM/perf cliff at 70B scale, so warn loudly and reshard
            # leaf-by-leaf after restore where specs still line up.
            import warnings
            warnings.warn(
                f"load_checkpoint({path}): shardings tree does not match the "
                "checkpoint structure; restoring unsharded and resharding "
                "matching leaves with device_put. Re-convert the checkpoint "
                "to silence this.", stacklevel=2)
            target = jax.tree.map(lambda m: spec_for(m, None), meta)
            fallback = True
    params = ckptr.restore(path / _TREE_DIR, target)
    if fallback:
        flat_sh = {tuple(map(str, p)): s for p, s in
                   jax.tree_util.tree_flatten_with_path(
                       shardings, is_leaf=lambda x: x is None)[0] if s is not None}
        flat_pm = jax.tree_util.tree_flatten_with_path(params)[0]
        moved = {tuple(map(str, p)): jax.device_put(v, flat_sh[tuple(map(str, p))])
                 for p, v in flat_pm if tuple(map(str, p)) in flat_sh}
        params = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params),
            [moved.get(tuple(map(str, p)), v) for p, v in flat_pm])
    return cfg, params


def convert_hf_to_checkpoint(
    model_path: str | Path,
    out_path: str | Path,
    model_name: str = "hf-model",
    quantize_int8: bool = False,
    dtype=None,
    allow_random_init: bool = False,
) -> Path:
    """One-time conversion: HF safetensors → (optionally int8) orbax dir.

    Raises ``FileNotFoundError`` for a missing ``model_path`` — falling
    through to random init here would write a valid-looking checkpoint of
    garbage weights with no error. ``allow_random_init=True`` opts into
    that fallback explicitly (CI / no-egress smoke checkpoints).
    """
    import jax.numpy as jnp

    from runbookai_tpu.models.hf_loader import load_or_init

    if not Path(model_path).exists() and not allow_random_init:
        raise FileNotFoundError(
            f"weights convert: model_path does not exist: {model_path} "
            "(pass --random-init to write a random-weights checkpoint)")

    cfg, params = load_or_init(
        model_name if model_name in CONFIGS else "hf-model",
        model_path, dtype=dtype or jnp.bfloat16, quantize_int8=quantize_int8,
    )
    return save_checkpoint(out_path, cfg, params)
