"""Multi-LoRA serving: per-request low-rank adapters batched in one engine.

TPU-first shape discipline: ALL registered adapters live in one stacked
pytree under ``params["lora"]`` —

    {"wq": {"A": [L, N, D, r], "B": [L, N, r, out]}, "wv": {...}, ...}

— so the scan-stacked forward carries them like any other layer leaf, and a
single compiled program serves every adapter mix: each decode/prefill
dispatch passes ``adapter_ids [batch]`` and the layer body gathers that
row's A/B before two small einsums (rank r ≈ 8–64, negligible FLOPs next
to the base matmul). Adapter index 0 is RESERVED as the zero adapter (A=0,
B=0): requests without an adapter select it and get exactly the base
model, so the no-LoRA fast path needs no branch.

``alpha/r`` scaling is baked into B at registration time. Adapters load
from HF PEFT directories (``adapter_config.json`` +
``adapter_model.safetensors``). No reference counterpart (hosted APIs);
parity target is the multi-LoRA feature of vLLM-class serving frameworks.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from runbookai_tpu.models.llama import LlamaConfig

# Projections LoRA can target, with their output widths.
_TARGET_OUT = {
    "wq": lambda cfg: cfg.n_heads * cfg.head_dim,
    "wk": lambda cfg: cfg.n_kv_heads * cfg.head_dim,
    "wv": lambda cfg: cfg.n_kv_heads * cfg.head_dim,
    "wo": lambda cfg: cfg.dim,
}
# HF PEFT module names -> our leaves.
_PEFT_NAMES = {"q_proj": "wq", "k_proj": "wk", "v_proj": "wv", "o_proj": "wo"}


def _target_in_dim(cfg: LlamaConfig, leaf: str) -> int:
    return cfg.n_heads * cfg.head_dim if leaf == "wo" else cfg.dim


class LoraRegistry:
    """Name -> adapter index; owns the stacked adapter pytree.

    Registration re-stacks the (host-side) arrays — it happens once per
    adapter at startup, while the hot path only ever gathers rows.
    """

    def __init__(self, cfg: LlamaConfig, rank: int = 8,
                 targets: tuple[str, ...] = ("wq", "wv"),
                 dtype=jnp.bfloat16):
        if not targets:
            raise ValueError("LoRA targets must be non-empty (empty targets "
                             "would silently alias every adapter to the "
                             "reserved base row)")
        for t in targets:
            if t not in _TARGET_OUT:
                raise ValueError(f"unsupported LoRA target {t!r}")
        self.cfg = cfg
        self.rank = rank
        self.targets = tuple(targets)
        self.dtype = dtype
        self._names: dict[str, int] = {}
        # Hot-loading mutates the registry from HTTP handler threads while
        # the engine reads it — one lock covers every mutation + stack.
        self._mutex = threading.Lock()
        L = cfg.n_layers
        # index 0 = the zero adapter (base model).
        self._host: dict[str, dict[str, list[np.ndarray]]] = {
            t: {"A": [np.zeros((L, _target_in_dim(cfg, t), rank), np.float32)],
                "B": [np.zeros((L, rank, _TARGET_OUT[t](cfg)), np.float32)]}
            for t in targets
        }
        self._stacked: Optional[dict[str, dict[str, jnp.ndarray]]] = None

    # ------------------------------------------------------------- queries

    @property
    def n_adapters(self) -> int:
        """Including the reserved zero adapter at index 0."""
        return len(next(iter(self._host.values()))["A"]) if self._host else 1

    def index_of(self, name: Optional[str]) -> int:
        if name is None:
            return 0
        if name not in self._names:
            raise KeyError(
                f"unknown LoRA adapter {name!r}; loaded: {sorted(self._names)}")
        return self._names[name]

    @property
    def names(self) -> list[str]:
        return sorted(self._names)

    # -------------------------------------------------------- registration

    def register(self, name: str, weights: dict[str, dict[str, np.ndarray]],
                 alpha: Optional[float] = None) -> int:
        """Add an adapter. ``weights[leaf] = {"A": [L, in, r], "B": [L, r, out]}``
        (missing targets act as zero). ``alpha/r`` scaling folds into B."""
        with self._mutex:
            return self._register_locked(name, weights, alpha)

    def _register_locked(self, name, weights, alpha) -> int:
        if name in self._names:
            raise ValueError(f"adapter {name!r} already registered")
        scale = (alpha / self.rank) if alpha is not None else 1.0
        L = self.cfg.n_layers
        # Validate EVERY target before appending ANY row: a mid-loop shape
        # failure must not leave earlier targets with an extra row (the
        # per-target row counts would diverge and jit-time gather clamping
        # would then silently serve the wrong adapter).
        staged: list[tuple[str, np.ndarray, np.ndarray]] = []
        for t in self.targets:
            if t in weights:
                a = np.asarray(weights[t]["A"], np.float32)
                b = np.asarray(weights[t]["B"], np.float32) * scale
                want_a = (L, _target_in_dim(self.cfg, t), self.rank)
                want_b = (L, self.rank, _TARGET_OUT[t](self.cfg))
                if a.shape != want_a or b.shape != want_b:
                    raise ValueError(
                        f"{name}/{t}: A{a.shape}/B{b.shape} != "
                        f"{want_a}/{want_b}")
            else:
                a = np.zeros((L, _target_in_dim(self.cfg, t), self.rank),
                             np.float32)
                b = np.zeros((L, self.rank, _TARGET_OUT[t](self.cfg)),
                             np.float32)
            staged.append((t, a, b))
        for t, a, b in staged:
            self._host[t]["A"].append(a)
            self._host[t]["B"].append(b)
        idx = self.n_adapters - 1
        self._names[name] = idx
        self._stacked = None  # re-stack lazily
        return idx

    def update_adapter(self, name: str,
                       weights: dict[str, dict[str, np.ndarray]]) -> None:
        """Replace ONE adapter's weights in place (float32 host invariant)
        — the write-back path for a fine-tuned adapter. Other rows are
        untouched, so concurrent trainers/registrations can't clobber each
        other through a stale full-tree snapshot."""
        with self._mutex:
            idx = self.index_of(name)
            for t in self.targets:
                if t in weights:
                    self._host[t]["A"][idx] = np.asarray(weights[t]["A"],
                                                         np.float32)
                    self._host[t]["B"][idx] = np.asarray(weights[t]["B"],
                                                         np.float32)
            self._stacked = None

    def load_peft_dir(self, name: str, adapter_dir: str | Path) -> int:
        """Register an HF PEFT adapter directory (safetensors)."""
        from safetensors import safe_open

        adapter_dir = Path(adapter_dir)
        acfg = json.loads((adapter_dir / "adapter_config.json").read_text())
        if int(acfg.get("r", self.rank)) != self.rank:
            raise ValueError(
                f"adapter rank {acfg.get('r')} != registry rank {self.rank}")
        # Serving an adapter with some of its deltas dropped would silently
        # degrade outputs — refuse modules the registry doesn't cover.
        declared = set(acfg.get("target_modules") or [])
        covered = {p for p, leaf in _PEFT_NAMES.items()
                   if leaf in self.targets}
        uncovered = declared - covered
        if uncovered:
            raise ValueError(
                f"adapter targets {sorted(uncovered)} not covered by "
                f"registry targets {self.targets} — refusing a partial "
                f"adapter")
        alpha = float(acfg.get("lora_alpha", self.rank))
        f = safe_open(str(adapter_dir / "adapter_model.safetensors"),
                      framework="numpy")
        keys = list(f.keys())
        weights: dict[str, dict[str, list]] = {}
        L = self.cfg.n_layers
        for peft_name, leaf in _PEFT_NAMES.items():
            if leaf not in self.targets:
                continue
            a_layers, b_layers = [], []
            for i in range(L):
                a_key = next((k for k in keys
                              if f"layers.{i}.self_attn.{peft_name}.lora_A" in k),
                             None)
                if a_key is None:
                    break
                b_key = next(k for k in keys
                             if f"layers.{i}.self_attn.{peft_name}.lora_B" in k)
                # PEFT stores [r, in] and [out, r]; ours are [in, r]/[r, out].
                a_layers.append(f.get_tensor(a_key).T)
                b_layers.append(f.get_tensor(b_key).T)
            if a_layers:
                if len(a_layers) != L:
                    raise ValueError(
                        f"{name}/{leaf}: adapter covers {len(a_layers)} of "
                        f"{L} layers")
                weights[leaf] = {"A": np.stack(a_layers),
                                 "B": np.stack(b_layers)}
        return self.register(name, weights, alpha=alpha)

    # ------------------------------------------------------------ the tree

    def stacked(self) -> dict[str, dict[str, jnp.ndarray]]:
        """Device pytree ``{leaf: {"A": [L, N, in, r], "B": [L, N, r, out]}}``
        (layer axis LEADING so it scans with the other layer leaves)."""
        with self._mutex:
            if self._stacked is None:
                self._stacked = {
                    t: {"A": jnp.asarray(np.stack(self._host[t]["A"], axis=1),
                                         self.dtype),
                        "B": jnp.asarray(np.stack(self._host[t]["B"], axis=1),
                                         self.dtype)}
                    for t in self.targets
                }
            return self._stacked


def apply_lora(x: jnp.ndarray, lp_lora: dict, leaf: str,
               adapter_ids: jnp.ndarray) -> jnp.ndarray:
    """Per-row adapter contribution for one layer: ``x [B, T, in]`` ->
    ``[B, T, out]``. ``lp_lora[leaf] = {"A": [N, in, r], "B": [N, r, out]}``
    (the layer axis was consumed by the scan); rows gather their adapter."""
    if lp_lora is None or leaf not in lp_lora:
        return 0.0
    a = lp_lora[leaf]["A"][adapter_ids]  # [B, in, r]
    b = lp_lora[leaf]["B"][adapter_ids]  # [B, r, out]
    low = jnp.einsum("bti,bir->btr", x, a.astype(x.dtype))
    return jnp.einsum("btr,bro->bto", low, b.astype(x.dtype))
