"""Llama-3 family in JAX — pure-functional, scan-stacked, paged-KV native.

Design (TPU-first, no reference counterpart — RunbookAI calls hosted APIs):

- Params are a plain pytree with all transformer layers **stacked on a leading
  axis** and the forward pass runs ``lax.scan`` over them: one compiled layer
  body regardless of depth (32/80 layers), which keeps XLA compile times flat
  and makes TP sharding specs uniform.
- A single forward covers chunked prefill and decode (decode is T=1): the
  chunk's K/V are scattered into the paged pool, then queries attend over the
  pool via :func:`runbookai_tpu.ops.attention.paged_attention`.
- GQA (n_kv_heads < n_heads), RMSNorm in float32, bf16 weights by default,
  logits in float32 for stable sampling/grammar masking.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from runbookai_tpu.ops.attention import paged_attention, write_kv_pages_batch
from runbookai_tpu.ops.rope import apply_rope

Params = dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    ffn_dim: int
    rope_theta: float = 500_000.0
    # Llama-3.1 long-context rope scaling (NTK-by-parts): tuple
    # (factor, low_freq_factor, high_freq_factor, original_max_pos) or
    # None. Set from HF config.json's rope_scaling (rope_type "llama3").
    rope_scaling: Optional[tuple] = None
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # Qwen2-family attention: biases on the q/k/v projections only (HF
    # ``Qwen2Attention``); Llama/Mistral run bias-free. The scan-stacked
    # layer dict simply carries three extra [L, heads*hd] leaves.
    qkv_bias: bool = False
    # Model family ("llama" | "qwen2" | "mistral" | "mixtral") — drives the
    # chat template. Set from HF config.json's authoritative ``model_type``
    # by the loader; name sniffing is only the fallback for bare names.
    family: str = "llama"
    # Mixture-of-Experts (Mixtral): 0 = dense FFN. When > 0 the FFN leaves
    # gain a leading expert axis ([L, E, D, F]) plus a router [L, D, E],
    # and the block runs :func:`runbookai_tpu.ops.moe.moe_ffn`. Expert
    # parallelism shards the E axis over the mesh's model axis.
    n_experts: int = 0
    top_k_experts: int = 2
    # Per-expert queue headroom. 0 (default) = dropless: capacity N, exact
    # Mixtral/transformers numerics at E× the buffer cost. Perf-tuned
    # serving can trade exactness for smaller dispatch buffers by setting
    # e.g. 1.25–2.0 (token-expert assignments past the capacity drop).
    capacity_factor: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def matmul_params(self) -> int:
        """Analytic count of params that participate in matmuls *per token*
        (layer projections + LM head; excludes the embedding gather) — the
        ``N`` in the decode-FLOPs model ``2·N`` used for MFU reporting. For
        MoE this counts the ``top_k`` ACTIVE experts (the FLOPs actually
        spent per token), not the full expert bank."""
        D, hd = self.dim, self.head_dim
        ffn_mult = self.top_k_experts if self.n_experts else 1
        per_layer = (
            D * self.n_heads * hd          # wq
            + 2 * D * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * D         # wo
            + ffn_mult * 3 * D * self.ffn_dim  # active FFN experts
            + (D * self.n_experts if self.n_experts else 0)  # router
        )
        return self.n_layers * per_layer + D * self.vocab_size

    @property
    def total_params(self) -> int:
        """All weights, including every expert (the memory-side count)."""
        D = self.dim
        embed = self.vocab_size * D * (1 if self.tie_embeddings else 2)
        norms = self.n_layers * 2 * D + D
        ffn_mult = self.n_experts if self.n_experts else 1
        ffn_delta = (ffn_mult - (self.top_k_experts if self.n_experts else 1)
                     ) * 3 * D * self.ffn_dim * self.n_layers
        return (self.matmul_params - D * self.vocab_size + embed + norms
                + ffn_delta)


CONFIGS: dict[str, LlamaConfig] = {
    "llama3-8b-instruct": LlamaConfig(
        name="llama3-8b-instruct", vocab_size=128_256, dim=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, ffn_dim=14_336,
    ),
    "llama3-70b-instruct": LlamaConfig(
        name="llama3-70b-instruct", vocab_size=128_256, dim=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, ffn_dim=28_672,
    ),
    "llama3-1b-bench": LlamaConfig(
        # Small-dim stand-in for quick single-chip bench sanity runs.
        name="llama3-1b-bench", vocab_size=128_256, dim=2048, n_layers=16,
        n_heads=32, n_kv_heads=8, ffn_dim=8192,
    ),
    # Llama-3.1/3.2: same blocks with NTK-by-parts rope scaling for 128k
    # contexts; 3.2 ties embeddings. (8B dims match llama3-8b.)
    "llama3.1-8b-instruct": LlamaConfig(
        name="llama3.1-8b-instruct", vocab_size=128_256, dim=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14_336,
        max_seq_len=131_072, rope_scaling=(8.0, 1.0, 4.0, 8192),
    ),
    "llama3.1-70b-instruct": LlamaConfig(
        name="llama3.1-70b-instruct", vocab_size=128_256, dim=8192,
        n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28_672,
        max_seq_len=131_072, rope_scaling=(8.0, 1.0, 4.0, 8192),
    ),
    # Llama-3.3-70B ships the 3.1-70B architecture exactly (dims, rope
    # scaling, 128k window) — served under its own name for HF parity.
    "llama3.3-70b-instruct": LlamaConfig(
        name="llama3.3-70b-instruct", vocab_size=128_256, dim=8192,
        n_layers=80, n_heads=64, n_kv_heads=8, ffn_dim=28_672,
        max_seq_len=131_072, rope_scaling=(8.0, 1.0, 4.0, 8192),
    ),
    "llama3.2-1b-instruct": LlamaConfig(
        name="llama3.2-1b-instruct", vocab_size=128_256, dim=2048,
        n_layers=16, n_heads=32, n_kv_heads=8, ffn_dim=8192,
        max_seq_len=131_072, rope_scaling=(32.0, 1.0, 4.0, 8192),
        tie_embeddings=True,
    ),
    "llama3.2-3b-instruct": LlamaConfig(
        name="llama3.2-3b-instruct", vocab_size=128_256, dim=3072,
        n_layers=28, n_heads=24, n_kv_heads=8, ffn_dim=8192,
        max_seq_len=131_072, rope_scaling=(32.0, 1.0, 4.0, 8192),
        tie_embeddings=True,
    ),
    "llama3-test": LlamaConfig(
        # Tiny config for CPU tests; vocab matches the byte tokenizer (262).
        # max_seq_len covers real agent/orchestrator prompts (byte tokenizer:
        # 1 token per byte), so live-eval e2e runs fit without truncation.
        name="llama3-test", vocab_size=262, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, ffn_dim=128, max_seq_len=8192, rope_theta=10_000.0,
    ),
    # Qwen2 family: identical block structure with q/k/v projection biases
    # and ChatML prompts (HF ``Qwen2ForCausalLM``; config.json model_type
    # "qwen2"). Serving/training/TP paths are shared with Llama.
    "qwen2-7b-instruct": LlamaConfig(
        name="qwen2-7b-instruct", vocab_size=152_064, dim=3584, n_layers=28,
        n_heads=28, n_kv_heads=4, ffn_dim=18_944, rope_theta=1_000_000.0,
        max_seq_len=32_768, qkv_bias=True, family="qwen2",
    ),
    # Qwen2.5-7B ships the same architecture/dims as Qwen2-7B (vocab,
    # qkv biases, theta) — served under its own name for HF parity.
    "qwen2.5-7b-instruct": LlamaConfig(
        name="qwen2.5-7b-instruct", vocab_size=152_064, dim=3584,
        n_layers=28, n_heads=28, n_kv_heads=4, ffn_dim=18_944,
        rope_theta=1_000_000.0, max_seq_len=32_768, qkv_bias=True,
        family="qwen2",
    ),
    "qwen2.5-14b-instruct": LlamaConfig(
        name="qwen2.5-14b-instruct", vocab_size=152_064, dim=5120,
        n_layers=48, n_heads=40, n_kv_heads=8, ffn_dim=13_824,
        rope_theta=1_000_000.0, max_seq_len=32_768, qkv_bias=True,
        family="qwen2",
    ),
    "qwen2.5-32b-instruct": LlamaConfig(
        name="qwen2.5-32b-instruct", vocab_size=152_064, dim=5120,
        n_layers=64, n_heads=40, n_kv_heads=8, ffn_dim=27_648,
        rope_theta=1_000_000.0, max_seq_len=32_768, qkv_bias=True,
        family="qwen2",
    ),
    "qwen2-test": LlamaConfig(
        name="qwen2-test", vocab_size=262, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, ffn_dim=128, max_seq_len=8192, rope_theta=10_000.0,
        qkv_bias=True, family="qwen2",
    ),
    # Mistral v0.3: Llama block structure exactly (GQA, no bias), different
    # dims/vocab/theta. Sliding-window variants (v0.1) are served with full
    # attention — exact for contexts ≤ the window (4096).
    "mistral-7b-instruct": LlamaConfig(
        name="mistral-7b-instruct", vocab_size=32_768, dim=4096, n_layers=32,
        n_heads=32, n_kv_heads=8, ffn_dim=14_336, rope_theta=1_000_000.0,
        max_seq_len=32_768, family="mistral",
    ),
    # Mixtral 8x7B: Mistral attention + 8-expert top-2 MoE FFN. Serving on
    # v5e needs int8 + TP/EP (47B total params); the test config exercises
    # the identical code path on CPU.
    "mixtral-8x7b-instruct": LlamaConfig(
        name="mixtral-8x7b-instruct", vocab_size=32_000, dim=4096,
        n_layers=32, n_heads=32, n_kv_heads=8, ffn_dim=14_336,
        rope_theta=1_000_000.0, max_seq_len=32_768, family="mixtral",
        n_experts=8, top_k_experts=2,
    ),
    "mixtral-test": LlamaConfig(
        name="mixtral-test", vocab_size=262, dim=64, n_layers=2, n_heads=4,
        n_kv_heads=2, ffn_dim=128, max_seq_len=8192, rope_theta=10_000.0,
        family="mixtral", n_experts=4, top_k_experts=2,
    ),
}


def get_config(name: str) -> LlamaConfig:
    if name not in CONFIGS:
        raise KeyError(f"Unknown model {name!r}; known: {sorted(CONFIGS)}")
    return CONFIGS[name]


def _layer_shapes(cfg: LlamaConfig) -> dict[str, tuple[tuple[int, ...], int]]:
    """The stacked layer matrices as ``name -> (shape, fan_in)`` — the
    single source of truth shared by the bf16 and direct-int8 inits. MoE
    configs put a leading expert axis on the FFN leaves (+ a router, which
    stays un-quantized — it's tiny and routing is precision-critical)."""
    L, D, KV, F = cfg.n_layers, cfg.dim, cfg.n_kv_heads, cfg.ffn_dim
    H, hd = cfg.n_heads, cfg.head_dim
    shapes = {
        "wq": ((L, D, H * hd), D),
        "wk": ((L, D, KV * hd), D),
        "wv": ((L, D, KV * hd), D),
        "wo": ((L, H * hd, D), H * hd),
    }
    if cfg.n_experts:
        E = cfg.n_experts
        shapes.update({
            "w_gate": ((L, E, D, F), D),
            "w_up": ((L, E, D, F), D),
            "w_down": ((L, E, F, D), F),
            "router": ((L, D, E), D),
        })
    else:
        shapes.update({
            "w_gate": ((L, D, F), D),
            "w_up": ((L, D, F), D),
            "w_down": ((L, F, D), F),
        })
    return shapes


def _build_params(key: jax.Array, cfg: LlamaConfig, dtype,
                  layer_factory=None) -> Params:
    """Shared init skeleton; ``layer_factory(key, shape, fan_in)`` makes the
    seven stacked layer matrices (default: the same scaled-normal ``dense``
    used for embed/lm_head; the int8 init passes ``qdense``)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    L, D = cfg.n_layers, cfg.dim

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                / jnp.sqrt(fan_in)).astype(dtype)

    if layer_factory is None:
        layer_factory = dense
    shapes = _layer_shapes(cfg)
    ks = jax.random.split(k_layers, len(shapes))
    layers: dict[str, Any] = {
        # The router stays in the dense dtype even under int8 init —
        # routing logits are precision-critical and the tensor is tiny.
        name: (dense if name == "router" else layer_factory)(k, shape, fan_in)
        for k, (name, (shape, fan_in)) in zip(ks, shapes.items())
    }
    layers["attn_norm"] = jnp.ones((L, D), dtype=jnp.float32)
    layers["mlp_norm"] = jnp.ones((L, D), dtype=jnp.float32)
    if cfg.qkv_bias:
        hd = cfg.head_dim
        layers["bq"] = jnp.zeros((L, cfg.n_heads * hd), dtype=dtype)
        layers["bk"] = jnp.zeros((L, cfg.n_kv_heads * hd), dtype=dtype)
        layers["bv"] = jnp.zeros((L, cfg.n_kv_heads * hd), dtype=dtype)
    params: Params = {
        "embed": dense(k_embed, (cfg.vocab_size, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dtype=jnp.float32),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (D, cfg.vocab_size), D)
    return params


def init_params(key: jax.Array, cfg: LlamaConfig, dtype=jnp.bfloat16) -> Params:
    """Random-init params (scaled normal). Layer weights stacked on axis 0."""
    return _build_params(key, cfg, dtype)


def init_params_quantized(key: jax.Array, cfg: LlamaConfig,
                          dtype=jnp.bfloat16) -> Params:
    """Random-init params with the seven layer matrices directly in int8.

    For big-model benchmarking on one chip: 8B bf16 is ~16GB and cannot be
    materialized then quantized on a 16GB-HBM v5e. Sampling ``q`` uniform
    int8 with a per-channel scale chosen so the dequantized std matches the
    scaled-normal init (1/sqrt(fan_in)) gives the same matmul cost and
    magnitude as quantizing real weights, without the bf16 intermediate.
    Leaves match :mod:`runbookai_tpu.models.quant` (``{"q": int8, "s": f32}``).
    """

    def qdense(key, shape, fan_in):
        q = jax.random.randint(key, shape, -127, 128, dtype=jnp.int8)
        # uniform[-127,127] has std 127/sqrt(3); scale to std 1/sqrt(fan_in)
        scale = float(3 ** 0.5 / (127.0 * fan_in ** 0.5))
        s = jnp.full(shape[:-2] + (1, shape[-1]), scale, dtype=jnp.float32)
        return {"q": q, "s": s}

    return _build_params(key, cfg, dtype, qdense)


def qmm(x: jnp.ndarray, w: Any, impl: str = "xla") -> jnp.ndarray:
    """Matmul that accepts int8 weight-only quantized weights.

    Quantized leaves are ``{"q": int8 [.., in, out], "s": f32 [.., 1, out]}``
    (:mod:`runbookai_tpu.models.quant`). The matmul runs on the MXU in the
    activation dtype (int8→bf16 cast is exact) and the per-output-channel
    scale applies to the result — identical math to dequantize-first, since
    the scale is constant along the contraction.

    ``impl="pallas"`` streams the int8 tiles through the Pallas kernel
    (:mod:`runbookai_tpu.ops.qmm_pallas`) at decode/verify shapes — the
    convert happens in-register, so HBM moves half the bf16 bytes by
    construction instead of by fusion luck. Shapes the kernel does not
    cover (chunked prefill M, ragged dims, unquantized leaves) fall back
    to the XLA expression below, same math.
    """
    if isinstance(w, dict):
        if impl == "pallas" and w["q"].ndim == 2:
            from runbookai_tpu.ops.qmm_pallas import (
                qmm_pallas,
                qmm_pallas_eligible,
            )

            lead = x.shape[:-1]
            k_dim, n = w["q"].shape
            if qmm_pallas_eligible(math.prod(lead), k_dim, n):
                out = qmm_pallas(
                    x.reshape(-1, k_dim), w["q"], w["s"].reshape(1, n),
                    interpret=jax.default_backend() == "cpu",
                )
                return out.reshape(*lead, n)
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def ffn_block(y: jnp.ndarray, lp: dict, cfg: LlamaConfig,
              qmm_impl: str = "xla") -> jnp.ndarray:
    """SwiGLU FFN (dense) or Mixtral MoE, by config — shared by the paged
    serving forward, the dense training forward, and the pipeline stages.
    Residual is added by the caller."""
    if cfg.n_experts:
        from runbookai_tpu.ops.moe import moe_ffn

        return moe_ffn(y, lp["router"], lp["w_gate"], lp["w_up"],
                       lp["w_down"], cfg.top_k_experts, cfg.capacity_factor)
    mm = partial(qmm, impl=qmm_impl)
    return mm(jax.nn.silu(mm(y, lp["w_gate"])) * mm(y, lp["w_up"]),
              lp["w_down"])


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    norm = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * weight).astype(x.dtype)


def _forward_hidden(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T] int32 token ids for the current chunk
    positions: jnp.ndarray,  # [B, T] absolute positions (pad with pos of last real)
    kv_k: jnp.ndarray,  # [n_layers, num_pages * page_size, n_kv, head_dim]
    kv_v: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [B, max_pages]
    ctx_lens: jnp.ndarray,  # [B] cache length AFTER this chunk
    page_size: int,
    block_pages: int = 32,
    attn_impl: str = "xla",
    mesh=None,
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32 LoRA rows
    qmm_impl: str = "xla",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Transformer stack over one paged chunk, WITHOUT the LM head.

    Returns (hidden [B, T, D], kv_k', kv_v'). Shared by
    :func:`forward_impl` (full [B, T, vocab] logits) and
    :func:`forward_ragged_impl` (mixed prefill+decode batches, which gather
    the few rows they need before paying for the vocab projection).
    """
    b, t = tokens.shape
    hd, n_kv = cfg.head_dim, cfg.n_kv_heads
    h = params["embed"][tokens]  # [B, T, D]
    lora = params.get("lora")  # {leaf: {"A": [L,N,in,r], "B": [L,N,r,out]}}
    if lora is not None and adapter_ids is None:
        adapter_ids = jnp.zeros((b,), jnp.int32)  # zero adapter = base

    if lora is not None:
        from runbookai_tpu.models.lora import apply_lora  # deferred: cycle

    # KV page-split serving (parallel/kv_split.py): a serving mesh with a
    # seq axis shards the page pool's token axis past the GQA head count;
    # page writes and attention then run as shard_map with a flash-partial
    # merge across the seq axis.
    kv_split_active = False
    if mesh is not None:
        from runbookai_tpu.parallel.mesh import SEQ_AXIS

        kv_split_active = mesh.shape.get(SEQ_AXIS, 1) > 1
    # int8 KV pools are (values, scales) tuples — XLA gather path only.
    # Checked BEFORE any page write: the kv-split writer has no scale
    # plumbing and would fail opaquely on a tuple mid-scan. (The engine
    # refuses this combination at init; this covers direct callers.)
    kv_quantized = isinstance(kv_k, tuple)
    if kv_quantized and kv_split_active:
        raise ValueError("int8 KV is not supported with the KV "
                         "page-split mesh")

    # The Pallas qmm runs per-device code; under a TP mesh the layer
    # matmuls are partitioned by XLA SPMD (sharding annotations, not
    # shard_map), so the kernel path is single-model-shard only. DP-only
    # meshes keep it: the weights are replicated per device.
    if qmm_impl == "pallas" and mesh is not None:
        from runbookai_tpu.parallel.mesh import MODEL_AXIS

        if mesh.shape.get(MODEL_AXIS, 1) > 1 or kv_split_active:
            qmm_impl = "xla"
    mm = partial(qmm, impl=qmm_impl)

    def layer_step(hidden, layer_in):
        lp, lp_lora, k_pages, v_pages = layer_in
        x = rms_norm(hidden, lp["attn_norm"], cfg.norm_eps)
        q, k, v = mm(x, lp["wq"]), mm(x, lp["wk"]), mm(x, lp["wv"])
        if lp_lora is not None:
            q = q + apply_lora(x, lp_lora, "wq", adapter_ids)
            k = k + apply_lora(x, lp_lora, "wk", adapter_ids)
            v = v + apply_lora(x, lp_lora, "wv", adapter_ids)
        if cfg.qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(b, t, cfg.n_heads, hd)
        k = k.reshape(b, t, n_kv, hd)
        v = v.reshape(b, t, n_kv, hd)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)

        # Scatter the whole batch's K/V into the page pool in one scatter
        # (program size stays flat as max_batch_slots grows; disjoint page
        # ownership makes flattened destinations collision-free).
        if kv_split_active:
            from runbookai_tpu.parallel.kv_split import (
                write_kv_pages_batch_kv_split,
            )

            k_pages = write_kv_pages_batch_kv_split(
                mesh, k_pages, k, positions, page_tables, page_size)
            v_pages = write_kv_pages_batch_kv_split(
                mesh, v_pages, v, positions, page_tables, page_size)
        else:
            k_pages = write_kv_pages_batch(k_pages, k, positions,
                                           page_tables, page_size)
            v_pages = write_kv_pages_batch(v_pages, v, positions,
                                           page_tables, page_size)

        # int8 pools: the decode kernel reads int8 pages + scales
        # directly (widened in VMEM); chunked prefill is compute-bound
        # and stays on the XLA gather path; the per-head-shard shard_map
        # path has no scale plumbing (mesh model>1 falls back below).
        use_pallas = (attn_impl == "pallas" and not kv_split_active
                      and (not kv_quantized or t == 1))
        shardable = False
        if use_pallas and kv_quantized and mesh is not None:
            from runbookai_tpu.parallel.mesh import MODEL_AXIS

            if mesh.shape.get(MODEL_AXIS, 1) > 1:
                use_pallas = False
        elif use_pallas and mesh is not None:
            from runbookai_tpu.ops.paged_attention_pallas import tp_shardable
            from runbookai_tpu.parallel.mesh import MODEL_AXIS

            # On a TP mesh the kernel must run per head-shard (shard_map);
            # when GQA kv heads don't divide the axis the pool replicates
            # (kv_pool_sharding) and the XLA gather path is the honest
            # fallback rather than an implicit every-step all-gather.
            shardable = tp_shardable(mesh, n_kv)
            if mesh.shape.get(MODEL_AXIS, 1) > 1 and not shardable:
                use_pallas = False
        if use_pallas:
            from runbookai_tpu.ops.paged_attention_pallas import (
                paged_chunk_attention,
                paged_chunk_attention_tp,
                paged_decode_attention,
                paged_decode_attention_tp,
            )

            # Interpret mode on CPU keeps the kernel path testable on the
            # virtual mesh; on TPU this compiles under Mosaic.
            interp = jax.default_backend() == "cpu"
            if shardable:
                if t == 1:
                    attn = paged_decode_attention_tp(
                        mesh, q[:, 0], k_pages, v_pages, page_tables,
                        ctx_lens, page_size=page_size, interpret=interp,
                    )[:, None]
                else:
                    attn = paged_chunk_attention_tp(
                        mesh, q, k_pages, v_pages, page_tables, ctx_lens,
                        positions, page_size=page_size, interpret=interp,
                    )
            elif t == 1:
                attn = paged_decode_attention(
                    q[:, 0], k_pages, v_pages, page_tables, ctx_lens,
                    page_size=page_size, interpret=interp,
                )[:, None]
            else:
                attn = paged_chunk_attention(
                    q, k_pages, v_pages, page_tables, ctx_lens, positions,
                    page_size=page_size, interpret=interp,
                )
        elif kv_split_active:
            from runbookai_tpu.parallel.kv_split import (
                paged_attention_kv_split,
                paged_decode_attention_kv_split_pallas,
            )

            if attn_impl == "pallas" and t == 1:
                # Decode hot loop on the Pallas partial kernel (ownership-
                # masked local pages + seq-axis flash merge); chunked
                # prefill stays on the XLA kv-split path (compute-bound).
                attn = paged_decode_attention_kv_split_pallas(
                    mesh, q[:, 0], k_pages, v_pages, page_tables, ctx_lens,
                    page_size=page_size,
                    interpret=jax.default_backend() == "cpu")[:, None]
            else:
                attn = paged_attention_kv_split(
                    mesh, q, k_pages, v_pages, page_tables, ctx_lens,
                    positions, page_size=page_size, block_pages=block_pages)
        else:
            attn = paged_attention(
                q, k_pages, v_pages, page_tables, ctx_lens, positions,
                page_size=page_size, block_pages=block_pages,
            )
        ctx = attn.reshape(b, t, cfg.n_heads * hd)
        o = mm(ctx, lp["wo"])
        if lp_lora is not None:
            o = o + apply_lora(ctx, lp_lora, "wo", adapter_ids)
        hidden = hidden + o

        y = rms_norm(hidden, lp["mlp_norm"], cfg.norm_eps)
        hidden = hidden + ffn_block(y, lp, cfg, qmm_impl=qmm_impl)
        return hidden, (k_pages, v_pages)

    h, (kv_k_new, kv_v_new) = jax.lax.scan(
        layer_step, h, (params["layers"], lora, kv_k, kv_v)
    )
    return h, kv_k_new, kv_v_new


def forward_impl(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T] int32 token ids for the current chunk
    positions: jnp.ndarray,  # [B, T] absolute positions (pad with pos of last real)
    kv_k: jnp.ndarray,  # [n_layers, num_pages * page_size, n_kv, head_dim]
    kv_v: jnp.ndarray,  # same
    page_tables: jnp.ndarray,  # [B, max_pages]
    ctx_lens: jnp.ndarray,  # [B] cache length AFTER this chunk
    page_size: int,
    block_pages: int = 32,
    attn_impl: str = "xla",
    mesh=None,
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] int32 LoRA rows
    qmm_impl: str = "xla",
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One forward chunk. Returns (logits [B, T, vocab] f32, kv_k', kv_v').

    Raw (un-jitted) implementation so callers can inline it inside their own
    compiled step functions — nested jit inside lax.scan hangs some remote
    compile backends. ``attn_impl="pallas"`` selects the Pallas ragged paged
    decode kernel when T == 1; with a TP ``mesh`` the kernel runs per
    model-axis shard via shard_map (falling back to the XLA gather path only
    when GQA heads don't divide the axis — the pool replicates there too).
    Donate ``kv_k``/``kv_v`` at the jit call site for in-place page updates.
    """
    h, kv_k_new, kv_v_new = _forward_hidden(
        params, cfg, tokens, positions, kv_k, kv_v, page_tables, ctx_lens,
        page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
        mesh=mesh, adapter_ids=adapter_ids, qmm_impl=qmm_impl,
    )
    return lm_head_logits(params, cfg, h), kv_k_new, kv_v_new


def forward_ragged_impl(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [N] int32 flat ragged token batch
    positions: jnp.ndarray,  # [N] absolute positions (pads: trash position)
    row_ids: jnp.ndarray,  # [N] int32 row (sequence) owning each token
    kv_k: jnp.ndarray,
    kv_v: jnp.ndarray,
    page_tables: jnp.ndarray,  # [R, max_pages(+1)] per-ROW page tables
    ctx_lens: jnp.ndarray,  # [R] cache length AFTER this step, per row
    sel_idx: jnp.ndarray,  # [S] flat token indices whose logits are wanted
    page_size: int,
    block_pages: int = 32,
    attn_impl: str = "xla",
    mesh=None,
    adapter_ids: Optional[jnp.ndarray] = None,  # [R] int32 LoRA rows, per row
    qmm_impl: str = "xla",
    ragged_block: int = 8,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Mixed prefill+decode forward over ONE flat ragged token batch.

    The serving entry for the unified mixed dispatch (PAPERS.md "Ragged
    Paged Attention"): decode rows contribute one token each and prefill
    rows a whole chunk, flattened into a single [N] buffer with per-token
    ``row_ids`` selecting each token's page-table row / context length /
    adapter.

    Layout contract (the engine's builder upholds it): every row's token
    run is contiguous ascending and starts at a multiple of
    ``ragged_block``; pad tokens carry the trash position (their K/V land
    in the reserved null page) and either their run's row id or a
    dedicated null row with ``ctx_len = 0``. Under that alignment each
    ``ragged_block``-sized block belongs to exactly one row, so the whole
    stack runs as a [N/ragged_block, ragged_block] chunked forward with
    per-BLOCK gathered tables — the same transform
    :func:`runbookai_tpu.ops.attention.ragged_paged_attention` and the
    Pallas ``paged_ragged_attention`` apply per attention call, hoisted
    here above the layer scan so KV writes and page loads share it.

    Returns (logits [S, vocab] f32 for the ``sel_idx`` tokens only — the
    vocab projection is paid for S rows, not N — kv_k', kv_v').
    """
    n = tokens.shape[0]
    rq = ragged_block
    nb = n // rq
    block_rows = row_ids.reshape(nb, rq)[:, 0]
    h, kv_k_new, kv_v_new = _forward_hidden(
        params, cfg, tokens.reshape(nb, rq), positions.reshape(nb, rq),
        kv_k, kv_v, page_tables[block_rows], ctx_lens[block_rows],
        page_size=page_size, block_pages=block_pages, attn_impl=attn_impl,
        mesh=mesh,
        adapter_ids=(adapter_ids[block_rows]
                     if adapter_ids is not None else None),
        qmm_impl=qmm_impl,
    )
    h_sel = h.reshape(n, h.shape[-1])[sel_idx]
    return lm_head_logits(params, cfg, h_sel), kv_k_new, kv_v_new


forward = partial(jax.jit, static_argnames=("cfg", "page_size", "block_pages",
                                            "attn_impl", "mesh",
                                            "qmm_impl"))(forward_impl)


def dense_causal_attention(cfg: LlamaConfig, b: int, t: int):
    """Default training attention: materialized causal softmax over [T, T]."""
    hd, n_kv, n_q = cfg.head_dim, cfg.n_kv_heads, cfg.n_heads
    group = n_q // n_kv
    causal = jnp.tril(jnp.ones((t, t), dtype=bool))

    def attn_fn(q, k, v):
        qg = (q * (1.0 / jnp.sqrt(jnp.float32(hd)))).reshape(b, t, n_kv, group, hd)
        scores = jnp.einsum("btkgd,bskd->btkgs", qg.astype(jnp.float32),
                            k.astype(jnp.float32))
        scores = jnp.where(causal[None, :, None, None, :], scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("btkgs,bskd->btkgd", attn, v).reshape(b, t, n_q, hd)

    return attn_fn


def transformer_layer(hidden, lp, cfg: LlamaConfig, positions, attn_fn,
                      lora_lp=None, adapter_ids=None):
    """One pre-norm attention + SwiGLU block — shared by every forward path
    (dense training, sequence-parallel ring, pipeline stages). ``lora_lp``
    (one layer's stacked adapters) + ``adapter_ids`` apply per-row LoRA,
    exactly as the serving forward does — the fine-tuning path trains the
    same tree serving gathers from."""
    b, t = hidden.shape[:2]
    hd, n_kv, n_q = cfg.head_dim, cfg.n_kv_heads, cfg.n_heads
    if lora_lp is not None:
        from runbookai_tpu.models.lora import apply_lora
    x = rms_norm(hidden, lp["attn_norm"], cfg.norm_eps)
    q, k, v = qmm(x, lp["wq"]), qmm(x, lp["wk"]), qmm(x, lp["wv"])
    if lora_lp is not None:
        q = q + apply_lora(x, lora_lp, "wq", adapter_ids)
        k = k + apply_lora(x, lora_lp, "wk", adapter_ids)
        v = v + apply_lora(x, lora_lp, "wv", adapter_ids)
    if cfg.qkv_bias:
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    q = apply_rope(q.reshape(b, t, n_q, hd), positions, cfg.rope_theta,
                   cfg.rope_scaling)
    k = apply_rope(k.reshape(b, t, n_kv, hd), positions, cfg.rope_theta,
                   cfg.rope_scaling)
    v = v.reshape(b, t, n_kv, hd)
    ctx = attn_fn(q, k, v).reshape(b, t, n_q * hd)
    o = qmm(ctx, lp["wo"])
    if lora_lp is not None:
        o = o + apply_lora(ctx, lora_lp, "wo", adapter_ids)
    hidden = hidden + o
    y = rms_norm(hidden, lp["mlp_norm"], cfg.norm_eps)
    return hidden + ffn_block(y, lp, cfg)


def lm_head_logits(params: Params, cfg: LlamaConfig, hidden) -> jnp.ndarray:
    """Final norm + (tied or untied) LM head, float32 logits."""
    h = rms_norm(hidden, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return (h @ head).astype(jnp.float32)


def forward_train(
    params: Params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,
    positions: Optional[jnp.ndarray] = None,  # [B, T] absolute positions
    attn_fn=None,  # (q [B,T,n_q,hd], k [B,T,n_kv,hd], v) -> [B,T,n_q,hd]
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] LoRA rows
) -> jnp.ndarray:
    """Training-mode forward: dense causal attention over [B, T], no KV cache.

    Used by the fine-tuning path and the multi-chip dry-run; shares every
    parameter and norm with the serving forward, differing only in attention
    materialization (XLA fuses the masked softmax; sequence fits in one pass).
    ``attn_fn`` swaps the attention implementation while keeping the rest of
    the layer identical — the sequence-parallel path passes ring attention
    here (``parallel/sequence_parallel.py``) so the two forwards cannot drift.
    """
    b, t = tokens.shape
    if positions is None:
        positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    positions = jnp.broadcast_to(positions, (b, t))
    if attn_fn is None:
        attn_fn = dense_causal_attention(cfg, b, t)

    h = params["embed"][tokens]
    lora = params.get("lora")
    if lora is not None and adapter_ids is None:
        adapter_ids = jnp.zeros((b,), jnp.int32)

    def layer_step(hidden, layer_in):
        lp, lp_lora = layer_in
        return transformer_layer(hidden, lp, cfg, positions, attn_fn,
                                 lora_lp=lp_lora,
                                 adapter_ids=adapter_ids), None

    h, _ = jax.lax.scan(layer_step, h, (params["layers"], lora))
    return lm_head_logits(params, cfg, h)
