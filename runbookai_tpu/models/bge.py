"""bge-base-en-v1.5 (BERT-base) encoder in JAX — the knowledge embedder model.

Replaces the reference's hosted OpenAI embedder
(``src/knowledge/indexer/embedder.ts:20-22``: text-embedding-3-small, 1536-d)
with an on-device 768-d encoder. Same scan-stacked design as the Llama stack:
one compiled layer body, bidirectional attention with a padding mask, post-LN
BERT blocks, CLS pooling + L2 normalization (the bge recipe).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from functools import partial
from pathlib import Path
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class BertConfig:
    name: str
    vocab_size: int = 30_522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    ffn_dim: int = 3072
    max_positions: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS = {
    "bge-base-en-v1.5": BertConfig(name="bge-base-en-v1.5"),
    "bge-test": BertConfig(name="bge-test", vocab_size=262, dim=32, n_layers=2,
                           n_heads=4, ffn_dim=64, max_positions=128),
}


def init_params(key: jax.Array, cfg: BertConfig, dtype=jnp.float32) -> dict[str, Any]:
    ks = jax.random.split(key, 12)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan_in)).astype(dtype)

    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    return {
        "word_emb": dense(ks[0], (cfg.vocab_size, D), D),
        "pos_emb": dense(ks[1], (cfg.max_positions, D), D),
        "type_emb": dense(ks[2], (cfg.type_vocab_size, D), D),
        "emb_norm_w": jnp.ones((D,), jnp.float32),
        "emb_norm_b": jnp.zeros((D,), jnp.float32),
        "layers": {
            "wq": dense(ks[3], (L, D, D), D),
            "bq": jnp.zeros((L, D), dtype),
            "wk": dense(ks[4], (L, D, D), D),
            "bk": jnp.zeros((L, D), dtype),
            "wv": dense(ks[5], (L, D, D), D),
            "bv": jnp.zeros((L, D), dtype),
            "wo": dense(ks[6], (L, D, D), D),
            "bo": jnp.zeros((L, D), dtype),
            "attn_norm_w": jnp.ones((L, D), jnp.float32),
            "attn_norm_b": jnp.zeros((L, D), jnp.float32),
            "w1": dense(ks[7], (L, D, F), D),
            "b1": jnp.zeros((L, F), dtype),
            "w2": dense(ks[8], (L, F, D), F),
            "b2": jnp.zeros((L, D), dtype),
            "mlp_norm_w": jnp.ones((L, D), jnp.float32),
            "mlp_norm_b": jnp.zeros((L, D), jnp.float32),
        },
    }


def layer_norm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(-1, keepdims=True)
    return (((xf - mean) * jax.lax.rsqrt(var + eps)) * w + b).astype(x.dtype)


@partial(jax.jit, static_argnames=("cfg",))
def encode(
    params: dict[str, Any],
    cfg: BertConfig,
    tokens: jnp.ndarray,  # [B, T] int32 (padded)
    attention_mask: jnp.ndarray,  # [B, T] 1 for real tokens
) -> jnp.ndarray:
    """Returns L2-normalized [B, dim] float32 embeddings (CLS pooling)."""
    b, t = tokens.shape
    h = (
        params["word_emb"][tokens]
        + params["pos_emb"][None, :t]
        + params["type_emb"][0][None, None, :]
    )
    h = layer_norm(h, params["emb_norm_w"], params["emb_norm_b"], cfg.norm_eps)

    # Additive mask: [B, 1, 1, T] — padded keys masked for every query.
    neg = jnp.asarray(-1e30, jnp.float32)
    mask = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    def layer_step(hidden, lp):
        hd, nh = cfg.head_dim, cfg.n_heads
        q = (hidden @ lp["wq"] + lp["bq"]).reshape(b, t, nh, hd)
        k = (hidden @ lp["wk"] + lp["bk"]).reshape(b, t, nh, hd)
        v = (hidden @ lp["wv"] + lp["bv"]).reshape(b, t, nh, hd)
        scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
        scores = scores / np.sqrt(hd) + mask
        attn = jax.nn.softmax(scores, axis=-1).astype(hidden.dtype)
        ctx = jnp.einsum("bhts,bshd->bthd", attn, v).reshape(b, t, cfg.dim)
        hidden = layer_norm(hidden + (ctx @ lp["wo"] + lp["bo"]),
                            lp["attn_norm_w"], lp["attn_norm_b"], cfg.norm_eps)
        ffn = jax.nn.gelu(hidden @ lp["w1"] + lp["b1"]) @ lp["w2"] + lp["b2"]
        hidden = layer_norm(hidden + ffn, lp["mlp_norm_w"], lp["mlp_norm_b"], cfg.norm_eps)
        return hidden, None

    h, _ = jax.lax.scan(layer_step, h, params["layers"])
    cls = h[:, 0].astype(jnp.float32)
    return cls / jnp.maximum(jnp.linalg.norm(cls, axis=-1, keepdims=True), 1e-9)


# --------------------------------------------------------------------------- #
# HF loading                                                                  #
# --------------------------------------------------------------------------- #

_HF_LAYER = {
    "wq": ("encoder.layer.{i}.attention.self.query.weight", True),
    "bq": ("encoder.layer.{i}.attention.self.query.bias", False),
    "wk": ("encoder.layer.{i}.attention.self.key.weight", True),
    "bk": ("encoder.layer.{i}.attention.self.key.bias", False),
    "wv": ("encoder.layer.{i}.attention.self.value.weight", True),
    "bv": ("encoder.layer.{i}.attention.self.value.bias", False),
    "wo": ("encoder.layer.{i}.attention.output.dense.weight", True),
    "bo": ("encoder.layer.{i}.attention.output.dense.bias", False),
    "attn_norm_w": ("encoder.layer.{i}.attention.output.LayerNorm.weight", False),
    "attn_norm_b": ("encoder.layer.{i}.attention.output.LayerNorm.bias", False),
    "w1": ("encoder.layer.{i}.intermediate.dense.weight", True),
    "b1": ("encoder.layer.{i}.intermediate.dense.bias", False),
    "w2": ("encoder.layer.{i}.output.dense.weight", True),
    "b2": ("encoder.layer.{i}.output.dense.bias", False),
    "mlp_norm_w": ("encoder.layer.{i}.output.LayerNorm.weight", False),
    "mlp_norm_b": ("encoder.layer.{i}.output.LayerNorm.bias", False),
}


def load_params(model_dir: str | Path, dtype=jnp.float32) -> tuple[BertConfig, dict]:
    """Load a bge/BERT checkpoint from an HF directory (safetensors)."""
    from safetensors import safe_open

    model_dir = Path(model_dir)
    raw = json.loads((model_dir / "config.json").read_text())
    cfg = BertConfig(
        name=model_dir.name,
        vocab_size=raw["vocab_size"], dim=raw["hidden_size"],
        n_layers=raw["num_hidden_layers"], n_heads=raw["num_attention_heads"],
        ffn_dim=raw["intermediate_size"],
        max_positions=raw.get("max_position_embeddings", 512),
        type_vocab_size=raw.get("type_vocab_size", 2),
        norm_eps=raw.get("layer_norm_eps", 1e-12),
    )
    shard = next(iter(sorted(model_dir.glob("*.safetensors"))))
    f = safe_open(str(shard), framework="numpy")
    names = set(f.keys())

    def get(name: str) -> np.ndarray:
        for candidate in (name, f"bert.{name}"):
            if candidate in names:
                return f.get_tensor(candidate)
        raise KeyError(name)

    params = {
        "word_emb": jnp.asarray(get("embeddings.word_embeddings.weight"), dtype),
        "pos_emb": jnp.asarray(get("embeddings.position_embeddings.weight"), dtype),
        "type_emb": jnp.asarray(get("embeddings.token_type_embeddings.weight"), dtype),
        "emb_norm_w": jnp.asarray(get("embeddings.LayerNorm.weight"), jnp.float32),
        "emb_norm_b": jnp.asarray(get("embeddings.LayerNorm.bias"), jnp.float32),
    }
    layers: dict[str, Any] = {}
    for leaf, (tmpl, transpose) in _HF_LAYER.items():
        mats = [get(tmpl.format(i=i)) for i in range(cfg.n_layers)]
        stacked = np.stack([m.T if transpose else m for m in mats])
        leaf_dtype = jnp.float32 if "norm" in leaf else dtype
        layers[leaf] = jnp.asarray(stacked, leaf_dtype)
    params["layers"] = layers
    return cfg, params


def load_or_init(model_name: str, model_path: Optional[str | Path],
                 dtype=jnp.float32, seed: int = 0) -> tuple[BertConfig, dict]:
    if model_path and Path(model_path).exists():
        return load_params(model_path, dtype=dtype)
    cfg = CONFIGS.get(model_name, CONFIGS["bge-test"])
    return cfg, init_params(jax.random.PRNGKey(seed), cfg, dtype=dtype)
