"""Int8 weight-only quantization for serving (SURVEY.md §7 hard part 4).

bf16 Llama-3-70B is ~140GB — it cannot fit 16 v5e chips (16GB HBM each) with
any KV headroom. Per-output-channel symmetric int8 halves the weight bytes
(~72GB sharded → ~4.5GB/chip + bf16 embeddings/head), leaving page-pool room.

Scheme: for a weight ``w [.., in, out]``, ``scale = max|w| / 127`` over the
input axis (one scale per output channel) and ``q = round(w / scale)``. The
matmul then runs on the MXU in bf16 (int8→bf16 cast is exact for |q| ≤ 127)
and the per-channel scale is applied to the *output* — mathematically
identical to dequantize-then-matmul because the scale is constant along the
contraction:  sum_i x_i·q_io·s_o == s_o·sum_i x_i·q_io.

Quantized leaves are plain pytrees ``{"q": int8, "s": float32}``, so they
flow through ``lax.scan`` layer stacking, ``jax.device_put`` sharding, and
checkpointing unchanged. Norms, embeddings, and the LM head stay bf16
(< 3% of 70B bytes; quality-critical).

No reference counterpart: RunbookAI calls hosted LLM APIs (SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

Params = dict[str, Any]

# Stacked layer matrices that dominate the byte budget.
LAYER_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w: Any) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_array_np(w, in_axis: int = -2):
    """Host-side (numpy) quantization for the weight-loading path — the full
    bf16 tensor never reaches device HBM. Returns ``(q int8, s f32)``."""
    import numpy as np

    wf = np.asarray(w, dtype=np.float32)
    s = np.abs(wf).max(axis=in_axis, keepdims=True) / 127.0
    s = np.maximum(s, 1e-8)
    q = np.clip(np.round(wf / s), -127, 127).astype(np.int8)
    return q, s.astype(np.float32)


def quantize_tensor(w: jnp.ndarray, in_axis: int = -2) -> dict[str, jnp.ndarray]:
    """Symmetric per-output-channel int8: ``{"q": int8, "s": f32 keepdims}``."""
    wf = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=in_axis, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)  # all-zero channels
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s}


def dequantize_tensor(w: dict[str, jnp.ndarray], dtype=jnp.float32) -> jnp.ndarray:
    return (w["q"].astype(jnp.float32) * w["s"]).astype(dtype)


def quantize_params(params: Params) -> Params:
    """Serving transform: quantize the seven stacked layer matrices."""
    out = dict(params)
    out["layers"] = {
        k: quantize_tensor(v) if k in LAYER_QUANT_KEYS else v
        for k, v in params["layers"].items()
    }
    return out


def dequantize_params(params: Params, dtype=jnp.bfloat16) -> Params:
    out = dict(params)
    out["layers"] = {
        k: dequantize_tensor(v, dtype) if is_quantized(v) else v
        for k, v in params["layers"].items()
    }
    return out


def shardings_with_quant(shardings: Params, params: Optional[Params] = None,
                         keys=LAYER_QUANT_KEYS) -> Params:
    """Expand a ``param_shardings`` tree to match quantized param structure.

    ``q`` keeps the original weight's spec. ``s`` (``[.., 1, out]``, same
    rank as the weight) keeps the weight's spec except on the contraction
    axis (-2), where it has extent 1 and must replicate: column-parallel
    weights shard their scales the same way; row-parallel weights
    (contraction sharded) replicate them — the scale multiplies the
    *partial sums' combined* output, and XLA applies it after its inserted
    psum. Works for the dense rank-3 [L, in, out] and the MoE rank-4
    [L, E, in, out] leaves alike. With ``params`` given, only leaves
    actually quantized there are expanded; otherwise every key in ``keys``
    is (skipping keys absent from the sharding tree).
    """
    if params is not None:
        keys = [k for k, v in params["layers"].items() if is_quantized(v)]
    out = dict(shardings)
    layers = dict(shardings["layers"])
    for k in keys:
        if k not in shardings["layers"]:
            continue
        base: NamedSharding = shardings["layers"][k]
        spec = list(base.spec)
        # param_shardings writes full-rank specs (3 dense / 4 MoE); clear
        # the contraction axis (-2), where the scale has extent 1. An empty
        # (replicated) spec stays replicated.
        if len(spec) >= 3:
            spec[-2] = None
        s_spec = P(*spec) if any(a is not None for a in spec) else P()
        layers[k] = {"q": base, "s": NamedSharding(base.mesh, s_spec)}
    out["layers"] = layers
    return out


def weight_bytes(params: Params) -> int:
    """Total bytes across all weight leaves (quantized or not)."""
    import jax

    return sum(x.nbytes for x in jax.tree.leaves(params))
