"""Pipeline parallelism — GPipe-style microbatch schedule over a ``pipe`` axis.

The scan-stacked layer dimension is the natural stage boundary: each device
on the ``pipe`` mesh axis holds ``n_layers / n_stages`` contiguous layers
(the leading layer axis is simply sharded over ``pipe``), and activations
flow stage→stage with ``jax.lax.ppermute`` — a nearest-neighbor ICI hop, the
same primitive ring attention uses on ``seq``.

Schedule: plain GPipe fill-drain over ``M`` microbatches. The whole pipeline
runs as ONE compiled SPMD program of ``M + S - 1`` ticks (a ``lax.scan``):
at tick ``t`` stage ``s`` processes microbatch ``t - s`` (predicated with
``where`` — XLA-friendly static control flow, no per-stage programs to
launch). Bubble fraction is the usual ``(S-1)/(M+S-1)``; raise ``M`` to
amortize.

Embedding runs on stage 0, the LM head on the last stage; intermediate
logits never materialize anywhere else (the head matmul is applied once to
the collected hidden buffer, then masked + psum'd so every device returns
the same logits — convenient for loss computation under DP on top).

SURVEY.md §2.10 lists PP as the optional extension beyond the north-star TP
configs; it exists so depth-dominated models (Llama-3-70B's 80 layers) can
trade TP collective volume for pipeline bubbles on narrow meshes. No
reference counterpart (the reference executes no models).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from runbookai_tpu.models.llama import (
    LlamaConfig,
    dense_causal_attention,
    lm_head_logits,
    transformer_layer,
)
from runbookai_tpu.parallel.mesh import PIPE_AXIS
from runbookai_tpu.parallel.ring_attention import _mark_varying


def _pipeline_local(params, tokens_mb, cfg: LlamaConfig, axis_name: str):
    """Run the GPipe schedule on this stage's layer slice (inside shard_map).

    params["layers"] leaves arrive sharded to [L/S, ...]; tokens_mb is the
    replicated [M, mb, T] microbatched token array.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    m_total, mb, t = tokens_mb.shape
    positions = jnp.arange(t, dtype=jnp.int32)[None, :]
    attn_fn = dense_causal_attention(cfg, mb, t)
    is_first = stage == 0
    is_last = stage == n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # no wraparound

    def stage_apply(act):
        def step(h, lp):
            return transformer_layer(h, lp, cfg, positions, attn_fn), None

        h, _ = jax.lax.scan(step, act, params["layers"])
        return h

    def tick(carry, tk):
        act_in, out_buf = carry
        m_idx = tk - stage  # which microbatch this stage handles at this tick
        valid = (m_idx >= 0) & (m_idx < m_total)
        m_clip = jnp.clip(m_idx, 0, m_total - 1)
        tok = jax.lax.dynamic_index_in_dim(tokens_mb, m_clip, 0, keepdims=False)
        emb = params["embed"][tok]
        h_out = stage_apply(jnp.where(is_first, emb, act_in))
        stored = jax.lax.dynamic_update_index_in_dim(out_buf, h_out, m_clip, 0)
        out_buf = jnp.where(valid & is_last, stored, out_buf)
        act_next = jax.lax.ppermute(h_out, axis_name, perm)
        return (act_next, out_buf), None

    dtype = params["embed"].dtype
    act0 = _mark_varying(jnp.zeros((mb, t, cfg.dim), dtype), axis_name)
    out0 = _mark_varying(jnp.zeros((m_total, mb, t, cfg.dim), dtype), axis_name)
    n_ticks = m_total + n_stages - 1
    (act, out_buf), _ = jax.lax.scan(tick, (act0, out0), jnp.arange(n_ticks))

    logits = lm_head_logits(params, cfg, out_buf.reshape(m_total * mb, t, cfg.dim))
    logits = jnp.where(is_last, logits, 0.0)
    # Only the last stage holds real logits; psum broadcasts them pipe-wide.
    return jax.lax.psum(logits, axis_name)


def forward_train_pp(
    params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T]
    mesh: Mesh,
    n_microbatches: int = 4,
    axis_name: str = PIPE_AXIS,
) -> jnp.ndarray:
    """Dense causal forward with layers pipelined over ``mesh[axis_name]``.

    Numerically equivalent to ``models.llama.forward_train``; requires
    ``n_layers % n_stages == 0`` and ``B % n_microbatches == 0``. Returns
    replicated [B, T, vocab] float32 logits.
    """
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers % n_stages:
        raise ValueError(f"{cfg.n_layers} layers not divisible by {n_stages} stages")
    b, t = tokens.shape
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches} microbatches")
    tokens_mb = tokens.reshape(n_microbatches, b // n_microbatches, t)

    param_specs = {
        "embed": P(),
        "layers": P(axis_name),  # prefix spec: leading layer axis → stages
        "final_norm": P(),
    }
    if not cfg.tie_embeddings:
        param_specs["lm_head"] = P()

    kwargs = {}
    try:
        import inspect

        if "axis_names" in inspect.signature(shard_map).parameters:
            kwargs["axis_names"] = {axis_name}
    except (TypeError, ValueError):
        pass
    fn = shard_map(
        partial(_pipeline_local, cfg=cfg, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        **kwargs,
    )
    logits = fn(params, tokens_mb)
    return logits.reshape(b, t, -1)


def pp_param_shardings(cfg: LlamaConfig, mesh: Mesh,
                       axis_name: str = PIPE_AXIS) -> dict:
    """NamedShardings for pipeline training: every stacked layer leaf's
    leading layer axis shards over ``pipe`` (each stage materializes only
    its own L/S layers — and, with the optimizer state following the same
    placement, only its own Adam moments); embed/head/final-norm replicate."""
    from jax.sharding import NamedSharding

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    layer_keys = ["wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
                  "attn_norm", "mlp_norm"]
    if cfg.qkv_bias:
        layer_keys += ["bq", "bk", "bv"]
    if cfg.n_experts:
        layer_keys.append("router")
    shardings = {
        "embed": ns(),
        "layers": {k: ns(axis_name) for k in layer_keys},
        "final_norm": ns(),
    }
    if not cfg.tie_embeddings:
        shardings["lm_head"] = ns()
    return shardings


def loss_fn_pp(params, cfg: LlamaConfig, tokens: jnp.ndarray, pad_id: int,
               mesh: Mesh, n_microbatches: int = 4) -> jnp.ndarray:
    """Mean next-token cross-entropy through the GPipe forward — the
    differentiable training entry (VERDICT r2 next-round #9: the backward
    flows through the whole schedule: scan ticks, ppermute hops
    (transposed to the reverse permutation), stage masks, and the psum'd
    head). Uses the same ``masked_cross_entropy`` as the dense trainer."""
    from runbookai_tpu.train.trainer import masked_cross_entropy

    logits = forward_train_pp(params, cfg, tokens[:, :-1], mesh,
                              n_microbatches=n_microbatches)
    return masked_cross_entropy(logits, tokens[:, 1:], pad_id)
