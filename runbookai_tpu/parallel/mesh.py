"""Device mesh / topology module — the distributed-communication backend.

SURVEY.md §2.10: the reference has *no* distributed backend (all communication
is HTTPS to SaaS APIs); the TPU-native equivalent is XLA collectives over ICI
expressed through ``jax.sharding.Mesh`` + ``NamedSharding``. This module is
the single place device topology is defined:

- ``data`` axis — batches independent sequences / eval cases (DP).
- ``seq`` axis — shards the sequence dimension for long-context ring
  attention (``parallel/ring_attention.py``); K/V shards rotate around this
  axis's ICI ring via ``ppermute``.
- ``model`` axis — shards attention heads, MLP, vocab (Megatron TP); psum /
  all-gather reductions ride ICI inside compiled programs.

Multi-host (DCN) scale-out uses the same axis names over
``jax.distributed``-initialized global device lists.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def build_mesh(
    data: int = 1,
    model: int = 1,
    seq: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, seq, model) mesh over the first ``data*seq*model`` devices.

    Uses ``mesh_utils.create_device_mesh`` when the whole device set is used
    (it picks an ICI-friendly physical layout — the ``seq`` axis lands on a
    ring so ppermute hops are nearest-neighbor); falls back to a simple
    reshape for subsets (tests, single-chip).
    """
    devices = list(devices if devices is not None else jax.devices())
    need = data * seq * model
    if need > len(devices):
        raise ValueError(
            f"mesh {data}x{seq}x{model} needs {need} devices, have {len(devices)}")
    if need == len(devices):
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh((data, seq, model), devices=devices)
            return Mesh(arr, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
        except Exception:
            pass
    arr = np.asarray(devices[:need]).reshape(data, seq, model)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def single_device_mesh() -> Mesh:
    return build_mesh(1, 1)


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
