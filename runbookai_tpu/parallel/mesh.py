"""Device mesh / topology module — the distributed-communication backend.

SURVEY.md §2.10: the reference has *no* distributed backend (all communication
is HTTPS to SaaS APIs); the TPU-native equivalent is XLA collectives over ICI
expressed through ``jax.sharding.Mesh`` + ``NamedSharding``. This module is
the single place device topology is defined:

- ``data`` axis — batches independent sequences / eval cases (DP).
- ``pipe`` axis — pipeline stages: the scan-stacked layer dimension is
  partitioned across this axis and activations flow stage-to-stage via
  ``ppermute`` (``parallel/pipeline.py``).
- ``seq`` axis — shards the sequence dimension for long-context ring
  attention (``parallel/ring_attention.py``); K/V shards rotate around this
  axis's ICI ring via ``ppermute``.
- ``model`` axis — shards attention heads, MLP, vocab (Megatron TP); psum /
  all-gather reductions ride ICI inside compiled programs.

Multi-host (DCN) scale-out uses the same axis names over
``jax.distributed``-initialized global device lists.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"

AXIS_ORDER = (DATA_AXIS, PIPE_AXIS, SEQ_AXIS, MODEL_AXIS)


def build_mesh(
    data: int = 1,
    model: int = 1,
    seq: int = 1,
    pipe: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, pipe, seq, model) mesh over the first N needed devices.

    Uses ``mesh_utils.create_device_mesh`` when the whole device set is used
    (it picks an ICI-friendly physical layout — the ``seq``/``pipe`` axes land
    on rings so ppermute hops are nearest-neighbor); falls back to a simple
    reshape for subsets (tests, single-chip).
    """
    devices = list(devices if devices is not None else jax.devices())
    shape = (data, pipe, seq, model)
    need = data * pipe * seq * model
    if need > len(devices):
        raise ValueError(
            f"mesh {'x'.join(map(str, shape))} needs {need} devices, have {len(devices)}")
    if need == len(devices):
        try:
            from jax.experimental import mesh_utils

            arr = mesh_utils.create_device_mesh(shape, devices=devices)
            return Mesh(arr, AXIS_ORDER)
        except Exception:
            pass
    arr = np.asarray(devices[:need]).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def single_device_mesh() -> Mesh:
    return build_mesh(1, 1)


def replica_device_slices(
    dp: int,
    per_replica: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> list[Optional[list[jax.Device]]]:
    """Disjoint device slices along the dp axis for an engine fleet.

    Each of the ``dp`` replicas owns ``per_replica`` consecutive devices
    (the replica-internal axes — model/seq — stay within a slice, so their
    high-frequency collectives ride ICI while replicas never communicate
    inside compiled programs at all). When the host has fewer devices than
    the fleet needs, every entry is ``None``: replicas share the default
    device — the CPU tier-1 virtual-fleet case when the platform exposes a
    single device.
    """
    devices = list(devices if devices is not None else jax.devices())
    if dp < 1 or per_replica < 1:
        raise ValueError("dp and per_replica must be >= 1")
    if len(devices) < dp * per_replica:
        return [None] * dp
    return [devices[i * per_replica:(i + 1) * per_replica]
            for i in range(dp)]


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
