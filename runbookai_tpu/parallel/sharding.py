"""Sharding rules: Megatron-style TP specs for the Llama pytree + KV pool.

The scan-stacked param layout makes the specs uniform: every layer leaf has a
leading layer axis that is never sharded; the ``model`` mesh axis shards
attention heads / MLP hidden / vocab:

- ``wq/wk/wv``: [L, D, heads*hd]   → column-parallel, P(None, None, model)
- ``wo``:       [L, heads*hd, D]   → row-parallel,    P(None, model, None)
- ``w_gate/up``:[L, D, F]          → column-parallel
- ``w_down``:   [L, F, D]          → row-parallel
- ``embed``:    [V, D]             → vocab-sharded
- ``lm_head``:  [D, V]             → vocab-sharded (logit psum/all-gather by XLA)
- KV pool:      [L, tokens, n_kv, hd] → kv-heads sharded when divisible,
  replicated otherwise (e.g. 70B GQA n_kv=8 on TP16 — documented trade-off;
  a 2D head×seq mesh is the extension path).

XLA inserts the psum/all-gather collectives from these placements (the
scaling-book recipe: pick a mesh, annotate shardings, let XLA do the rest).
"""

from __future__ import annotations

from typing import Any

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbookai_tpu.models.llama import LlamaConfig
from runbookai_tpu.parallel.mesh import MODEL_AXIS


def param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict[str, Any]:
    """Pytree of NamedShardings matching ``init_params`` structure.

    With a KV page-split serving mesh (``seq`` axis > 1 —
    ``parallel/kv_split.py``), the full tp factor is ``model × seq``:
    column/row-parallel leaves shard over the combined tuple axis
    (model-major, so query heads stay adjacent to their GQA kv head),
    while ``wk``/``wv`` shard over ``model`` only — every page shard of a
    kv group needs that group's K/V projections.
    """
    from runbookai_tpu.parallel.mesh import SEQ_AXIS

    def ns(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    pg = mesh.shape.get(SEQ_AXIS, 1)
    kv_sh = mesh.shape.get(MODEL_AXIS, 1)
    tp = kv_sh * pg
    TP_AXES = (MODEL_AXIS, SEQ_AXIS) if pg > 1 else MODEL_AXIS
    vocab_ok = cfg.vocab_size % tp == 0
    heads_ok = cfg.n_heads % tp == 0
    ffn_ok = cfg.ffn_dim % tp == 0
    kv_ok = cfg.n_kv_heads % kv_sh == 0

    col = ns(None, None, TP_AXES) if heads_ok else ns()
    shardings: dict[str, Any] = {
        "embed": ns(TP_AXES, None) if vocab_ok else ns(),
        "layers": {
            "wq": col,
            "wk": ns(None, None, MODEL_AXIS) if kv_ok else ns(),
            "wv": ns(None, None, MODEL_AXIS) if kv_ok else ns(),
            "wo": ns(None, TP_AXES, None) if heads_ok else ns(),
            "w_gate": ns(None, None, TP_AXES) if ffn_ok else ns(),
            "w_up": ns(None, None, TP_AXES) if ffn_ok else ns(),
            "w_down": ns(None, TP_AXES, None) if ffn_ok else ns(),
            "attn_norm": ns(),
            "mlp_norm": ns(),
        },
        "final_norm": ns(),
    }
    if cfg.n_experts:
        # Expert parallelism: the FFN leaves are [L, E, D, F]/[L, E, F, D];
        # shard the expert axis over the model axis (XLA inserts the
        # dispatch/combine collectives from the einsum operand shardings).
        # The router is tiny and replicates.
        ep_ok = cfg.n_experts % tp == 0
        ep = ns(None, MODEL_AXIS, None, None) if ep_ok else ns()
        shardings["layers"].update(
            {"w_gate": ep, "w_up": ep, "w_down": ep, "router": ns()})
    if cfg.qkv_bias:
        # Biases follow their projection's output axis (column-parallel).
        shardings["layers"]["bq"] = (
            ns(None, TP_AXES) if heads_ok else ns())
        shardings["layers"]["bk"] = ns(None, MODEL_AXIS) if kv_ok else ns()
        shardings["layers"]["bv"] = ns(None, MODEL_AXIS) if kv_ok else ns()
    if not cfg.tie_embeddings:
        shardings["lm_head"] = ns(None, TP_AXES) if vocab_ok else ns()
    return shardings


def kv_pool_sharding(cfg: LlamaConfig, mesh: Mesh) -> NamedSharding:
    """[L, tokens, n_kv, hd] placement for the paged pool.

    Heads shard over ``model``; with a KV page-split mesh the token axis
    additionally shards over ``seq`` (``parallel/kv_split.py``), so
    per-chip KV bytes shrink by the FULL tp factor even past the GQA
    head count — tp16 on 70B (n_kv=8) runs model=8 × seq=2 instead of
    replicating the pool (the r3 warning path is gone; ``plan_kv_split``
    decides the factorization and raises on unservable layouts).
    """
    from runbookai_tpu.parallel.mesh import SEQ_AXIS

    kv_sh = mesh.shape.get(MODEL_AXIS, 1)
    pg = mesh.shape.get(SEQ_AXIS, 1)
    if cfg.n_kv_heads % kv_sh != 0:
        raise ValueError(
            f"n_kv_heads={cfg.n_kv_heads} not divisible by the mesh model "
            f"axis ({kv_sh}); factor the extra parallelism onto the seq "
            f"axis via parallel.kv_split.plan_kv_split")
    if pg > 1:
        return NamedSharding(mesh, P(None, SEQ_AXIS, MODEL_AXIS, None))
    return NamedSharding(mesh, P(None, None, MODEL_AXIS, None))
