"""Sharding rules: Megatron-style TP specs for the Llama pytree + KV pool.

The scan-stacked param layout makes the specs uniform: every layer leaf has a
leading layer axis that is never sharded; the ``model`` mesh axis shards
attention heads / MLP hidden / vocab:

- ``wq/wk/wv``: [L, D, heads*hd]   → column-parallel, P(None, None, model)
- ``wo``:       [L, heads*hd, D]   → row-parallel,    P(None, model, None)
- ``w_gate/up``:[L, D, F]          → column-parallel
- ``w_down``:   [L, F, D]          → row-parallel
- ``embed``:    [V, D]             → vocab-sharded
- ``lm_head``:  [D, V]             → vocab-sharded (logit psum/all-gather by XLA)
- KV pool:      [L, tokens, n_kv, hd] → kv-heads sharded when divisible,
  replicated otherwise (e.g. 70B GQA n_kv=8 on TP16 — documented trade-off;
  a 2D head×seq mesh is the extension path).

XLA inserts the psum/all-gather collectives from these placements (the
scaling-book recipe: pick a mesh, annotate shardings, let XLA do the rest).
"""

from __future__ import annotations

from typing import Any

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbookai_tpu.models.llama import LlamaConfig
from runbookai_tpu.parallel.mesh import MODEL_AXIS


def param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict[str, Any]:
    """Pytree of NamedShardings matching ``init_params`` structure."""

    def ns(*spec) -> NamedSharding:
        return NamedSharding(mesh, P(*spec))

    tp = mesh.shape.get(MODEL_AXIS, 1)
    vocab_ok = cfg.vocab_size % tp == 0
    heads_ok = cfg.n_heads % tp == 0
    ffn_ok = cfg.ffn_dim % tp == 0
    kv_ok = cfg.n_kv_heads % tp == 0

    col = ns(None, None, MODEL_AXIS) if heads_ok else ns()
    shardings: dict[str, Any] = {
        "embed": ns(MODEL_AXIS, None) if vocab_ok else ns(),
        "layers": {
            "wq": col,
            "wk": ns(None, None, MODEL_AXIS) if kv_ok else ns(),
            "wv": ns(None, None, MODEL_AXIS) if kv_ok else ns(),
            "wo": ns(None, MODEL_AXIS, None) if heads_ok else ns(),
            "w_gate": ns(None, None, MODEL_AXIS) if ffn_ok else ns(),
            "w_up": ns(None, None, MODEL_AXIS) if ffn_ok else ns(),
            "w_down": ns(None, MODEL_AXIS, None) if ffn_ok else ns(),
            "attn_norm": ns(),
            "mlp_norm": ns(),
        },
        "final_norm": ns(),
    }
    if cfg.n_experts:
        # Expert parallelism: the FFN leaves are [L, E, D, F]/[L, E, F, D];
        # shard the expert axis over the model axis (XLA inserts the
        # dispatch/combine collectives from the einsum operand shardings).
        # The router is tiny and replicates.
        ep_ok = cfg.n_experts % tp == 0
        ep = ns(None, MODEL_AXIS, None, None) if ep_ok else ns()
        shardings["layers"].update(
            {"w_gate": ep, "w_up": ep, "w_down": ep, "router": ns()})
    if cfg.qkv_bias:
        # Biases follow their projection's output axis (column-parallel).
        shardings["layers"]["bq"] = (
            ns(None, MODEL_AXIS) if heads_ok else ns())
        shardings["layers"]["bk"] = ns(None, MODEL_AXIS) if kv_ok else ns()
        shardings["layers"]["bv"] = ns(None, MODEL_AXIS) if kv_ok else ns()
    if not cfg.tie_embeddings:
        shardings["lm_head"] = ns(None, MODEL_AXIS) if vocab_ok else ns()
    return shardings


def kv_pool_sharding(cfg: LlamaConfig, mesh: Mesh) -> NamedSharding:
    tp = mesh.shape.get(MODEL_AXIS, 1)
    if cfg.n_kv_heads % tp == 0:
        return NamedSharding(mesh, P(None, None, MODEL_AXIS, None))
    # GQA with tp > n_kv_heads (e.g. 70B n_kv=8 on TP16): the pool — and
    # wk/wv — replicate, costing tp× the KV memory. That silently defeats
    # the TP memory plan, so say so; the supported layout for 70B-on-16 is
    # tp=8 × dp=2 (int8 weights ≈ 8.75GB/chip + sharded KV). A head×seq 2D
    # KV mesh is the documented extension path.
    import warnings

    warnings.warn(
        f"KV pool cannot shard: n_kv_heads={cfg.n_kv_heads} not divisible by "
        f"tp={tp}; replicating the full page pool on every chip. Use tp ≤ "
        f"{cfg.n_kv_heads} (e.g. tp=8 × dp=2 on a 16-chip slice).",
        stacklevel=2,
    )
    return NamedSharding(mesh, P())
