"""Ring attention — sequence/context parallelism over the ICI ring.

Long-context serving and training shard the *sequence* axis across devices;
each device holds a [B, T/n, H, D] slice of Q/K/V. Attention then needs every
(query, key) pair, which ring attention supplies without ever materializing
the full sequence on one chip: K/V shards rotate around the device ring via
``jax.lax.ppermute`` while each device accumulates its queries' attention
online (flash-style running max / normalizer, numerically exact).

Design notes (TPU-first):

- The rotation is a neighbor-exchange — on a TPU slice the ``seq`` mesh axis
  maps onto an ICI ring, so each hop is a nearest-neighbor transfer that
  overlaps with the local block matmul (XLA schedules the ppermute DMA
  concurrently with compute inside the scanned body).
- Causal masking uses *global* positions derived from ``lax.axis_index``, so
  fully-masked blocks still cost one fused matmul — acceptable because the
  dominant regime (n_shards ≪ T_local) amortizes; a skip via ``lax.cond``
  would break the static schedule XLA wants.
- GQA is supported (n_q a multiple of n_kv); K/V travel in their compact
  n_kv form so ring traffic is minimal (the GQA ratio also divides ring
  bandwidth cost by group size vs. MHA).

No reference counterpart: RunbookAI scales context *down* via compaction
(SURVEY.md §5.7); this module is the scale-*out* path the reference lacks.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30

SEQ_AXIS = "seq"


def _mark_varying(x, axis_name):
    """Mark an array device-varying over ``axis_name`` for shard_map's VMA check.

    Newer jax spells this ``lax.pcast(..., to='varying')``; older ``lax.pvary``;
    oldest shard_map has no VMA tracking at all.
    """
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        try:
            return pcast(x, (axis_name,), to="varying")
        except TypeError:
            pass
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, axis_name)
    return x


def _flash_block(qf, kb, vb, mask, m, l, acc):
    """One online-softmax accumulation step.

    qf:  [B, T, n_kv, group, d] scaled float32 queries
    kb:  [B, S, n_kv, d] keys for this block; vb same for values
    mask: [B, T, S] bool — True where attention is allowed
    m, l, acc: running max / normalizer / weighted-value accumulators
    """
    scores = jnp.einsum("btkgd,bskd->btkgs", qf, kb)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum("btkgs,bskd->btkgd", p, vb)
    return m_new, l_new, acc_new


def ring_attention_local(
    q: jnp.ndarray,  # [B, T_local, n_q, d] — this device's query shard
    k: jnp.ndarray,  # [B, T_local, n_kv, d]
    v: jnp.ndarray,  # [B, T_local, n_kv, d]
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    seg_ids: Optional[jnp.ndarray] = None,  # [B, T_local] segment ids (0 = pad)
) -> jnp.ndarray:
    """Ring attention body — call inside shard_map with the seq axis mapped.

    Returns this device's [B, T_local, n_q, d] output shard. With
    ``seg_ids`` given, attention is additionally blocked across segment
    boundaries (packed sequences) and pad (id 0) keys are masked out.
    """
    b, t_loc, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    n_shards = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qf = (q.astype(jnp.float32) * scale).reshape(b, t_loc, n_kv, group, d)
    q_pos = my_idx * t_loc + jnp.arange(t_loc)  # [T_local] global positions

    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    def block(m, l, acc, kb, vb, sb, hop):
        # After `hop` rotations we hold the shard originally on (my - hop) % n.
        src = (my_idx - hop) % n_shards
        k_pos = src * t_loc + jnp.arange(t_loc)
        mask = jnp.ones((b, t_loc, t_loc), dtype=bool)
        if causal:
            mask = mask & (k_pos[None, None, :] <= q_pos[None, :, None])
        if sb is not None:
            mask = mask & (sb[:, None, :] == seg_ids[:, :, None]) & (sb[:, None, :] > 0)
        return _flash_block(
            qf, kb.astype(jnp.float32), vb.astype(jnp.float32), mask, m, l, acc
        )

    def ring_step(carry, hop):
        m, l, acc, kb, vb, sb = carry
        m, l, acc = block(m, l, acc, kb, vb, sb, hop)
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        if sb is not None:
            sb = jax.lax.ppermute(sb, axis_name, perm)
        return (m, l, acc, kb, vb, sb), None

    m0 = jnp.full((b, t_loc, n_kv, group), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, t_loc, n_kv, group), dtype=jnp.float32)
    acc0 = jnp.zeros((b, t_loc, n_kv, group, d), dtype=jnp.float32)
    # The carry becomes device-varying after the first flash update, so the
    # init must be marked varying for shard_map's VMA tracking.
    m0, l0, acc0 = (_mark_varying(x, axis_name) for x in (m0, l0, acc0))
    # n_shards-1 rotated hops; the last shard is consumed without a rotation.
    (m, l, acc, kb, vb, sb), _ = jax.lax.scan(
        ring_step, (m0, l0, acc0, k, v, seg_ids), jnp.arange(n_shards - 1)
    )
    m, l, acc = block(m, l, acc, kb, vb, sb, n_shards - 1)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(b, t_loc, n_q, d).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, T, n_q, d] — global arrays (sharded by caller or not)
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
    causal: bool = True,
    seg_ids: Optional[jnp.ndarray] = None,  # [B, T]
) -> jnp.ndarray:
    """Shard q/k/v over ``mesh[axis_name]`` along T and run ring attention.

    Convenience entry for callers holding unsharded arrays; inside pjit
    programs prefer calling :func:`ring_attention_local` from your own
    shard_map with the rest of the layer.
    """
    spec = P(None, axis_name, None, None)
    seg_spec = P(None, axis_name)
    # Only the seq axis goes manual; data/model stay automatic so DP/TP
    # placements on the same mesh compose (older jax lacks axis_names — there
    # every axis is manual, which still works since specs leave them unused).
    kwargs = {}
    try:
        import inspect

        if "axis_names" in inspect.signature(shard_map).parameters:
            kwargs["axis_names"] = {axis_name}
    except (TypeError, ValueError):
        pass
    if seg_ids is None:
        fn = shard_map(
            partial(ring_attention_local, axis_name=axis_name, causal=causal),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            **kwargs,
        )
        return fn(q, k, v)

    def body(q, k, v, seg):
        return ring_attention_local(q, k, v, axis_name=axis_name, causal=causal,
                                    seg_ids=seg)

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
                   out_specs=spec, **kwargs)
    return fn(q, k, v, seg_ids)


def full_attention_reference(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = True,
    seg_ids: Optional[jnp.ndarray] = None,  # [B, T]
) -> jnp.ndarray:
    """Unsharded GQA attention — the numerics oracle for ring attention tests."""
    b, t, n_q, d = q.shape
    n_kv = k.shape[2]
    group = n_q // n_kv
    qf = (q.astype(jnp.float32) / jnp.sqrt(jnp.float32(d))).reshape(b, t, n_kv, group, d)
    scores = jnp.einsum("btkgd,bskd->btkgs", qf, k.astype(jnp.float32))
    mask = jnp.ones((b, t, t), dtype=bool)
    if causal:
        mask = mask & jnp.tril(jnp.ones((t, t), dtype=bool))[None]
    if seg_ids is not None:
        mask = mask & (seg_ids[:, None, :] == seg_ids[:, :, None]) & (seg_ids[:, None, :] > 0)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    attn = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", attn, v.astype(jnp.float32))
    return out.reshape(b, t, n_q, d).astype(q.dtype)
