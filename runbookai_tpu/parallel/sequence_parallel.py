"""Sequence-parallel transformer forward — the long-context execution path.

Shards the *token* axis of one (possibly very long) sequence batch across the
``seq`` mesh axis: every per-token op (embeds, norms, QKV/MLP matmuls, logits)
runs on the local shard untouched, and the only cross-device exchange is the
K/V rotation inside :func:`ring_attention_local`. Context length therefore
scales linearly with the number of chips on the ring — the scale-*out*
answer to the reference's scale-*down* compaction machinery (SURVEY.md §5.7).

Composes with TP on the same mesh: only the ``seq`` axis goes manual in the
shard_map (``axis_names``); ``data``/``model`` stay automatic, so TP-sharded
weights keep their ``parallel/sharding.py`` placements and XLA inserts the
TP collectives as usual.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from runbookai_tpu.models.llama import LlamaConfig, forward_train
from runbookai_tpu.parallel.mesh import SEQ_AXIS
from runbookai_tpu.parallel.ring_attention import ring_attention_local


def _forward_local(params, tokens, cfg: LlamaConfig, axis_name: str):
    """Transformer forward on a [B, T_local] token shard (inside shard_map).

    Reuses the dense ``forward_train`` layer stack verbatim — only positions
    (offset by the shard index) and the attention implementation (ring) differ.
    """
    b, t_loc = tokens.shape
    my_idx = jax.lax.axis_index(axis_name)
    positions = my_idx * t_loc + jnp.arange(t_loc, dtype=jnp.int32)[None, :]
    return forward_train(
        params, cfg, tokens,
        positions=jnp.broadcast_to(positions, (b, t_loc)),
        attn_fn=partial(ring_attention_local, axis_name=axis_name, causal=True),
    )


def forward_train_sp(
    params,
    cfg: LlamaConfig,
    tokens: jnp.ndarray,  # [B, T] with T divisible by the seq-axis size
    mesh: Mesh,
    axis_name: str = SEQ_AXIS,
) -> jnp.ndarray:
    """Dense causal forward with the sequence sharded over ``mesh[axis_name]``.

    Numerically equivalent to ``models.llama.forward_train`` (same params,
    same math); returns [B, T, vocab] float32 logits sharded along T.
    """
    tok_spec = P(None, axis_name)
    kwargs = {}
    try:
        import inspect

        if "axis_names" in inspect.signature(shard_map).parameters:
            # Manual over seq only — data/model placements stay automatic so
            # TP-sharded weights compose without gathering.
            kwargs["axis_names"] = {axis_name}
    except (TypeError, ValueError):
        pass
    fn = shard_map(
        partial(_forward_local, cfg=cfg, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(), tok_spec),
        out_specs=P(None, axis_name, None),
        **kwargs,
    )
    return fn(params, tokens)
