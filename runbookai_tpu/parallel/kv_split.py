"""KV page-split serving: TP past the GQA kv-head count (SURVEY §7 hard 4).

Problem: Megatron TP shards the KV pool on the kv-head axis, so tp is capped
at ``n_kv_heads`` — Llama-3-70B has 8 KV heads, and the v5e-16 target (tp16)
would replicate the entire page pool on every chip (r3 VERDICT weak #6).

The TPU-native fix factors the model parallelism into two mesh axes:

- ``model`` (= ``kv_shards``): shards KV heads, exactly as before.
- ``seq``  (= ``pg_shards``): shards the page pool's TOKEN axis — each
  device owns a contiguous block of physical pages and attends only over
  context tokens stored there.

Query heads shard over BOTH axes (model-major, so every query stays next to
its GQA kv head); each device computes flash partials ``(m, l, acc)`` over
its own pages, and the partials merge across the ``seq`` axis with three
tiny collectives (pmax + 2 psum — payload is B·T·heads·(hd+2) floats, riding
ICI). ``wq``/``wo``/FFN shard over the combined ``(model, seq)`` axes (full
tp-way weight split); ``wk``/``wv`` shard over ``model`` only — their output
is needed by every page shard of the same kv group.

Alignment requirement: ``group % pg_shards == 0`` (so a device's query heads
all map to its kv head). Llama-3-70B: group 8, pg_shards 2 — fine.

This is the serving-side analogue of ring attention's KV sharding
(``parallel/ring_attention.py`` is the train-side one): same math (merge of
flash partials), different topology (static page ownership + psum instead of
a rotating ring — pages are randomly interleaved across shards by the
allocator, so load balance is statistical rather than positional).

No reference counterpart: RunbookAI calls hosted LLM APIs (SURVEY §2.2).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from runbookai_tpu.parallel.mesh import MODEL_AXIS, SEQ_AXIS

NEG_INF = -1e30


@dataclass(frozen=True)
class KVSplitPlan:
    """How a requested tp factor maps onto (kv_shards, pg_shards)."""

    tp: int
    kv_shards: int  # shards of the KV-head axis  -> mesh 'model'
    pg_shards: int  # shards of the page/token axis -> mesh 'seq'

    @property
    def split(self) -> bool:
        return self.pg_shards > 1


def plan_kv_split(cfg, tp: int) -> KVSplitPlan:
    """Decide the KV layout for ``tp``-way model parallelism.

    tp <= n_kv_heads (divisible): pure head sharding, pg_shards = 1 — the
    existing layout. Otherwise shard heads as far as they go and put the
    remaining factor on the page axis, validating the GQA alignment. This
    replaces the r3 replication *warning* with a planned layout: per-chip
    KV bytes always shrink by the full tp factor.
    """
    if tp <= 1:
        return KVSplitPlan(tp=tp, kv_shards=max(tp, 1), pg_shards=1)
    kv_shards = math.gcd(cfg.n_kv_heads, tp)
    pg_shards = tp // kv_shards
    group = cfg.n_heads // cfg.n_kv_heads
    if pg_shards > 1:
        if cfg.n_heads % tp != 0:
            raise ValueError(
                f"n_heads={cfg.n_heads} not divisible by tp={tp}")
        if group % pg_shards != 0:
            raise ValueError(
                f"KV split needs group ({group}) % pg_shards "
                f"({pg_shards}) == 0 so each device's query heads share "
                f"its kv head; use tp <= {cfg.n_kv_heads * group}")
    return KVSplitPlan(tp=tp, kv_shards=kv_shards, pg_shards=pg_shards)


# ------------------------------------------------------------------ specs

def q_heads_spec() -> P:
    """Query-head axis: model-major over both axes (head h sits on model
    shard h // (n_heads/kv_shards) — next to its GQA kv head)."""
    return P(None, None, (MODEL_AXIS, SEQ_AXIS), None)


def kv_pool_split_sharding(mesh: Mesh) -> NamedSharding:
    """[L, tokens, n_kv, hd]: tokens page-sharded over seq, heads over
    model."""
    return NamedSharding(mesh, P(None, SEQ_AXIS, MODEL_AXIS, None))


# ------------------------------------------------------------- attention

def _partial_flash(
    q,  # [B, T, nql, d] — this device's query heads
    k_loc,  # [tokens_local, nkvl, d] — this device's page slice
    v_loc,
    page_tables,  # [B, max_pages] GLOBAL physical page ids
    ctx_lens,  # [B]
    q_positions,  # [B, T]
    page_size: int,
    block_pages: int,
    pages_local: int,
    my_pg,  # scalar int32 — this device's page-shard index
):
    """Flash partials over locally-owned pages. Mirrors
    ``ops.attention.paged_attention`` exactly, plus a page-ownership mask
    (physical page p lives on shard p // pages_local) and local gather
    indices; returns un-normalized ``(m, l, acc)`` for the seq-axis merge.
    """
    b, t, nql, d = q.shape
    nkvl = k_loc.shape[1]
    group = nql // nkvl
    max_pages = page_tables.shape[1]
    n_blocks = max(1, (max_pages + block_pages - 1) // block_pages)
    block_tokens = block_pages * page_size

    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    qf = q.astype(jnp.float32) * scale
    qf = qf.reshape(b, t, nkvl, group, d)

    def block_step(carry, blk):
        m, l, acc = carry
        page_idx = blk * block_pages + jnp.arange(block_pages)
        phys_blk = jnp.take_along_axis(
            page_tables,
            jnp.broadcast_to(page_idx[None, :], (b, block_pages)) % max_pages,
            axis=1)  # [B, block_pages] global page ids
        owned_pg = (phys_blk // pages_local) == my_pg  # [B, block_pages]
        local_pg = jnp.clip(phys_blk - my_pg * pages_local,
                            0, pages_local - 1)
        token_off = jnp.arange(block_tokens)
        flat_idx = (local_pg[:, token_off // page_size] * page_size
                    + token_off % page_size)  # [B, block_tokens]
        kb = k_loc[flat_idx].astype(jnp.float32)  # [B, bt, nkvl, d]
        vb = v_loc[flat_idx].astype(jnp.float32)

        cache_pos = blk * block_tokens + token_off
        valid = (cache_pos[None, :] < ctx_lens[:, None])[:, None, :]
        causal = cache_pos[None, None, :] <= q_positions[:, :, None]
        owned = owned_pg[:, token_off // page_size][:, None, :]  # [B,1,bt]
        mask = (valid & causal & owned)[:, :, None, None, :]

        scores = jnp.einsum("btkgd,bskd->btkgs", qf, kb)
        scores = jnp.where(mask, scores, NEG_INF)

        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        # Zero fully-masked probabilities explicitly: rows where m stays
        # NEG_INF would otherwise contribute exp(0)=1 per masked token
        # (mask [B,T,1,1,block] broadcasts over kv-head/group).
        p = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "btkgs,bskd->btkgd", p, vb)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, t, nkvl, group), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((b, t, nkvl, group), dtype=jnp.float32)
    acc0 = jnp.zeros((b, t, nkvl, group, d), dtype=jnp.float32)
    # scan carries inside shard_map must be marked device-varying up front
    # (the body output varies over the mesh axes; jax requires the init to
    # match). _mark_varying handles the pcast/pvary API generations.
    from runbookai_tpu.parallel.ring_attention import _mark_varying

    m0, l0, acc0 = (_mark_varying(_mark_varying(x, SEQ_AXIS), MODEL_AXIS)
                    for x in (m0, l0, acc0))
    (m, l, acc), _ = jax.lax.scan(block_step, (m0, l0, acc0),
                                  jnp.arange(n_blocks))
    return m, l, acc


def paged_attention_kv_split(
    mesh: Mesh,
    q: jnp.ndarray,  # [B, T, n_q, hd] (sharded (model, seq) on heads)
    k_flat: jnp.ndarray,  # [tokens, n_kv, hd] (seq on tokens, model on heads)
    v_flat: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, max_pages] (replicated)
    ctx_lens: jnp.ndarray,  # [B]
    q_positions: jnp.ndarray,  # [B, T]
    page_size: int,
    block_pages: int = 32,
) -> jnp.ndarray:
    """Paged attention over the (kv-head × page)-sharded pool.

    Each device flashes over its page slice; partials merge across the
    ``seq`` axis with pmax/psum (the ring-attention merge identity), so
    the result equals unsharded :func:`ops.attention.paged_attention`.
    """
    pg_shards = mesh.shape.get(SEQ_AXIS, 1)
    tokens_global = k_flat.shape[0]
    num_pages = tokens_global // page_size
    if num_pages % pg_shards != 0:
        # A page straddling the shard boundary would be silently
        # mis-owned (floored pages_local) — wrong attention, no error.
        raise ValueError(
            f"num_pages={num_pages} must divide by pg_shards={pg_shards}")
    pages_local = num_pages // pg_shards

    def local_fn(q_l, k_l, v_l, tables, ctx, qpos):
        my_pg = jax.lax.axis_index(SEQ_AXIS)
        nql = q_l.shape[2]
        # Every page shard must flash the SAME query heads for the merge
        # to be head-aligned, so gather the model-shard's full head set
        # across ``seq`` (tiny payload: B·T·group·hd). Each chip still
        # reads only its own page slice — the bandwidth term, which is
        # what decode is bound by — and GQA reuses those K/V bytes across
        # all gathered heads.
        q_full = jax.lax.all_gather(q_l, SEQ_AXIS, axis=2, tiled=True)
        m, l, acc = _partial_flash(
            q_full, k_l, v_l, tables, ctx, qpos, page_size=page_size,
            block_pages=block_pages, pages_local=pages_local, my_pg=my_pg)
        m_g = jax.lax.pmax(m, SEQ_AXIS)
        corr = jnp.exp(m - m_g)
        l_g = jax.lax.psum(l * corr, SEQ_AXIS)
        acc_g = jax.lax.psum(acc * corr[..., None], SEQ_AXIS)
        out = acc_g / jnp.maximum(l_g[..., None], 1e-30)
        b, t, nkvl, group, d = out.shape
        out = out.reshape(b, t, nkvl * group, d).astype(q_l.dtype)
        # Keep this device's own head slice (model-major tuple sharding:
        # within a model shard, seq-coordinate s owns heads [s·nql, ...)).
        return jax.lax.dynamic_slice_in_dim(out, my_pg * nql, nql, axis=2)

    kv_spec = P(SEQ_AXIS, MODEL_AXIS, None)
    rep = P(None, None)
    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(q_heads_spec(), kv_spec, kv_spec, rep, P(None), rep),
        out_specs=q_heads_spec(),
    )(q, k_flat, v_flat, page_tables, ctx_lens, q_positions)


def paged_decode_attention_kv_split_pallas(
    mesh: Mesh,
    q: jnp.ndarray,  # [B, n_q, hd] (T=1 decode shape, heads (model,seq))
    k_flat: jnp.ndarray,
    v_flat: jnp.ndarray,
    page_tables: jnp.ndarray,
    ctx_lens: jnp.ndarray,
    page_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Decode attention on the page-split pool via the Pallas partial
    kernel: each device runs ``_decode_kernel_partial`` over its OWN page
    slice (ownership-masked, locally-indexed scalar-prefetch maps) and
    the flash partials merge across ``seq`` exactly like the XLA path."""
    from runbookai_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_partial,
    )

    pg_shards = mesh.shape.get(SEQ_AXIS, 1)
    num_pages = k_flat.shape[0] // page_size
    if num_pages % pg_shards != 0:
        raise ValueError(
            f"num_pages={num_pages} must divide by pg_shards={pg_shards}")
    pages_local = num_pages // pg_shards

    def local_fn(q_l, k_l, v_l, tables, ctx):
        my_pg = jax.lax.axis_index(SEQ_AXIS)
        nql = q_l.shape[1]
        q_full = jax.lax.all_gather(q_l, SEQ_AXIS, axis=1, tiled=True)
        acc, m, l = paged_decode_attention_partial(
            q_full, k_l, v_l, tables, ctx, my_pg.astype(jnp.int32),
            page_size=page_size, pages_local=pages_local,
            interpret=interpret)
        m_g = jax.lax.pmax(m, SEQ_AXIS)
        corr = jnp.exp(m - m_g)
        l_g = jax.lax.psum(l * corr, SEQ_AXIS)
        acc_g = jax.lax.psum(acc * corr[..., None], SEQ_AXIS)
        out = (acc_g / jnp.maximum(l_g[..., None], 1e-30)).astype(q_l.dtype)
        return jax.lax.dynamic_slice_in_dim(out, my_pg * nql, nql, axis=1)

    heads = P(None, (MODEL_AXIS, SEQ_AXIS), None)
    kv_spec = P(SEQ_AXIS, MODEL_AXIS, None)
    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(heads, kv_spec, kv_spec, P(None, None), P(None)),
        out_specs=heads,
        check_vma=False,  # pallas out_shapes carry no vma info
    )(q, k_flat, v_flat, page_tables, ctx_lens)


# ----------------------------------------------------------------- write

def write_kv_pages_batch_kv_split(
    mesh: Mesh,
    kv_flat: jnp.ndarray,  # [tokens, n_kv, hd] (seq × model sharded)
    new_kv: jnp.ndarray,  # [B, T, n_kv, hd] (model-sharded heads)
    positions: jnp.ndarray,  # [B, T] (replicated)
    page_tables: jnp.ndarray,  # [B, max_pages(+1)] (replicated)
    page_size: int,
) -> jnp.ndarray:
    """Batch K/V scatter where each device keeps only writes landing in
    its own page slice (out-of-slice destinations drop — they are some
    other device's writes)."""
    pg_shards = mesh.shape.get(SEQ_AXIS, 1)
    if (kv_flat.shape[0] // page_size) % pg_shards != 0:
        raise ValueError(
            f"num_pages={kv_flat.shape[0] // page_size} must divide by "
            f"pg_shards={pg_shards}")
    tokens_local = kv_flat.shape[0] // pg_shards

    def local_fn(kv_l, new_l, pos, tables):
        my_pg = jax.lax.axis_index(SEQ_AXIS)
        b, t = pos.shape
        logical_page = pos // page_size
        offset = pos % page_size
        phys = jnp.take_along_axis(tables, logical_page, axis=1)
        dest = (phys * page_size + offset).reshape(b * t)
        local = dest - my_pg * tokens_local
        # Foreign destinations must map to an out-of-bounds-HIGH sentinel:
        # mode='drop' only drops high indices — a negative index wraps
        # Python-style and would corrupt this shard's mirror slot.
        in_slice = (local >= 0) & (local < tokens_local)
        local = jnp.where(in_slice, local, tokens_local)
        flat_new = new_l.reshape((b * t,) + new_l.shape[2:])
        return kv_l.at[local].set(flat_new.astype(kv_l.dtype), mode="drop")

    kv_spec = P(SEQ_AXIS, MODEL_AXIS, None)
    return jax.shard_map(
        local_fn, mesh=mesh,
        in_specs=(kv_spec, P(None, None, MODEL_AXIS, None), P(None, None),
                  P(None, None)),
        out_specs=kv_spec,
    )(kv_flat, new_kv, positions, page_tables)
