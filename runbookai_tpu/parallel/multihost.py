"""Multi-host bootstrap: one call turns N processes into one global mesh.

SURVEY.md §5.8: the reference's "distributed backend" is HTTPS to SaaS — the
TPU-native equivalent at multi-host scale is ``jax.distributed`` (one
process per host, each owning its local chips) plus the same
``jax.sharding.Mesh`` axes this repo uses single-host. After
:func:`initialize`, ``jax.devices()`` is the GLOBAL device list and
``build_mesh`` lays axes out so that the fastest-varying axes (``model``,
``seq``) stay within a host's ICI domain while ``data`` (gradient/eval
batching — one psum per step) crosses hosts over DCN, matching the
scaling-book guidance that high-frequency collectives must ride ICI.

Coordinator discovery follows the TPU-pod convention: every process reads
the same env (set by GKE/QR metadata or the launcher) —

    RUNBOOK_COORDINATOR   host:port of process 0 (or JAX_COORDINATOR_ADDRESS)
    RUNBOOK_NUM_PROCESSES world size             (or JAX_NUM_PROCESSES)
    RUNBOOK_PROCESS_ID    this process's rank    (or JAX_PROCESS_ID)

On Cloud TPU VMs all three are optional: ``jax.distributed.initialize()``
auto-discovers from the TPU metadata server.
"""

from __future__ import annotations

import os
from typing import Optional

import jax


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> dict:
    """Join (or create) the multi-process JAX runtime. Idempotent; a no-op
    single-process fallback when no coordinator is configured or
    discoverable, so single-host code paths need no branching.

    Returns a summary dict (``process_index``, ``process_count``,
    ``local_devices``, ``global_devices``) for logs/health endpoints.
    """
    coordinator = coordinator or os.environ.get(
        "RUNBOOK_COORDINATOR") or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else int(
        os.environ.get("RUNBOOK_NUM_PROCESSES")
        or os.environ.get("JAX_NUM_PROCESSES") or 0) or None
    process_id = process_id if process_id is not None else (
        int(os.environ.get("RUNBOOK_PROCESS_ID")
            or os.environ.get("JAX_PROCESS_ID") or -1))
    if process_id < 0:
        process_id = None

    # Probe the distributed client WITHOUT touching the backend:
    # jax.process_count() would initialize the local runtime first, after
    # which jax.distributed.initialize() is an error — the exact multi-host
    # path this module exists for would always fail at bootstrap.
    if not _distributed_client_active() and (coordinator
                                             or _on_cloud_tpu_pod()):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    return summary()


def _distributed_client_active() -> bool:
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — private API moved; assume inactive
        return False


def _on_cloud_tpu_pod() -> bool:
    """Cloud TPU pod VMs auto-discover peers from instance metadata; the
    launcher env markers below are what libtpu's own bootstrap keys off."""
    return bool(os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",")
                or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))


def summary() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def local_replica_range(dp_replicas: int) -> range:
    """Engine-fleet replica indices THIS host owns.

    A pod-wide fleet of ``dp_replicas`` engines is partitioned statically:
    process ``p`` of ``P`` builds replicas ``[p*dp/P, (p+1)*dp/P)`` over its
    ``jax.local_devices()`` — replicas never span hosts (their device slices
    must stay within one ICI domain), so an indivisible count is a config
    error, same policy as :func:`assert_batch_divisible`.
    """
    pc = jax.process_count()
    if dp_replicas % pc:
        raise ValueError(
            f"dp_replicas {dp_replicas} not divisible by process count {pc}")
    per = dp_replicas // pc
    start = jax.process_index() * per
    return range(start, start + per)


def shard_for_host() -> tuple[int, int]:
    """Static ``(index, count)`` benchmark shard for this process — the
    ``--shard auto`` source for ``evalsuite/run_all.py``: each host takes
    cases ``index::count`` before its local fleet balances dynamically."""
    return jax.process_index(), jax.process_count()


def assert_batch_divisible(global_batch: int, data_axis_size: int) -> int:
    """Per-process batch share for the host-sharded input pipeline: each
    process feeds only its local slice of the ``data`` axis (global arrays
    assemble via ``jax.make_array_from_process_local_data``)."""
    if global_batch % data_axis_size:
        raise ValueError(
            f"global batch {global_batch} not divisible by data axis "
            f"{data_axis_size}")
    per_data_shard = global_batch // data_axis_size
    if data_axis_size % jax.process_count():
        # A fallback here would silently feed duplicated data; indivisible
        # topologies are config errors.
        raise ValueError(
            f"data axis {data_axis_size} not divisible by process count "
            f"{jax.process_count()}")
    shards_here = data_axis_size // jax.process_count()
    return per_data_shard * shards_here
