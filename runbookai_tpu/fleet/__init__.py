"""Multi-model fleet serving (ROADMAP item 5a).

One ``AsyncFleet``-compatible surface over named model groups: replicas
are partitioned per served model (``llm.models`` — each group built from
its own derived ``LLMConfig``/serving plan), the router dispatches on
the request's ``model`` field into the owning group's prefix-affinity /
least-loaded placement, ``GET /v1/models`` lists the full catalog, and
every metric/flight-record/health row carries the model it serves.

- :mod:`runbookai_tpu.fleet.multimodel` — :class:`MultiModelFleet` /
  :class:`ModelGroup`, the engine-level facade.
- :mod:`runbookai_tpu.fleet.build` — config -> cores: the ONE engine
  construction path (also used by the single-model client), group
  config derivation, global replica index / device carving.

The single-model path is untouched by construction: ``llm.models``
absent means ``JaxTpuClient.from_config`` builds exactly the classic
engine or dp fleet (parity pinned in tests/test_multimodel.py).
"""

from runbookai_tpu.fleet.build import (
    BuiltGroup,
    build_group,
    build_multi_model_fleet,
    derive_group_llm,
)
from runbookai_tpu.fleet.multimodel import (
    CURRENT_MODEL,
    ModelGroup,
    MultiModelFleet,
)

__all__ = [
    "BuiltGroup",
    "build_group",
    "build_multi_model_fleet",
    "derive_group_llm",
    "CURRENT_MODEL",
    "ModelGroup",
    "MultiModelFleet",
]
