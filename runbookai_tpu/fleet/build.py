"""Model-group construction: ``LLMConfig`` -> engine cores, once.

This module owns THE engine-construction path — the code that used to
live inline in ``JaxTpuClient.from_config``. The single-model client and
the multi-model fleet both call :func:`build_group`, so there is exactly
one place where a config's plan is applied, weights are discovered,
meshes are planned, and cores are built — multi-model serving cannot
drift from the single-model path it must stay byte-identical to.

Multi-model (``llm.models``): each group entry derives its own
``LLMConfig`` from the base ``llm`` block (:func:`derive_group_llm`;
group ``overrides`` beat the group ``plan`` beat the base — the same
explicit-beats-plan precedence as ``llm.plan``),
:func:`build_multi_model_fleet` assigns GLOBAL replica indices
contiguously across groups, carves the host's devices into disjoint
per-group slices when there are enough, and fronts each group's cores
with an :class:`~runbookai_tpu.engine.fleet.AsyncFleet` labeled with the
group's served name.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

from runbookai_tpu.engine.engine import (
    EngineConfig,
    EngineCore,
    resolve_kv_dtype,
)
from runbookai_tpu.fleet.multimodel import ModelGroup, MultiModelFleet


@dataclass
class BuiltGroup:
    """One constructed model group (or the whole single-model build)."""

    cores: list[EngineCore]
    tokenizer: Any
    chat_format: str
    model_cfg: Any           # LlamaConfig actually loaded
    llm_cfg: Any             # the (plan-applied) LLMConfig it was built from
    fleet_cfg: Optional[Any] = None   # engine.fleet.FleetConfig or None
    lora_registry: Optional[Any] = None

    @property
    def core(self) -> EngineCore:
        return self.cores[0]


def apply_group_plan(llm_cfg):
    """Resolve ``llm.plan`` onto the config (explicit YAML keys keep
    winning — ``autotune.plan.apply_plan_to_llm``); returns the
    (possibly) rewritten config and the loaded plan (or ``None``)."""
    serving_plan = None
    if getattr(llm_cfg, "plan", None):
        from runbookai_tpu.autotune.plan import apply_plan_to_llm, load_plan

        serving_plan = load_plan(llm_cfg.plan)
        if serving_plan.model != llm_cfg.model:
            raise ValueError(
                f"llm.plan {serving_plan.plan_id!r} was tuned for "
                f"model {serving_plan.model!r}, not {llm_cfg.model!r} "
                f"— plans are per model×topology; re-run `runbook tune`")
        llm_cfg = apply_plan_to_llm(llm_cfg, serving_plan)
    return llm_cfg, serving_plan


def build_group(llm_cfg, *,
                replica_indices: Optional[Sequence[int]] = None,
                devices: Optional[Sequence[Any]] = None,
                pin_devices: bool = False) -> BuiltGroup:
    """Build one model's engine cores from its ``LLMConfig``.

    With ``replica_indices=None`` this is exactly the historical
    single-model construction (including the multihost pod split and the
    TP/mesh path). A multi-model caller passes the group's GLOBAL
    replica indices and its carved device slice instead — group builds
    always go through ``build_engine_fleet`` (even dp=1) so every
    replica carries its global index and, with ``pin_devices``, owns its
    device slice.
    """
    import jax
    import jax.numpy as jnp

    from runbookai_tpu.model.chat_template import format_for_model
    from runbookai_tpu.model.guided import JsonMaskProvider
    from runbookai_tpu.model.schema_guided import orchestrator_schemas
    from runbookai_tpu.models.hf_loader import load_or_init
    from runbookai_tpu.utils.tokens import load_tokenizer
    from runbookai_tpu.utils.weights import discover_weights

    llm_cfg, serving_plan = apply_group_plan(llm_cfg)
    model_path = discover_weights(llm_cfg.model, llm_cfg.model_path)
    tokenizer = load_tokenizer(llm_cfg.tokenizer_path or model_path)
    mesh = None
    shardings = None
    model_cfg_name = llm_cfg.model
    # int8 = weight-only quantization; activations and KV stay bf16.
    quantize = llm_cfg.dtype == "int8"
    dtype = jnp.float32 if llm_cfg.dtype == "float32" else jnp.bfloat16
    dp_replicas = max(1, getattr(llm_cfg, "dp_replicas", 1))
    if dp_replicas > 1 and llm_cfg.mesh.device_count > 1:
        # Replicas are single-slice engines; sharding a model WITHIN a
        # replica on top of dp is a later composition — refuse loudly
        # rather than silently building N full-mesh engines that all
        # claim the same devices.
        raise ValueError(
            "llm.dp_replicas > 1 requires llm.mesh.data/model = 1 "
            "(each fleet replica owns its own device slice)")
    if llm_cfg.mesh.device_count > 1:
        from runbookai_tpu.models.llama import CONFIGS
        from runbookai_tpu.parallel.kv_split import plan_kv_split
        from runbookai_tpu.parallel.mesh import build_mesh
        from runbookai_tpu.parallel.sharding import param_shardings

        # KV layout planning: tp past the GQA head count factors onto
        # (model=kv_shards, seq=pg_shards) so the page pool shards by
        # the FULL tp (parallel/kv_split.py) instead of replicating.
        plan = (plan_kv_split(CONFIGS[llm_cfg.model], llm_cfg.mesh.model)
                if llm_cfg.model in CONFIGS else None)
        if plan is not None and plan.split:
            mesh = build_mesh(llm_cfg.mesh.data, model=plan.kv_shards,
                              seq=plan.pg_shards)
        else:
            mesh = build_mesh(llm_cfg.mesh.data, llm_cfg.mesh.model)
        if model_cfg_name in CONFIGS:
            shardings = param_shardings(CONFIGS[model_cfg_name], mesh)
            if quantize:
                from runbookai_tpu.models.quant import shardings_with_quant

                shardings = shardings_with_quant(shardings)
    cfg, params = load_or_init(
        model_cfg_name, model_path, dtype=dtype, shardings=shardings,
        quantize_int8=quantize,
    )
    kv_dtype = resolve_kv_dtype(llm_cfg.kv_cache_dtype, dtype)
    ecfg = EngineConfig(
        page_size=llm_cfg.page_size,
        num_pages=llm_cfg.num_pages,
        max_batch_slots=llm_cfg.max_batch_slots,
        prefill_chunk=llm_cfg.prefill_chunk,
        max_seq_len=min(llm_cfg.max_seq_len, cfg.max_seq_len),
        kv_dtype=kv_dtype,
        decode_steps_per_dispatch=llm_cfg.decode_steps,
        # The Pallas ragged-paged kernels are the TPU hot path (VERDICT r1
        # weak #3); the XLA gather path stays the portable fallback. On a
        # TP mesh the kernels run per head-shard via shard_map
        # (ops/paged_attention_pallas.py) — forward_impl itself falls
        # back to XLA attention only when GQA heads don't divide the
        # model axis (where the pool replicates anyway).
        attn_impl=(llm_cfg.attn_impl if llm_cfg.attn_impl != "auto"
                   else ("pallas"
                         if jax.default_backend() in ("tpu", "axon")
                         else "xla")),
        # The Pallas quantized matmul streams int8 weight tiles (half
        # the bf16 HBM bytes, the decode bound) — on-TPU default for
        # int8 weights; meaningless for unquantized ones.
        qmm_impl=(llm_cfg.qmm_impl if llm_cfg.qmm_impl != "auto"
                  else ("pallas"
                        if quantize and jax.default_backend()
                        in ("tpu", "axon")
                        else "xla")),
        dp_replicas=dp_replicas,
        kv_spill_pages=getattr(llm_cfg, "kv_spill_pages", 0),
    )
    sched_cfg = getattr(llm_cfg, "sched", None)
    if sched_cfg is not None:
        # Priority-class scheduling policy (llm.sched → sched/wdrr.py):
        # the weighted-deficit interleave by default, with the two
        # canonical class weights from config.
        import dataclasses as _dc

        from runbookai_tpu.sched import PRIORITY_BATCH, PRIORITY_INTERACTIVE

        ecfg = _dc.replace(
            ecfg, sched_policy=sched_cfg.policy,
            sched_weights={
                PRIORITY_BATCH: sched_cfg.batch_weight,
                PRIORITY_INTERACTIVE: sched_cfg.interactive_weight,
            })
    if serving_plan is not None:
        from runbookai_tpu.autotune.plan import engine_only_overrides

        # Plan keys with no llm.* spelling (speculative,
        # mixed_token_budget, prefill_batch, block_pages, …) apply
        # straight onto the engine config. (Named serving_plan: the
        # TP branch above rebinds `plan` to a KVSplitPlan.)
        overrides = engine_only_overrides(serving_plan)
        if overrides:
            import dataclasses as _dc

            ecfg = _dc.replace(ecfg, **overrides)
    lora_registry = None
    if getattr(llm_cfg, "lora_adapters", None):
        from runbookai_tpu.models.lora import LoraRegistry

        lora_registry = LoraRegistry(
            cfg, rank=llm_cfg.lora_rank,
            targets=tuple(llm_cfg.lora_targets), dtype=dtype)
        for name, path in llm_cfg.lora_adapters.items():
            lora_registry.load_peft_dir(name, path)
    draft_factory = None
    if llm_cfg.draft_model:
        from runbookai_tpu.engine.draft import DraftWorker

        dcfg, dparams = load_or_init(
            llm_cfg.draft_model, llm_cfg.draft_model_path, dtype=dtype)

        def draft_factory(_idx: int) -> "DraftWorker":
            # One worker per replica: its slot/page state is
            # per-engine and cannot be shared across cores.
            return DraftWorker(
                dcfg, dparams, max_batch_slots=ecfg.max_batch_slots,
                max_seq_len=ecfg.max_seq_len, page_size=ecfg.page_size,
                attn_impl=ecfg.attn_impl)
    masker = JsonMaskProvider(tokenizer, schemas=orchestrator_schemas())
    fleet_cfg = None
    if dp_replicas > 1 or replica_indices is not None:
        from runbookai_tpu.engine.fleet import FleetConfig

        router = getattr(llm_cfg, "fleet", None)
        if router is not None:
            disagg = getattr(router, "disagg", None)
            disagg_n = (disagg.prefill_replicas
                        if disagg is not None and disagg.enabled else 0)
            fleet_cfg = FleetConfig(
                affinity=router.affinity,
                affinity_load_slack=router.affinity_load_slack,
                shed_queue_depth=router.shed_queue_depth,
                max_retries=router.max_retries,
                kv_share=getattr(router, "kv_share", False),
                kv_share_min_pages=getattr(router, "kv_share_min_pages", 1),
                disagg_prefill_replicas=disagg_n,
                disagg_min_prompt_pages=(disagg.min_prompt_pages
                                         if disagg_n else 1),
                retry_backoff_base=getattr(router, "retry_backoff_base",
                                           0.05),
                retry_backoff_max=getattr(router, "retry_backoff_max",
                                          2.0))
    if replica_indices is not None:
        # Multi-model group build: cores always come from
        # build_engine_fleet so each carries its GLOBAL replica index
        # (request-id namespace, metric labels) and — with enough
        # devices — its own pinned slice, dp=1 groups included.
        from runbookai_tpu.engine.fleet import build_engine_fleet

        cores = build_engine_fleet(
            cfg, params, tokenizer, ecfg,
            mask_fn=masker.mask, advance_fn=masker.advance,
            lora_registry=lora_registry,
            draft_worker_factory=draft_factory,
            devices=devices,
            replica_indices=list(replica_indices),
            pin_devices=pin_devices,
        )
    elif dp_replicas > 1:
        from runbookai_tpu.engine.fleet import build_engine_fleet

        # Pod scale-out: each process builds only ITS replicas over
        # its local chips — replicas never span hosts (their device
        # slices must stay in one ICI domain). Single process owns
        # the whole fleet over the (== local) global device list.
        host_indices = None
        fleet_devices = None
        if jax.process_count() > 1:
            from runbookai_tpu.parallel.multihost import local_replica_range

            host_indices = list(local_replica_range(dp_replicas))
            fleet_devices = jax.local_devices()
        cores = build_engine_fleet(
            cfg, params, tokenizer, ecfg,
            mask_fn=masker.mask, advance_fn=masker.advance,
            lora_registry=lora_registry,
            draft_worker_factory=draft_factory,
            devices=fleet_devices,
            replica_indices=host_indices,
        )
    else:
        cores = [EngineCore(
            cfg, params, tokenizer, ecfg,
            mask_fn=masker.mask, advance_fn=masker.advance, mesh=mesh,
            lora_registry=lora_registry,
            draft_worker=draft_factory(0) if draft_factory else None,
        )]
    return BuiltGroup(
        cores=cores, tokenizer=tokenizer,
        chat_format=format_for_model(model_cfg_name, cfg.family),
        model_cfg=cfg, llm_cfg=llm_cfg, fleet_cfg=fleet_cfg,
        lora_registry=lora_registry)


def wire_feedback(cores: Sequence[EngineCore], llm_cfg,
                  slo_monitor) -> None:
    """SLO feedback controllers (llm.sched.feedback → sched/feedback.py):
    one per core — each core's prefill share is its own actuator, all
    reading the same process-wide TPOT burn. No-op when feedback is off;
    a feedback config without the tpot_p95_ms objective raises here (an
    open loop labeled closed is worse than failing)."""
    sched_cfg = getattr(llm_cfg, "sched", None)
    if sched_cfg is None or not getattr(sched_cfg, "feedback", False):
        return
    from runbookai_tpu.sched import MixedBudgetController

    for core in cores:
        core.feedback = MixedBudgetController.for_core(sched_cfg,
                                                       slo_monitor)


def derive_group_llm(base, entry):
    """Group entry -> the group's own ``LLMConfig``.

    ``model_copy(update=...)`` keeps the base block's explicitly-set
    keys in ``model_fields_set`` and adds the group's — so the group
    plan's apply (which only fills UNSET keys) sees exactly the intended
    precedence: group overrides > base explicit YAML > group plan >
    defaults. The derived config is re-validated as a whole (and the
    COERCED result returned, with the copy's fields_set restored — a
    YAML-quoted "512" must land as int 512, and a typo'd value must
    fail here at load, not at engine build)."""
    from runbookai_tpu.utils.config import RESERVED_GROUP_OVERRIDE_KEYS

    reserved = RESERVED_GROUP_OVERRIDE_KEYS & set(entry.overrides)
    if reserved:
        raise ValueError(
            f"llm.models[{entry.name!r}].overrides cannot set "
            f"{sorted(reserved)} — these are group-entry fields "
            f"(set them on the entry itself)")
    update: dict[str, Any] = {
        "model": entry.model or entry.name,
        "dp_replicas": entry.dp_replicas,
        "plan": entry.plan,
        "models": [],
    }
    if entry.model_path is not None:
        update["model_path"] = entry.model_path
    if entry.tokenizer_path is not None:
        update["tokenizer_path"] = entry.tokenizer_path
    update["lora_adapters"] = dict(entry.adapters)
    update.update(entry.overrides)
    derived = base.model_copy(update=update)
    # Whole-config validation (model_copy skips it): coerce/check the
    # override values against the pydantic field types, and KEEP the
    # coerced model. Its fields_set would claim every field explicit, so
    # restore the copy's — the plan-precedence bookkeeping.
    # warnings=False: the pre-coercion copy may hold YAML-typed values
    # (that is the point — model_validate below coerces or rejects them).
    coerced = type(base).model_validate(derived.model_dump(warnings=False))
    object.__setattr__(coerced, "__pydantic_fields_set__",
                       set(derived.model_fields_set))
    return coerced


def build_multi_model_fleet(llm_cfg, slo_monitor=None) -> MultiModelFleet:
    """``llm.models`` -> a :class:`MultiModelFleet`.

    Global replica indices are assigned contiguously in list order
    (group 0 gets ``r0..``, the next group continues), and the host's
    devices are carved into disjoint per-group slices when there are at
    least as many devices as total replicas — otherwise every group
    timeshares the default device (the CPU tier-1 case).
    """
    import jax

    entries = list(getattr(llm_cfg, "models", None) or [])
    if not entries:
        raise ValueError("llm.models is empty — nothing to serve")
    if jax.process_count() > 1:
        raise ValueError(
            "llm.models does not compose with multihost pods yet "
            "(per-group host placement is a later composition)")
    if llm_cfg.mesh.device_count > 1:
        raise ValueError(
            "llm.models requires llm.mesh.data/model = 1 (each group "
            "replica owns its own device slice; TP within a group is a "
            "later composition)")
    names = [e.name for e in entries]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate served model names in llm.models: "
                         f"{names}")
    total = sum(max(1, e.dp_replicas) for e in entries)
    all_devices = list(jax.devices())
    carve = len(all_devices) >= total
    if not carve:
        # Too few devices for disjoint per-group slices: EVERY replica
        # timeshares the default device (devices=[] below makes each
        # group's slice computation come up empty, so nothing pins).
        # Passing devices=None instead would let each dp>1 group slice
        # ALL devices independently — overlapping pinned meshes with
        # two models' weights double-committed on the same chips.
        # Legitimate on CPU tier-1; loud on an accelerator.
        import logging

        logging.getLogger(__name__).warning(
            "llm.models: %d total replicas but only %d device(s) — "
            "every group will timeshare the default device",
            total, len(all_devices))
    groups: list[ModelGroup] = []
    start = 0
    for i, entry in enumerate(entries):
        dp = max(1, entry.dp_replicas)
        derived = derive_group_llm(llm_cfg, entry)
        built = build_group(
            derived,
            replica_indices=range(start, start + dp),
            devices=(all_devices[start:start + dp] if carve else []),
            pin_devices=carve,
        )
        wire_feedback(built.cores, derived, slo_monitor)
        from runbookai_tpu.engine.fleet import AsyncFleet

        fleet = AsyncFleet(built.cores, built.fleet_cfg,
                           model_label=entry.name,
                           # One clear for the whole build: later groups
                           # must not drop the labelsets their siblings
                           # just bound.
                           clear_labeled=(i == 0))
        groups.append(ModelGroup(
            name=entry.name, fleet=fleet, tokenizer=built.tokenizer,
            chat_format=built.chat_format, llm_cfg=built.llm_cfg))
        start += dp
    return MultiModelFleet(groups)
