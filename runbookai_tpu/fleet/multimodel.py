"""Multi-model fleet: named model groups behind one serving surface.

AIBrix's production premise (PAPERS.md, arXiv:2504.03648) is that LLM
infrastructure is multi-model by default — routing, capacity and failure
isolation are managed *per model*, not per deployment. This module is the
engine-level half of that premise for the in-tree stack:

- a :class:`ModelGroup` is one served model: its own replicas (an
  :class:`~runbookai_tpu.engine.fleet.AsyncFleet` built from the group's
  derived ``LLMConfig``/plan — see ``fleet/build.py``), its own tokenizer
  and chat format, and its own LoRA adapter namespace;
- :class:`MultiModelFleet` fronts the groups with the same
  ``generate``/``generate_stream``/``start``/``stop`` surface as
  ``AsyncEngine``/``AsyncFleet`` plus a ``model`` dimension: callers name
  a group (or set :data:`CURRENT_MODEL` for a whole asyncio task) and the
  request is served entirely by that group's router and replicas — the
  existing prefix-affinity / least-loaded / queue-depth placement runs
  *within* the group, so per-request streams are byte-identical to a
  dedicated single-model fleet serving the same group config.

Replica indices are GLOBAL across groups (group 0 owns ``r0..``, the next
group continues where it left off), so request-id namespaces, metric
``replica`` labels and flight-recorder rows stay unambiguous fleet-wide;
the ``model`` label/tag separates the groups.

The single-model path never constructs this class: ``llm.models`` absent
means ``JaxTpuClient.from_config`` builds exactly the classic engine or
AsyncFleet, bit for bit (pinned by tests/test_multimodel.py).
"""

from __future__ import annotations

import asyncio
import time as _time
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from runbookai_tpu.engine.engine import EngineCore
from runbookai_tpu.engine.fleet import (
    AsyncFleet,
    _agg_utilization,
    install_fleet_aggregates,
)
from runbookai_tpu.utils import metrics as metrics_mod

# Per-asyncio-task model attribution: the eval runner (and any agent-side
# caller that serves a whole workload against one group) sets this around
# a case instead of threading ``model=`` through every engine call —
# contextvars flow through awaits exactly like the router's CURRENT_CASE.
CURRENT_MODEL: ContextVar[Optional[str]] = ContextVar(
    "runbook_fleet_model", default=None)


@dataclass
class ModelGroup:
    """One served model: a replica fleet plus the per-model pieces the
    serving surface needs (tokenizer, chat format, adapter names)."""

    name: str
    fleet: AsyncFleet
    tokenizer: Any
    chat_format: str = "llama3"
    # The group's derived LLMConfig — /healthz provenance and the
    # feedback/sched wiring read it; never consulted on the hot path.
    llm_cfg: Any = None

    @property
    def cores(self) -> list[EngineCore]:
        return self.fleet.cores

    @property
    def adapter_names(self) -> list[str]:
        lora = self.cores[0].lora
        return list(lora.names) if lora is not None else []

    @property
    def page_size(self) -> int:
        return self.cores[0].ecfg.page_size


class MultiModelFleet:
    """AsyncEngine-compatible facade over named model groups.

    Everything model-agnostic delegates to the resolved group's
    ``AsyncFleet``; everything fleet-wide (aggregate metrics, merged
    health/debug snapshots, eval attribution) unions the groups.
    """

    def __init__(self, groups: Sequence[ModelGroup]):
        if not groups:
            raise ValueError("a multi-model fleet needs at least one group")
        self.groups: dict[str, ModelGroup] = {}
        for g in groups:
            if g.name in self.groups:
                raise ValueError(f"duplicate model group {g.name!r}")
            self.groups[g.name] = g
        self.default = groups[0].name
        self.cores = [c for g in groups for c in g.cores]
        # Total replica count: the eval suite scales its concurrency
        # budget by this, exactly as it does for a single AsyncFleet.
        self.dp = len(self.cores)
        # GLOBAL replica id -> served model name (eval attribution, the
        # merged /debug/steps tags, dashboards joining replica series).
        self.replica_models: dict[int, str] = {
            gid: g.name for g in groups for gid in g.fleet.replica_ids}
        if len(self.replica_models) != self.dp:
            raise ValueError(
                "model groups must use disjoint global replica indices "
                f"(got {[g.fleet.replica_ids for g in groups]})")
        # Process-wide unlabeled names cover ALL groups (each group's
        # AsyncFleet bound them to its own cores during construction;
        # this final rebind wins).
        install_fleet_aggregates(self.cores)
        self._install_metrics()
        # Online replica rebuild (chaos/supervisor.py): when a group
        # fleet swaps a core, refresh the union core list and re-bind
        # the process-wide aggregates so no scrape keeps pinning (or
        # reading) the dead engine.
        for g in groups:
            g.fleet._rebuild_listener = self._on_group_rebuild

    def _on_group_rebuild(self) -> None:
        self.cores = [c for g in self.groups.values() for c in g.cores]
        install_fleet_aggregates(self.cores)

    # ------------------------------------------------------------ resolution

    def served_ids(self) -> list[str]:
        """Every name a request's ``model`` field may carry: group names
        first (serving order), then each group's adapters."""
        out = list(self.groups)
        for g in self.groups.values():
            out.extend(g.adapter_names)
        return out

    def resolve(self, requested: Optional[str]) -> tuple[str, Optional[str]]:
        """``model`` field -> ``(group_name, adapter)``. Absent/empty
        means the default group; a group name selects it; an adapter
        name resolves WITHIN its owning group (config validation pins
        global adapter uniqueness). Unknown names raise ``KeyError`` —
        the HTTP layer answers 404, never silent base-model serving."""
        if not requested:
            return self.default, None
        if requested in self.groups:
            return requested, None
        for name, g in self.groups.items():
            if requested in g.adapter_names:
                return name, requested
        raise KeyError(
            f"model {requested!r} not found; served: {self.served_ids()}")

    def group(self, model: Optional[str] = None) -> ModelGroup:
        name = model or CURRENT_MODEL.get() or self.default
        g = self.groups.get(name)
        if g is None:
            raise KeyError(
                f"model {name!r} not found; served: {self.served_ids()}")
        return g

    def engine_for(self, model: Optional[str] = None) -> AsyncFleet:
        """The resolved group's AsyncFleet — the HTTP layer serves the
        request directly through it, so streams are the group fleet's
        own, byte for byte."""
        return self.group(model).fleet

    def served_models(self) -> list[dict]:
        """``GET /v1/models`` catalog rows: every group, then every
        adapter with its group as ``parent`` (vLLM-style)."""
        rows = [{"id": g.name, "object": "model",
                 "owned_by": "runbookai-tpu",
                 "dp_replicas": g.fleet.dp}
                for g in self.groups.values()]
        for g in self.groups.values():
            rows.extend({"id": name, "object": "model",
                         "owned_by": "runbookai-tpu", "parent": g.name}
                        for name in g.adapter_names)
        return rows

    # ----------------------------------------------------- AsyncEngine API

    async def start(self) -> None:
        for g in self.groups.values():
            await g.fleet.start()

    async def stop(self) -> None:
        await asyncio.gather(*(g.fleet.stop()
                               for g in self.groups.values()))

    async def refresh_lora(self) -> None:
        await asyncio.gather(*(g.fleet.refresh_lora()
                               for g in self.groups.values()))

    async def generate(self, prompt_ids, sampling=None, timeout_s=None,
                       priority: int = 0, adapter: Optional[str] = None,
                       request_id: Optional[str] = None,
                       model: Optional[str] = None):
        return await self.group(model).fleet.generate(
            prompt_ids, sampling, timeout_s=timeout_s, priority=priority,
            adapter=adapter, request_id=request_id)

    async def generate_stream(self, prompt_ids, sampling=None,
                              priority: int = 0,
                              adapter: Optional[str] = None,
                              request_sink: Optional[list] = None,
                              request_id: Optional[str] = None,
                              model: Optional[str] = None):
        agen = self.group(model).fleet.generate_stream(
            prompt_ids, sampling, priority=priority, adapter=adapter,
            request_sink=request_sink, request_id=request_id)
        try:
            async for tok in agen:
                yield tok
        finally:
            await agen.aclose()

    def is_saturated(self, model: Optional[str] = None) -> bool:
        """A specific group's shed state, or (no model) whether EVERY
        group would shed — the conservative fleet-wide answer."""
        if model is not None:
            return self.group(model).fleet.is_saturated()
        return all(g.fleet.is_saturated() for g in self.groups.values())

    # -------------------------------------------------- eval attribution

    def begin_case(self, case_id: str):
        """Tag this asyncio task's routing with ``case_id`` (the shared
        router contextvar — every group's fleet reads the same one)."""
        return next(iter(self.groups.values())).fleet.begin_case(case_id)

    def end_case(self, token) -> None:
        next(iter(self.groups.values())).fleet.end_case(token)

    def set_case_model(self, model: str):
        """Attribute (and route) this asyncio task's engine calls to
        ``model`` until :meth:`reset_case_model` — how the eval runner
        exercises multi-model routing without threading ``model=``
        through the orchestrator."""
        if model not in self.groups:
            raise KeyError(
                f"model {model!r} not found; served: {list(self.groups)}")
        return CURRENT_MODEL.set(model)

    def reset_case_model(self, token) -> None:
        CURRENT_MODEL.reset(token)

    def case_routes(self, case_id: str) -> dict[int, int]:
        """Pop {global_replica: count} for a finished case, merged across
        groups (indices are globally disjoint, so this is a plain
        union)."""
        merged: dict[int, int] = {}
        for g in self.groups.values():
            for rid, n in g.fleet.case_routes(case_id).items():
                merged[rid] = merged.get(rid, 0) + n
        return merged

    # ------------------------------------------------------- observability

    def _install_metrics(self) -> None:
        """Per-model rollup gauges (the per-replica series already carry
        the model label; these are the direct per-group saturation
        signals the docs' PromQL alerts read)."""
        reg = metrics_mod.get_registry()
        per_model = (
            (reg.gauge("runbook_model_running_requests",
                       "Requests holding a decode slot, per served model "
                       "group", labels=("model",)),
             lambda g: float(sum(len(c.decoding) for c in g.cores))),
            (reg.gauge("runbook_model_waiting_requests",
                       "Requests queued or prefilling, per served model "
                       "group", labels=("model",)),
             lambda g: float(sum(len(c.waiting) + len(c.prefilling)
                                 for c in g.cores))),
            (reg.gauge("runbook_model_kv_pool_utilization",
                       "Fraction of allocatable KV pages held by live "
                       "sequences, per served model group",
                       labels=("model",)),
             lambda g: _agg_utilization(g.cores)),
            (reg.counter("runbook_model_decode_tokens_total",
                         "Tokens sampled by decode dispatches, per served "
                         "model group", labels=("model",)),
             lambda g: float(sum(c.metrics.get("decode_tokens", 0)
                                 for c in g.cores))),
        )
        for metric, fn in per_model:
            metric.clear_functions()
            for g in self.groups.values():
                # runbook: noqa[RBK010] — model label: served-group
                # catalog names, fixed at fleet build.
                metric.labels(model=g.name).set_function(
                    lambda gg=g, f=fn: f(gg))

    def health_snapshot(self, lock_timeout: float = 0.5) -> dict:
        """``/healthz`` body: the classic fleet-wide totals (summed
        metrics dict, pooled KV stats, every replica row — each stamped
        with its model) PLUS a per-group ``models`` block, under ONE
        shared lock budget across all groups."""
        deadline = _time.monotonic() + lock_timeout
        models: dict[str, dict] = {}
        agg: dict = {}
        replicas: list[dict] = []
        kv_total = kv_used = kv_cached = 0
        for name, g in self.groups.items():
            budget = max(0.0, deadline - _time.monotonic())
            snap = g.fleet.health_snapshot(lock_timeout=budget)
            for row in snap["replicas"]:
                row["model"] = name
            replicas.extend(snap["replicas"])
            for k, v in snap["metrics"].items():
                agg[k] = agg.get(k, 0) + v
            kv_total += snap["kv"]["pages_total"]
            kv_used += snap["kv"]["pages_in_use"]
            kv_cached += snap["kv"]["pages_cached"]
            models[name] = {
                "dp_replicas": snap["dp_replicas"],
                "adapters": g.adapter_names,
                "kv": snap["kv"],
                "router": snap["router"],
                "decode_tokens": snap["metrics"].get("decode_tokens", 0),
            }
            # Supervision / chaos surfaces ride per group (each group
            # fleet has its own supervisor + injector when enabled).
            for key in ("supervisor", "chaos", "unresponsive_replicas"):
                if key in snap:
                    models[name][key] = snap[key]
        usable = sum(c.kv.allocator.num_pages - 1 for c in self.cores)
        return {
            "dp_replicas": self.dp,
            "multi_model": True,
            "models": models,
            "kv": {"pages_total": kv_total, "pages_in_use": kv_used,
                   "pages_cached": kv_cached,
                   "utilization": (round(kv_used / usable, 4)
                                   if usable else 0.0)},
            "metrics": agg,
            "replicas": replicas,
        }

    def debug_steps(self, last_n: Optional[int] = None,
                    lock_timeout: float = 0.5) -> dict:
        """Fleet-wide flight records merged across groups, each record
        tagged with its serving model — one ts-ordered timeline under
        one shared lock budget (the single-fleet contract)."""
        deadline = _time.monotonic() + lock_timeout
        merged: list[dict] = []
        capacity = 0
        steps_total = 0
        for name, g in self.groups.items():
            budget = max(0.0, deadline - _time.monotonic())
            snap = g.fleet.debug_steps(last_n, lock_timeout=budget)
            for row in snap["steps"]:
                row["model"] = name
            merged.extend(snap["steps"])
            capacity += snap["capacity"]
            steps_total += snap["steps_total"]
        merged.sort(key=lambda r: r.get("ts", 0.0))
        if last_n is not None:
            n = max(0, int(last_n))
            merged = merged[-n:] if n else []
        return {"capacity": capacity, "steps_total": steps_total,
                "dp_replicas": self.dp, "models": list(self.groups),
                "steps": merged}


__all__ = ["CURRENT_MODEL", "ModelGroup", "MultiModelFleet"]
