"""Dataset converters: public RCA datasets → fixture JSON.

Parity target: reference ``src/eval/rcaeval-to-fixtures.ts`` /
``rootly-logs-to-fixtures.ts`` / ``tracerca-to-fixtures.ts`` (json / jsonl /
csv / tsv inputs). Formats are inferred from extension; each converter maps a
dataset row onto the shared fixture schema (``scoring.EvalCase``).
"""

from __future__ import annotations

import csv
import json
from pathlib import Path
from typing import Any


def _read_rows(path: str | Path) -> list[dict[str, Any]]:
    p = Path(path)
    suffix = p.suffix.lower()
    text = p.read_text()
    if suffix == ".json":
        data = json.loads(text)
        return data if isinstance(data, list) else data.get("cases", data.get("data", []))
    if suffix == ".jsonl":
        return [json.loads(line) for line in text.splitlines() if line.strip()]
    if suffix in (".csv", ".tsv"):
        delim = "\t" if suffix == ".tsv" else ","
        return list(csv.DictReader(text.splitlines(), delimiter=delim))
    raise ValueError(f"unsupported dataset format: {suffix}")


def _keywords(text: str, max_n: int = 6) -> list[str]:
    words = [w.strip(".,;:()[]") for w in text.split()]
    return [w for w in words if len(w) > 4][:max_n]


def rcaeval_to_fixtures(path: str | Path) -> list[dict[str, Any]]:
    """RCAEval rows: {case, system, root_cause_service, root_cause_metric/fault}."""
    fixtures = []
    for i, row in enumerate(_read_rows(path)):
        service = str(row.get("root_cause_service") or row.get("service") or "")
        fault = str(row.get("fault_type") or row.get("root_cause_metric")
                    or row.get("root_cause") or "")
        desc = str(row.get("description") or
                   f"Anomaly detected in {row.get('system', 'system')}: "
                   f"degradation around {service}")
        fixtures.append({
            "case_id": str(row.get("case") or row.get("id") or f"rcaeval-{i}"),
            "description": desc,
            "expected_root_cause": f"{fault} in {service}".strip(),
            "root_cause_keywords": [k for k in [service, *_keywords(fault)] if k],
            "expected_services": [service] if service else [],
            "expected_confidence": "medium",
        })
    return fixtures


def rootly_to_fixtures(path: str | Path) -> list[dict[str, Any]]:
    """Rootly incident rows: {title, summary, cause, services, severity}."""
    fixtures = []
    for i, row in enumerate(_read_rows(path)):
        services = row.get("services") or row.get("affected_services") or []
        if isinstance(services, str):
            services = [s.strip() for s in services.split(",") if s.strip()]
        cause = str(row.get("cause") or row.get("root_cause") or "")
        fixtures.append({
            "case_id": str(row.get("id") or f"rootly-{i}"),
            "description": str(row.get("title") or row.get("summary") or ""),
            "expected_root_cause": cause,
            "root_cause_keywords": _keywords(cause),
            "expected_services": list(services),
            "expected_confidence": "medium",
        })
    return fixtures


def tracerca_to_fixtures(path: str | Path) -> list[dict[str, Any]]:
    """TraceRCA rows: {trace_id/case, root_cause (service), anomaly_type}."""
    fixtures = []
    for i, row in enumerate(_read_rows(path)):
        service = str(row.get("root_cause") or row.get("root_cause_service") or "")
        anomaly = str(row.get("anomaly_type") or row.get("fault") or "latency anomaly")
        fixtures.append({
            "case_id": str(row.get("trace_id") or row.get("case") or f"tracerca-{i}"),
            "description": f"Trace anomaly ({anomaly}) in microservice system",
            "expected_root_cause": f"{anomaly} caused by {service}",
            "root_cause_keywords": [k for k in [service, *_keywords(anomaly)] if k],
            "expected_services": [service] if service else [],
            "expected_confidence": "medium",
        })
    return fixtures


CONVERTERS = {
    "rcaeval": rcaeval_to_fixtures,
    "rootly": rootly_to_fixtures,
    "tracerca": tracerca_to_fixtures,
}


def convert(benchmark: str, src: str | Path, dst: str | Path) -> int:
    fixtures = CONVERTERS[benchmark](src)
    Path(dst).parent.mkdir(parents=True, exist_ok=True)
    Path(dst).write_text(json.dumps({"pass_threshold": 0.7, "cases": fixtures}, indent=2))
    return len(fixtures)
