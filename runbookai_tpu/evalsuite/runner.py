"""Investigation benchmark runner: offline scoring or live DP-batched runs.

Parity target: reference ``src/eval/investigation-benchmark.ts`` (offline mode
:184-210 scores fixture ``mock_result`` without any model; live mode builds the
real runtime per case :121-187) and ``run-all-benchmarks.ts`` (:133-344 —
per-benchmark reports + ``summary.json``, skipped/failed statuses).

The TPU upgrade (SURVEY.md §3.5): cases are independent, so live mode runs N
investigations **concurrently** against the continuous-batching engine
(asyncio gather = data parallelism over the engine's batch slots). When the
client serves through a data-parallel engine fleet
(``EngineConfig.dp_replicas`` > 1, ``engine/fleet.py``), the fan-out widens
automatically — the concurrency budget multiplies by the replica count, the
prefix-affinity router spreads cases across replicas (each case's repeated
system prompt pins to the replica holding its KV pages), and every case's
report row records which replicas served its requests
(``replica_requests``). Across a pod, ``run_all.py --shard i/n`` first
splits cases statically per host; the fleet balances dynamically within one.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from runbookai_tpu.agent.orchestrator import InvestigationOrchestrator, ToolExecutor
from runbookai_tpu.agent.state_machine import InvestigationStateMachine
from runbookai_tpu.evalsuite.scoring import EvalCase, score_investigation_result
from runbookai_tpu.tools import simulated as sim_tools
from runbookai_tpu.tools.registry import ToolRegistry


@dataclass
class BenchmarkReport:
    name: str
    cases: list[dict[str, Any]] = field(default_factory=list)
    started_at: float = field(default_factory=time.time)
    elapsed_s: float = 0.0

    @property
    def passed(self) -> int:
        return sum(1 for c in self.cases if c["passed"])

    @property
    def pass_rate(self) -> float:
        return self.passed / len(self.cases) if self.cases else 0.0

    def to_dict(self) -> dict[str, Any]:
        return {
            "benchmark": self.name,
            "total": len(self.cases),
            "passed": self.passed,
            "pass_rate": round(self.pass_rate, 4),
            "elapsed_s": round(self.elapsed_s, 2),
            "cases": self.cases,
        }


def load_fixtures_file(path: str | Path) -> list[EvalCase]:
    raw = json.loads(Path(path).read_text())
    cases = raw["cases"] if isinstance(raw, dict) else raw
    default_threshold = raw.get("pass_threshold", 0.7) if isinstance(raw, dict) else 0.7
    out = []
    for c in cases:
        c.setdefault("pass_threshold", default_threshold)
        out.append(EvalCase.from_dict(c))
    return out


def run_offline(cases: list[EvalCase], name: str = "offline") -> BenchmarkReport:
    """Score fixture mock_results without any model (regression harness)."""
    report = BenchmarkReport(name=name)
    t0 = time.perf_counter()
    for case in cases:
        if case.mock_result is None:
            report.cases.append({"case_id": case.case_id, "status": "skipped",
                                 "passed": False, "reason": "no mock_result"})
            continue
        score = score_investigation_result(case, case.mock_result)
        report.cases.append({
            "case_id": case.case_id, "status": "scored", "passed": score.passed,
            "score": score.total, "dimensions": score.dimensions,
            "notes": score.notes,
        })
    report.elapsed_s = time.perf_counter() - t0
    return report


def _executor_for_case(case: EvalCase) -> ToolExecutor:
    reg = ToolRegistry()
    sim = sim_tools.SimulatedCloud(case.fixtures)
    sim_tools.register_aws(reg, sim)
    sim_tools.register_kubernetes(reg, sim)
    sim_tools.register_incident(reg, sim, None)
    return ToolExecutor({t.name: t for t in reg.all()})


async def run_live(
    cases: list[EvalCase],
    llm_factory: Callable[[], Any],
    name: str = "live",
    concurrency: int = 4,
    knowledge=None,
    max_iterations: int = 20,
    scale_concurrency_with_fleet: bool = True,
) -> BenchmarkReport:
    """Run full investigations concurrently against a shared engine.

    ``llm_factory`` returns the (shared) client exposing ``complete``; the
    continuous-batching engine interleaves all cases' decodes (DP batching).
    With an engine fleet behind the client, ``concurrency`` is the
    PER-REPLICA budget: the semaphore widens by the replica count (the
    router keeps per-replica load at roughly the configured level), and
    each case row gains ``replica_requests`` — how many engine calls each
    replica served for it.
    """
    report = BenchmarkReport(name=name)
    llm = llm_factory()
    engine = getattr(llm, "engine", None)
    dp = getattr(engine, "dp", 1)
    eff_concurrency = (concurrency * dp if scale_concurrency_with_fleet
                       else concurrency)
    # Fleet attribution (duck-typed so mock LLM clients need nothing):
    # begin_case tags the asyncio task; every routed request inside it is
    # credited to the case, however deep in the agent stack it happens.
    begin_case = getattr(engine, "begin_case", None)
    # Multi-model fleets: a case carrying a `model` pins every engine
    # call it makes (however deep in the agent stack) to that served
    # group via the fleet's CURRENT_MODEL contextvar — the eval suite is
    # then a real multi-model load generator, not a single-group one.
    set_model = getattr(engine, "set_case_model", None)
    replica_models = getattr(engine, "replica_models", None)
    if set_model is None and any(c.model for c in cases):
        # Say so LOUDLY: a per-model breakdown printed over cases that
        # all silently ran on one default engine would read as a
        # multi-model result that never happened.
        import logging

        logging.getLogger(__name__).warning(
            "eval cases carry a `model` but the engine has no model "
            "routing (llm.models not configured) — every case runs on "
            "the default model")
    sem = asyncio.Semaphore(eff_concurrency)
    t0 = time.perf_counter()

    async def run_case(case: EvalCase) -> dict[str, Any]:
        async with sem:
            token = begin_case(case.case_id) if begin_case else None
            model_token = None
            try:
                # Inside the try: a case naming an unserved model is a
                # FAILED case row, never a crashed eval run.
                if set_model and case.model:
                    model_token = set_model(case.model)
                orch = InvestigationOrchestrator(
                    llm, _executor_for_case(case),
                    machine=InvestigationStateMachine(
                        incident_id=case.incident_id or case.case_id,
                        max_iterations=max_iterations),
                    knowledge=knowledge,
                )
                result = await orch.investigate(case.incident_id, case.description)
                payload = {
                    "root_cause": result.root_cause,
                    "confidence": result.confidence,
                    "affected_services": result.affected_services,
                    "summary": result.conclusion_summary,
                }
                score = score_investigation_result(case, payload)
                out = {
                    "case_id": case.case_id, "status": "completed",
                    "passed": score.passed, "score": score.total,
                    "dimensions": score.dimensions,
                    "result": payload,
                    "event_counts": _count_events(result.events),
                    "iterations": result.summary["iterations"],
                }
            except Exception as exc:  # noqa: BLE001 — a case failure is a result
                out = {"case_id": case.case_id, "status": "failed",
                       "passed": False,
                       "error": f"{type(exc).__name__}: {exc}"}
            finally:
                if model_token is not None:
                    engine.reset_case_model(model_token)
                if token is not None:
                    engine.end_case(token)
            if begin_case:
                routes = engine.case_routes(case.case_id)
                out["replica_requests"] = {
                    f"r{i}": n for i, n in sorted(routes.items())}
                if replica_models:
                    # Per-model attribution (multi-model fleets): how
                    # many engine calls each served group handled for
                    # this case — summed into summary.json next to the
                    # per-replica totals.
                    per_model: dict[str, int] = {}
                    for i, n in routes.items():
                        name = replica_models.get(i, "unknown")
                        per_model[name] = per_model.get(name, 0) + n
                    out["model_requests"] = dict(sorted(per_model.items()))
            return out

    report.cases = list(await asyncio.gather(*(run_case(c) for c in cases)))
    report.elapsed_s = time.perf_counter() - t0
    return report


def _count_events(events) -> dict[str, int]:
    counts: dict[str, int] = {}
    for ev in events:
        counts[ev.kind] = counts.get(ev.kind, 0) + 1
    return counts


def write_reports(reports: list[BenchmarkReport], out_dir: str | Path) -> Path:
    """Per-benchmark JSONs + aggregate summary.json (run-all-benchmarks.ts)."""
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    for report in reports:
        (out / f"{report.name}.json").write_text(json.dumps(report.to_dict(), indent=2))
    from runbookai_tpu.utils.weights import discover_weights, quality_marker

    summary = {
        "generated_at": time.time(),
        "quality": quality_marker(discover_weights()),
        "benchmarks": [
            {"name": r.name, "total": len(r.cases), "passed": r.passed,
             "pass_rate": round(r.pass_rate, 4), "elapsed_s": round(r.elapsed_s, 2)}
            for r in reports
        ],
        "overall_pass_rate": round(
            sum(r.passed for r in reports) / max(1, sum(len(r.cases) for r in reports)), 4),
    }
    # Fleet runs: total engine requests each replica served, summed from
    # the per-case attribution run_live recorded.
    replica_totals: dict[str, int] = {}
    model_totals: dict[str, int] = {}
    for report in reports:
        for c in report.cases:
            for rep, n in (c.get("replica_requests") or {}).items():
                replica_totals[rep] = replica_totals.get(rep, 0) + n
            for name, n in (c.get("model_requests") or {}).items():
                model_totals[name] = model_totals.get(name, 0) + n
    if replica_totals:
        summary["replica_attribution"] = dict(sorted(replica_totals.items()))
    if model_totals:
        # Multi-model fleets: the same totals grouped by served model —
        # which group actually absorbed the eval load.
        summary["model_attribution"] = dict(sorted(model_totals.items()))
    path = out / "summary.json"
    path.write_text(json.dumps(summary, indent=2))
    return path
