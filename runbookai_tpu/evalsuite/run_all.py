"""Unified multi-benchmark eval driver.

Parity target: reference ``src/eval/run-all-benchmarks.ts`` (:133-435 —
per-benchmark pipeline: locate dataset input → convert to fixtures → run the
investigation benchmark → collect report; statuses passed|failed|skipped;
aggregate ``summary.json``) and ``setup-datasets.ts`` (:86-151 — shallow
git-clone of the public dataset repos under ``examples/evals/datasets/``).

Zero-egress note: ``setup_datasets`` shells out to ``git clone`` and reports
a per-dataset skipped/failed status instead of raising, so in an egress-less
environment the driver degrades to "skipped: input not found" exactly like
the reference does when a dataset is absent (:158).
"""

from __future__ import annotations

import json
import subprocess
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable, Optional

from runbookai_tpu.evalsuite.converters import CONVERTERS
from runbookai_tpu.evalsuite.runner import (
    BenchmarkReport,
    load_fixtures_file,
    run_offline,
    write_reports,
)

DATASET_REPOS = {
    "rcaeval": "https://github.com/phamquiluan/RCAEval.git",
    "rootly": "https://github.com/Rootly-AI-Labs/logs-dataset.git",
    "tracerca": "https://github.com/NetManAIOps/TraceRCA.git",
}

# Candidate input files inside each dataset checkout (first match wins);
# a bare file drop (e.g. hand-placed jsonl/csv) is also accepted.
INPUT_CANDIDATES = {
    "rcaeval": ["cases.json", "cases.jsonl", "data/cases.json", "rcaeval.csv"],
    "rootly": ["incidents.jsonl", "incidents.json", "data/incidents.jsonl",
               "rootly.csv"],
    "tracerca": ["labels.csv", "cases.csv", "data/labels.tsv",
                 "tracerca.jsonl"],
}


def parse_shard(spec: str) -> tuple[int, int]:
    """``"i/n"`` → (index, count), 0-based index; ``"auto"`` takes this
    process's rank in the multihost runtime (``multihost.shard_for_host``)
    so each pod host statically owns ``cases[i::n]`` before its local
    fleet balances dynamically."""
    if spec == "auto":
        from runbookai_tpu.parallel.multihost import shard_for_host

        return shard_for_host()
    try:
        idx_s, _, n_s = spec.partition("/")
        idx, n = int(idx_s), int(n_s)
    except ValueError:
        raise ValueError(f"shard must look like 'i/n' or 'auto', got {spec!r}")
    if n < 1 or not 0 <= idx < n:
        raise ValueError(f"shard index must satisfy 0 <= i < n, got {spec!r}")
    return idx, n


@dataclass
class BenchmarkRun:
    benchmark: str
    status: str  # passed | failed | skipped
    reason: str = ""
    report: Optional[BenchmarkReport] = None
    fixtures_path: str = ""
    case_count: int = 0

    def to_dict(self) -> dict[str, Any]:
        out = {"benchmark": self.benchmark, "status": self.status,
               "case_count": self.case_count}
        if self.reason:
            out["reason"] = self.reason
        if self.report is not None:
            out["pass_rate"] = round(self.report.pass_rate, 4)
        return out


def setup_datasets(root: str | Path,
                   benchmarks: Optional[list[str]] = None) -> dict[str, str]:
    """Shallow-clone missing dataset repos; returns {name: status-string}."""
    root = Path(root)
    root.mkdir(parents=True, exist_ok=True)
    statuses: dict[str, str] = {}
    for name in benchmarks or list(DATASET_REPOS):
        dest = root / name
        if (dest / ".git").exists() or _find_input(root, name) is not None:
            statuses[name] = "present"
            continue
        if dest.exists() and any(dest.iterdir()):
            # Partial checkout (e.g. interrupted clone): git refuses to clone
            # into a non-empty dir, so surface it instead of looping forever.
            statuses[name] = f"stale: remove {dest} to re-clone"
            continue
        try:
            proc = subprocess.run(
                ["git", "clone", "--depth", "1", DATASET_REPOS[name], str(dest)],
                capture_output=True, text=True, timeout=300)
            statuses[name] = ("cloned" if proc.returncode == 0
                              else f"failed: {proc.stderr.strip()[:160]}")
        except (OSError, subprocess.TimeoutExpired) as exc:
            statuses[name] = f"failed: {exc}"
    return statuses


def _find_input(root: Path, name: str) -> Optional[Path]:
    dataset_dir = root / name
    for candidate in INPUT_CANDIDATES[name]:
        path = dataset_dir / candidate
        if path.exists():
            return path
    # any loose data file at the dataset root
    if dataset_dir.exists():
        for path in sorted(dataset_dir.iterdir()):
            if path.suffix.lower() in (".json", ".jsonl", ".csv", ".tsv"):
                return path
    return None


def run_single_benchmark(
    name: str,
    datasets_root: str | Path,
    out_dir: str | Path,
    runner: Optional[Callable[[list], BenchmarkReport]] = None,
    input_path: Optional[str | Path] = None,
    min_pass_rate: float = 0.0,
    shard: Optional[tuple[int, int]] = None,
) -> BenchmarkRun:
    """Locate input → convert → run → report (run-all-benchmarks.ts:133).

    ``shard=(i, n)`` keeps only ``cases[i::n]`` — the static per-host split
    of a pod-wide run; each host's engine fleet balances its own share
    dynamically after this cut."""
    source = Path(input_path) if input_path else _find_input(Path(datasets_root), name)
    if source is None:
        return BenchmarkRun(name, "skipped",
                            reason=f"input not found under {datasets_root}/{name}")
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    fixtures_path = out / f"{name}-fixtures.json"
    try:
        fixtures = CONVERTERS[name](source)
        fixtures_path.write_text(json.dumps(
            {"pass_threshold": 0.7, "cases": fixtures}, indent=2))
        cases = load_fixtures_file(fixtures_path)
    except Exception as exc:  # noqa: BLE001 — converter failure is a status
        return BenchmarkRun(name, "failed", reason=f"convert: {exc}")
    if shard is not None:
        idx, n = shard
        cases = cases[idx::n]
        if not cases:
            return BenchmarkRun(name, "skipped",
                                reason=f"no cases in shard {idx}/{n}")
    if not cases:
        return BenchmarkRun(name, "skipped", reason="no cases after conversion")
    try:
        report = (runner or (lambda cs: run_offline(cs, name=name)))(cases)
        report.name = name
    except Exception as exc:  # noqa: BLE001
        return BenchmarkRun(name, "failed", reason=f"run: {exc}",
                            fixtures_path=str(fixtures_path),
                            case_count=len(cases))
    status = "passed" if report.pass_rate >= min_pass_rate else "failed"
    return BenchmarkRun(name, status, report=report,
                        fixtures_path=str(fixtures_path), case_count=len(cases))


def run_all_benchmarks(
    datasets_root: str | Path = "examples/evals/datasets",
    out_dir: str | Path = ".runbook/eval-reports",
    benchmarks: Optional[list[str]] = None,
    runner: Optional[Callable[[list], BenchmarkReport]] = None,
    min_pass_rate: float = 0.0,
    setup: bool = False,
    shard: Optional[tuple[int, int]] = None,
) -> dict[str, Any]:
    """All benchmarks → per-report JSONs + aggregate summary (ts:344-435)."""
    names = benchmarks or list(CONVERTERS)
    if setup:
        setup_datasets(datasets_root, names)
    runs = [run_single_benchmark(n, datasets_root, out_dir, runner=runner,
                                 min_pass_rate=min_pass_rate, shard=shard)
            for n in names]
    reports = [r.report for r in runs if r.report is not None]
    out = Path(out_dir)
    summary_path = write_reports(reports, out) if reports else None
    from runbookai_tpu.utils.weights import discover_weights, quality_marker

    aggregate = {
        "generated_at": time.time(),
        # Quality-axis honesty (VERDICT r4 #3): offline scoring exercises
        # the harness; pass@1 means investigation quality only once real
        # weights are in play — every artifact says which it was.
        "quality": quality_marker(discover_weights()),
        **({"shard": f"{shard[0]}/{shard[1]}"} if shard is not None else {}),
        "results": [r.to_dict() for r in runs],
        "passed": sum(1 for r in runs if r.status == "passed"),
        "failed": sum(1 for r in runs if r.status == "failed"),
        "skipped": sum(1 for r in runs if r.status == "skipped"),
    }
    out.mkdir(parents=True, exist_ok=True)
    (out / "run-all.json").write_text(json.dumps(aggregate, indent=2))
    # None when every benchmark was skipped and no summary file was written.
    aggregate["summary_path"] = None if summary_path is None else str(summary_path)
    return aggregate
