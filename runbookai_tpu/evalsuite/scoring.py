"""Investigation scoring: weighted root-cause / services / confidence / phrases.

Parity target: reference ``src/eval/scoring.ts`` — fixture schema (:3-35),
``scoreInvestigationResult`` (:134): root cause exact + keyword matching,
service alias coverage (:75-123), confidence ordinal distance (:54-58),
required/forbidden phrase checks; pass threshold from the fixture (default
0.7, ``examples/evals/investigation-fixtures.sample.json:3``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Optional

DEFAULT_WEIGHTS = {
    "root_cause": 0.45,
    "services": 0.2,
    "confidence": 0.15,
    "phrases": 0.2,
}

_CONFIDENCE_ORD = {"low": 0, "medium": 1, "high": 2}


@dataclass
class EvalCase:
    case_id: str
    description: str
    expected_root_cause: str
    root_cause_keywords: list[str] = field(default_factory=list)
    expected_services: list[str] = field(default_factory=list)
    service_aliases: dict[str, list[str]] = field(default_factory=dict)
    expected_confidence: str = "medium"
    required_phrases: list[str] = field(default_factory=list)
    forbidden_phrases: list[str] = field(default_factory=list)
    pass_threshold: float = 0.7
    incident_id: str = ""
    fixtures: Optional[dict[str, Any]] = None  # simulated-cloud fixture override
    mock_result: Optional[dict[str, Any]] = None  # offline mode
    # Served model group to run this case against (multi-model fleets);
    # None = the client's default model, exactly the historical behavior.
    model: Optional[str] = None

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "EvalCase":
        return cls(
            case_id=str(raw.get("case_id") or raw.get("id") or "case"),
            description=str(raw.get("description", "")),
            expected_root_cause=str(raw.get("expected_root_cause", "")),
            root_cause_keywords=[str(k) for k in raw.get("root_cause_keywords", [])],
            expected_services=[str(s) for s in raw.get("expected_services", [])],
            service_aliases={k: list(v) for k, v in raw.get("service_aliases", {}).items()},
            expected_confidence=str(raw.get("expected_confidence", "medium")),
            required_phrases=[str(p) for p in raw.get("required_phrases", [])],
            forbidden_phrases=[str(p) for p in raw.get("forbidden_phrases", [])],
            pass_threshold=float(raw.get("pass_threshold", 0.7)),
            incident_id=str(raw.get("incident_id", "")),
            fixtures=raw.get("fixtures"),
            mock_result=raw.get("mock_result") or raw.get("mockResult"),
            model=raw.get("model"),
        )


@dataclass
class CaseScore:
    case_id: str
    total: float
    passed: bool
    dimensions: dict[str, float]
    notes: list[str] = field(default_factory=list)


def _normalize(text: str) -> str:
    return re.sub(r"\s+", " ", text.lower()).strip()


def score_root_cause(expected: str, keywords: list[str], actual: str) -> tuple[float, str]:
    actual_n = _normalize(actual)
    if not actual_n:
        return 0.0, "empty root cause"
    if expected and _normalize(expected) in actual_n:
        return 1.0, "exact root-cause match"
    if keywords:
        hit = sum(1 for k in keywords if _normalize(k) in actual_n)
        return hit / len(keywords), f"{hit}/{len(keywords)} keywords"
    # fall back to token overlap with the expected statement
    exp_words = set(_normalize(expected).split())
    if not exp_words:
        return 0.0, "no expected root cause defined"
    overlap = sum(1 for w in exp_words if len(w) > 3 and w in actual_n)
    return min(1.0, overlap / max(1, len([w for w in exp_words if len(w) > 3]))), "token overlap"


def score_services(expected: list[str], aliases: dict[str, list[str]],
                   actual: list[str], answer_text: str = "") -> tuple[float, str]:
    if not expected:
        return 1.0, "no expected services"
    actual_n = {_normalize(s) for s in actual}
    text_n = _normalize(answer_text)
    covered = 0
    for svc in expected:
        names = [svc] + aliases.get(svc, [])
        if any(_normalize(n) in actual_n or _normalize(n) in text_n for n in names):
            covered += 1
    return covered / len(expected), f"{covered}/{len(expected)} services covered"


def score_confidence(expected: str, actual: str) -> float:
    """Ordinal distance (scoring.ts:54-58): exact 1.0, adjacent 0.5, else 0."""
    e = _CONFIDENCE_ORD.get(_normalize(expected))
    a = _CONFIDENCE_ORD.get(_normalize(actual))
    if e is None or a is None:
        return 0.0
    dist = abs(e - a)
    return 1.0 if dist == 0 else (0.5 if dist == 1 else 0.0)


def score_phrases(required: list[str], forbidden: list[str], text: str) -> tuple[float, list[str]]:
    notes = []
    text_n = _normalize(text)
    score = 1.0
    if required:
        hit = sum(1 for p in required if _normalize(p) in text_n)
        score = hit / len(required)
        if hit < len(required):
            notes.append(f"missing required phrases: {len(required) - hit}")
    for p in forbidden:
        if _normalize(p) in text_n:
            score = max(0.0, score - 0.5)
            notes.append(f"forbidden phrase present: {p!r}")
    return score, notes


def score_investigation_result(case: EvalCase, result: dict[str, Any],
                               weights: Optional[dict[str, float]] = None) -> CaseScore:
    """``result`` needs: root_cause, confidence, affected_services, summary."""
    w = weights or DEFAULT_WEIGHTS
    answer_text = " ".join(str(result.get(k, "")) for k in
                           ("root_cause", "summary", "conclusion_summary"))
    rc_score, rc_note = score_root_cause(
        case.expected_root_cause, case.root_cause_keywords,
        str(result.get("root_cause", "")))
    svc_score, svc_note = score_services(
        case.expected_services, case.service_aliases,
        list(result.get("affected_services", [])), answer_text)
    conf_score = score_confidence(case.expected_confidence,
                                  str(result.get("confidence", "")))
    phrase_score, phrase_notes = score_phrases(
        case.required_phrases, case.forbidden_phrases, answer_text)

    dims = {
        "root_cause": rc_score,
        "services": svc_score,
        "confidence": conf_score,
        "phrases": phrase_score,
    }
    total = sum(w[k] * dims[k] for k in w)
    return CaseScore(
        case_id=case.case_id,
        total=round(total, 4),
        passed=total >= case.pass_threshold,
        dimensions=dims,
        notes=[rc_note, svc_note, *phrase_notes],
    )
