"""LLM client seam — the boundary the TPU inference backend plugs into.

Parity target: reference ``src/model/llm.ts`` — ``LLMClient.chat(system, user,
tools) -> {content, toolCalls, thinking}`` (``src/agent/agent.ts:167-181``) plus
the orchestrator's simpler ``complete(prompt) -> str``
(``src/agent/investigation-orchestrator.ts:59-61``) and an optional streaming
variant. Where the reference fans out to 13 hosted HTTP providers via pi-ai,
this build's primary provider is ``jax-tpu``: the in-tree JAX engine
(:mod:`runbookai_tpu.engine`).
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator, Optional, Protocol, runtime_checkable

from runbookai_tpu.agent.types import LLMResponse


@runtime_checkable
class LLMClient(Protocol):
    """The seam every reasoning path talks through."""

    async def chat(
        self,
        system_prompt: str,
        user_prompt: str,
        tools: Optional[list[dict[str, Any]]] = None,
    ) -> LLMResponse: ...

    async def complete(self, prompt: str) -> str: ...

    def chat_stream(
        self,
        system_prompt: str,
        user_prompt: str,
        tools: Optional[list[dict[str, Any]]] = None,
    ) -> AsyncIterator[dict[str, Any]]: ...


class BaseLLMClient:
    """Default implementations of the derived methods."""

    async def chat(self, system_prompt, user_prompt, tools=None) -> LLMResponse:
        raise NotImplementedError

    async def complete(self, prompt: str) -> str:
        """Plain completion used by the structured orchestrator."""
        resp = await self.chat("", prompt, tools=None)
        return resp.content

    async def chat_stream(self, system_prompt, user_prompt, tools=None):
        """Fallback streaming: chunk a non-streaming response (reference
        ``src/model/llm.ts:152-203`` does the same)."""
        resp = await self.chat(system_prompt, user_prompt, tools)
        text = resp.content
        step = 64
        for i in range(0, len(text), step):
            yield {"type": "text", "delta": text[i : i + step]}
        for call in resp.tool_calls:
            yield {"type": "tool_call", "call": call}
        yield {"type": "done", "response": resp}


class MockLLMClient(BaseLLMClient):
    """Queue of canned responses for tests (reference ``src/model/llm.ts:280-298``).

    ``queue`` entries may be ``LLMResponse`` or plain strings. When the queue
    empties, returns ``default`` (an empty-content response) instead of raising,
    so loops terminate deterministically.
    """

    def __init__(self, responses: Optional[list[LLMResponse | str]] = None):
        self.queue: list[LLMResponse] = [
            r if isinstance(r, LLMResponse) else LLMResponse(content=r)
            for r in (responses or [])
        ]
        self.calls: list[dict[str, Any]] = []  # recorded for assertions

    def enqueue(self, *responses: LLMResponse | str) -> None:
        for r in responses:
            self.queue.append(r if isinstance(r, LLMResponse) else LLMResponse(content=r))

    async def chat(self, system_prompt, user_prompt, tools=None) -> LLMResponse:
        self.calls.append(
            {"system": system_prompt, "user": user_prompt, "tools": tools}
        )
        await asyncio.sleep(0)  # yield, as a real engine would
        if self.queue:
            return self.queue.pop(0)
        return LLMResponse(content="")


def create_llm_client(config: Any) -> BaseLLMClient:
    """Factory keyed on ``config.llm.provider`` (reference ``llm.ts:59``).

    ``jax-tpu`` builds the in-tree engine-backed client; ``mock`` returns a
    :class:`MockLLMClient` (used by the demo/offline paths and tests).
    """
    llm_cfg = getattr(config, "llm", config)
    provider = getattr(llm_cfg, "provider", "mock")
    if provider == "mock":
        return MockLLMClient()
    if provider == "jax-tpu":
        from runbookai_tpu.model.jax_tpu import JaxTpuClient

        return JaxTpuClient.from_config(llm_cfg)
    raise ValueError(
        f"Unknown llm.provider {provider!r}: this build serves models in-tree "
        "(jax-tpu) and does not proxy to hosted APIs; use 'mock' for modelless runs"
    )
