"""Guided decoding: byte-level JSON automaton → token-level logit masks.

SURVEY.md §7 hard part 2: the product depends on schema-valid JSON from the
model (the reference's zod schemas in ``src/agent/llm-parser.ts:21-210`` were
parsed tolerantly because hosted models drift). Serving in-tree lets us do
better: a pushdown automaton over UTF-8 bytes accepts exactly the JSON
language, and per-step token masks admit only tokens whose *entire* byte
sequence keeps the automaton alive. The tolerant parser remains downstream as
a belt-and-suspenders fallback.

Masks are cached by automaton state signature — states repeat heavily (e.g.
"inside a string"), so even 128k-vocab tokenizers amortize to a handful of
mask computations per generation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# Automaton modes
_VALUE = 0  # expecting start of a value
_STRING = 1  # inside a string
_STR_ESC = 2  # after backslash in string
_NUMBER = 3  # inside a number
_LITERAL = 4  # inside true/false/null
_AFTER = 5  # after a complete value (expecting , } ] or end)
_OBJ_KEY = 6  # right after '{': key string or '}'
_OBJ_COLON = 7  # expecting ':'
_OBJ_KEY_REQ = 8  # after ',' in an object: key string only (no trailing comma)
_ARR_FIRST = 9  # right after '[': value or ']'

_WS = b" \t\n\r"
_DIGITS = b"0123456789"
_LITERALS = {b"true", b"false", b"null"}
_ESC_SIMPLE = b'"\\/bfnrt'
_HEX = b"0123456789abcdefABCDEF"
# JSON number DFA: states where the number-so-far is a complete valid number.
_NUM_COMPLETE = ("zero", "int", "frac", "exp")


def utf8_lead(b: int):
    """Classify a UTF-8 lead byte per the standard DFA (no overlongs, no
    surrogates): returns ``(continuations, lo, hi)`` where lo..hi bounds the
    *first* continuation byte (later ones are always 0x80..0xBF), or None
    for an invalid lead. Shared by the generic and schema string automata so
    the two cannot drift."""
    if 0xC2 <= b <= 0xDF:
        return 1, 0x80, 0xBF
    if b == 0xE0:
        return 2, 0xA0, 0xBF
    if b == 0xED:
        return 2, 0x80, 0x9F
    if 0xE1 <= b <= 0xEF:
        return 2, 0x80, 0xBF
    if b == 0xF0:
        return 3, 0x90, 0xBF
    if 0xF1 <= b <= 0xF3:
        return 3, 0x80, 0xBF
    if b == 0xF4:
        return 3, 0x80, 0x8F
    return None


class JsonMachine:
    """Incremental **strict** JSON validator over bytes.

    Strict means: only documents ``json.loads`` accepts get through —
    full number grammar (no leading zeros, no dangling exponent), valid
    escape sequences (``\\uXXXX`` with 4 hex digits), and well-formed
    UTF-8 string content. Strictness matters because guided decoding uses
    this machine to *steer* sampling: any byte sequence the automaton
    admits, a random model will eventually emit.
    """

    def __init__(self, max_depth: int = 32, budget: int | None = None,
                 budget_bucket: int | None = None):
        self.mode = _VALUE
        self.stack: list[int] = []  # 123 for '{', 91 for '['
        self.literal: bytes = b""
        self.lit_pos = 0
        self.max_depth = max_depth
        self.complete = False
        self.dead = False
        self.num_state = ""  # JSON number DFA state while mode == _NUMBER
        self.u8_need = 0  # pending UTF-8 continuation bytes in a string
        self.u8_lo = 0x80  # allowed range for the next continuation byte
        self.u8_hi = 0xBF
        self.hex_rem = 0  # remaining \uXXXX hex digits
        # Optional byte budget: past it the machine enters WRAP-UP — only
        # completion-directed bytes stay admissible (close the current
        # string, no new elements, close containers), so a free-form value
        # embedded in a schema cannot absorb the whole token budget while
        # still ending as strictly-valid JSON. None = unbounded (the
        # standalone "json" grammar keeps its historical behavior).
        self.budget = budget
        # Head-room bucket for mask caching: must be STRICTLY greater than
        # the vocab's longest token byte-expansion, else a mask cached at a
        # high budget is reused at budget == bucket where a longest-token
        # whose final byte is re-interpreted (number-terminating ',') sees
        # the post-decrement budget hit 0 and diverges — admitting a token
        # in one state that kills the machine in the other. Callers with a
        # measured vocab pass max_token_bytes; +1 buys the strict margin.
        self.budget_bucket = max(
            self._BUDGET_BUCKET,
            (budget_bucket + 1) if budget_bucket is not None else 0)

    def _wrapup_allows(self, b: int) -> bool:
        """Completion-directed admissibility once the byte budget is spent.
        Every state keeps at least one legal byte admissible, so wrap-up can
        never deadlock the machine — it only forbids bytes that grow the
        document (string content, new elements, deeper nesting)."""
        mode = self.mode
        if mode == _STRING:
            if self.u8_need:  # must finish the in-flight UTF-8 character
                return self.u8_lo <= b <= self.u8_hi
            return b == 0x22  # close the string
        if mode == _STR_ESC:
            if self.hex_rem:
                return True  # finish the \uXXXX escape
            return b == 0x6E  # 'n' — shortest escape, then close
        if mode == _NUMBER:
            if self.num_state in _NUM_COMPLETE:
                # number may end: only structural continuation, no growth.
                # ',' is excluded — it would be re-interpreted in AFTER mode
                # as "next element", growing the document past the budget
                # ('}', ']' and ws remain admissible so no deadlock).
                return b not in b"0123456789.eE+-,"
            return b in b"0123456789"  # reach a terminal digit state
        if mode == _LITERAL:
            return True  # bounded by the literal itself
        if mode == _VALUE:
            # shortest values only: a digit, an empty string, or closing an
            # empty container ('}' / ']' stay subject to normal validity)
            return b in b'"0}]'
        if mode == _AFTER:
            return b != 0x2C  # no ',' — close out instead
        return True

    @property
    def in_string(self) -> bool:
        """Inside string content (where whitespace is content, not
        padding) — the mask provider's ws-suppression consults this."""
        return self.mode in (_STRING, _STR_ESC)

    @property
    def is_complete(self) -> bool:
        """True when the bytes so far form a complete JSON document. A
        top-level number qualifies once its DFA state is terminal (numbers
        have no terminator byte)."""
        return self.complete or (
            self.mode == _NUMBER and not self.stack
            and self.num_state in _NUM_COMPLETE
        )

    # Longest token byte-expansion we bucket budget head-room to: a mask
    # cached at one head-room value is only reused where no admissible
    # token can CROSS the wrap-up boundary mid-token (same hazard — and
    # same fix — as _StringFrame's max_str_len head-room bucketing).
    _BUDGET_BUCKET = 32

    def signature(self) -> tuple:
        return (self.mode, tuple(self.stack), self.literal, self.lit_pos,
                self.complete, self.dead, self.num_state,
                self.u8_need, self.u8_lo, self.u8_hi, self.hex_rem,
                None if self.budget is None
                else max(0, min(self.budget, self.budget_bucket)))

    def copy(self) -> "JsonMachine":
        m = JsonMachine(self.max_depth, self.budget)
        m.budget_bucket = self.budget_bucket  # already-resolved; no re-+1
        m.mode, m.stack = self.mode, list(self.stack)
        m.literal, m.lit_pos = self.literal, self.lit_pos
        m.complete, m.dead = self.complete, self.dead
        m.num_state = self.num_state
        m.u8_need, m.u8_lo, m.u8_hi = self.u8_need, self.u8_lo, self.u8_hi
        m.hex_rem = self.hex_rem
        return m

    # ------------------------------------------------------------------ core

    def _close_value(self) -> None:
        """A value just finished; decide what comes next."""
        if not self.stack:
            self.mode = _AFTER
            self.complete = True
        else:
            self.mode = _AFTER

    def advance(self, byte: int, _redo: bool = False) -> bool:
        """Consume one byte; returns False (and goes dead) on violation.
        ``_redo`` marks internal re-interpretation of the SAME byte (number
        termination, array-first fallthrough) — budget bookkeeping must run
        once per real byte, not per interpretation."""
        if self.dead:
            return False
        b = byte
        mode = self.mode
        if self.budget is not None:
            # Admissibility is checked per INTERPRETATION (so a byte that
            # terminates a number and is re-offered in AFTER mode is
            # re-checked against the new mode — the redo path must not
            # bypass wrap-up), but the budget decrements once per real byte.
            if self.budget <= 0 and not self._wrapup_allows(b):
                self.dead = True
                return False
            if not _redo:
                self.budget -= 1

        if mode == _STRING:
            if self.u8_need:  # inside a multi-byte UTF-8 character
                if self.u8_lo <= b <= self.u8_hi:
                    self.u8_need -= 1
                    self.u8_lo, self.u8_hi = 0x80, 0xBF
                    return True
                return self._die()
            if b == 0x5C:  # backslash
                self.mode = _STR_ESC
                return True
            if b == 0x22:  # closing quote
                if self.stack and self.stack[-1] == -1:
                    # This string was an object key: pop marker, expect colon.
                    self.stack.pop()
                    self.mode = _OBJ_COLON
                else:
                    self._close_value()
                return True
            if b < 0x20:
                return self._die()
            if b < 0x80:
                return True
            lead = utf8_lead(b)
            if lead is None:
                return self._die()
            self.u8_need, self.u8_lo, self.u8_hi = lead
            return True
        if mode == _STR_ESC:
            if self.hex_rem:
                if b in _HEX:
                    self.hex_rem -= 1
                    if self.hex_rem == 0:
                        self.mode = _STRING
                    return True
                return self._die()
            if b in _ESC_SIMPLE:
                self.mode = _STRING
                return True
            if b == 0x75:  # 'u' → four hex digits
                self.hex_rem = 4
                return True
            return self._die()
        if mode == _NUMBER:
            s = self.num_state
            if s == "neg":
                if b == 0x30:
                    self.num_state = "zero"
                    return True
                if b in _DIGITS:
                    self.num_state = "int"
                    return True
                return self._die()
            if s in ("zero", "int"):
                if b in _DIGITS:
                    if s == "zero":
                        return self._die()  # leading zero: 01 is not JSON
                    return True
                if b == 0x2E:  # '.'
                    self.num_state = "frac0"
                    return True
                if b in (0x65, 0x45):  # e/E
                    self.num_state = "exp0"
                    return True
            elif s == "frac0":
                if b in _DIGITS:
                    self.num_state = "frac"
                    return True
                return self._die()
            elif s == "frac":
                if b in _DIGITS:
                    return True
                if b in (0x65, 0x45):
                    self.num_state = "exp0"
                    return True
            elif s == "exp0":
                if b in (0x2B, 0x2D):  # sign
                    self.num_state = "exp1"
                    return True
                if b in _DIGITS:
                    self.num_state = "exp"
                    return True
                return self._die()
            elif s == "exp1":
                if b in _DIGITS:
                    self.num_state = "exp"
                    return True
                return self._die()
            elif s == "exp":
                if b in _DIGITS:
                    return True
            # Number ended; only complete DFA states may terminate, and the
            # byte is reinterpreted in AFTER mode.
            if self.num_state not in _NUM_COMPLETE:
                return self._die()
            self._close_value()
            self.complete = not self.stack and self.mode == _AFTER
            return self.advance(b, _redo=True)
        if mode == _LITERAL:
            if self.lit_pos < len(self.literal) and b == self.literal[self.lit_pos]:
                self.lit_pos += 1
                if self.lit_pos == len(self.literal):
                    self._close_value()
                return True
            return self._die()

        if b in _WS:
            return True

        if mode == _VALUE:
            if b == 0x22:  # '"'
                self.mode = _STRING
                return True
            if b == 0x7B:  # '{'
                if len(self.stack) >= self.max_depth:
                    return self._die()
                self.stack.append(0x7B)
                self.mode = _OBJ_KEY
                return True
            if b == 0x5B:  # '['
                if len(self.stack) >= self.max_depth:
                    return self._die()
                self.stack.append(0x5B)
                self.mode = _ARR_FIRST
                return True
            if b == 0x2D:  # '-'
                self.mode = _NUMBER
                self.num_state = "neg"
                return True
            if b in _DIGITS:
                self.mode = _NUMBER
                self.num_state = "zero" if b == 0x30 else "int"
                return True
            for lit in _LITERALS:
                if b == lit[0]:
                    self.mode = _LITERAL
                    self.literal, self.lit_pos = lit, 1
                    return True
            return self._die()

        if mode == _ARR_FIRST:
            if b == 0x5D:  # ']' — empty array
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            self.mode = _VALUE
            return self.advance(b, _redo=True)

        if mode in (_OBJ_KEY, _OBJ_KEY_REQ):
            if b == 0x22:
                self.stack.append(-1)  # marker: string being read is a key
                self.mode = _STRING
                return True
            if b == 0x7D and mode == _OBJ_KEY:  # '}' — empty object only;
                # after a comma a key is required (no trailing commas)
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            return self._die()

        if mode == _OBJ_COLON:
            if b == 0x3A:  # ':'
                self.mode = _VALUE
                return True
            return self._die()

        if mode == _AFTER:
            if not self.stack:
                return self._die()  # trailing garbage after a complete value
            top = self.stack[-1]
            if b == 0x2C:  # ','
                self.mode = _OBJ_KEY_REQ if top == 0x7B else _VALUE
                return True
            if b == 0x7D and top == 0x7B:
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            if b == 0x5D and top == 0x5B:
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            return self._die()

        return self._die()

    def _die(self) -> bool:
        self.dead = True
        return False

    def advance_bytes(self, data: bytes) -> bool:
        for b in data:
            if not self.advance(b):
                return False
        return True


def _in_string(machine) -> bool:
    """True when the automaton is inside string content (where whitespace
    tokens are real content, not structural padding). Both machine families
    expose ``in_string`` as part of their duck-typed contract — the logic
    lives with the frames, not here."""
    return bool(getattr(machine, "in_string", False))


# Token headroom the generic-JSON byte budget leaves for wrap-up: enough
# to close the deepest document a small budget can open (depth ≤ spent/2)
# plus an in-flight escape/UTF-8 tail, without eating a 48-token request's
# whole budget.
_JSON_WRAPUP_RESERVE = 16


class JsonMaskProvider:
    """Builds per-step allowed-token masks for an engine + tokenizer pair.

    ``schemas`` maps grammar names (``SamplingParams.guided`` values) to
    compiled schema trees (:mod:`runbookai_tpu.model.schema_guided`); the
    name ``"json"`` — or any unregistered name — selects the generic JSON
    automaton. Mask caching is shared: schema-machine signatures embed the
    schema name, so they never collide with generic-JSON signatures.
    """

    def __init__(self, tokenizer, schemas: Optional[dict] = None,
                 limits=None):
        self.tokenizer = tokenizer
        self.schemas = schemas or {}
        self.limits = limits
        self._token_bytes: Optional[list[bytes]] = None
        self._longest_token = 0  # set alongside _token_bytes
        self._cache: dict[tuple, np.ndarray] = {}
        self._vector: Optional[object] = None  # lazy VectorJsonMasker
        self._by_first: Optional[list[np.ndarray]] = None  # token ids per first byte
        # Control tokens are never content: their byte expansion is markup
        # ("<|eot_id|>") that would otherwise be admissible inside a string.
        self._special = frozenset(
            getattr(tokenizer, "special_ids", None)
            or (t for t in (tokenizer.bos_id, tokenizer.eos_id,
                            tokenizer.eot_id,
                            getattr(tokenizer, "pad_id", None))
                if t is not None)
        )

    def _bytes_table(self) -> list[bytes]:
        if self._token_bytes is None:
            self._token_bytes = [
                self.tokenizer.id_to_bytes(t) for t in range(self.tokenizer.vocab_size)
            ]
            self._longest_token = max(map(len, self._token_bytes))
        return self._token_bytes

    def machine_for(self, req):
        if req.guided_state is None:
            name = req.sampling.guided
            schema = self.schemas.get(name) if name else None
            if schema is not None:
                import dataclasses

                from runbookai_tpu.model.schema_guided import (
                    SchemaLimits,
                    SchemaMachine,
                )

                limits = self.limits or SchemaLimits()
                # Size the string-headroom cache bucket to the real vocab:
                # a bucket smaller than the longest token would let a cached
                # mask admit a token that overflows max_str_len.
                self._bytes_table()  # populates _longest_token once
                if limits.max_token_bytes < self._longest_token:
                    limits = dataclasses.replace(
                        limits, max_token_bytes=self._longest_token)
                req.guided_state = SchemaMachine(schema, name, limits=limits)
            else:
                # Budget-aware generic JSON: past ~the request's token
                # budget (bytes ≤ tokens: every token is ≥ 1 byte) the
                # machine enters WRAP-UP — only completion-directed bytes
                # stay admissible — so a random-weights model closes its
                # document INSIDE max_new_tokens instead of streaming an
                # ever-growing string into a "length" truncation that
                # parses as invalid JSON. The reserve leaves wrap-up room
                # to close every open string/container (closing needs at
                # most ~depth bytes, and depth ≤ budget/2).
                self._bytes_table()  # populates _longest_token once
                budget = max(4, req.sampling.max_new_tokens
                             - _JSON_WRAPUP_RESERVE)
                req.guided_state = JsonMachine(
                    budget=budget, budget_bucket=self._longest_token)
        return req.guided_state

    def mask(self, req) -> np.ndarray:
        machine = self.machine_for(req)
        sig = machine.signature()
        cached = self._cache.get(sig)
        if cached is not None:
            return cached
        table = self._bytes_table()
        # The vectorized sweep's packed automaton has no byte-budget
        # column: it is exact only while NO admissible token can cross the
        # wrap-up boundary mid-token, i.e. while the remaining budget
        # strictly exceeds the longest token's byte expansion (the same
        # hazard budget_bucket caps the cache signature for). At or below
        # the boundary, fall through to the scalar replay prober, which
        # runs the real machine (budget bookkeeping included) per token.
        if type(machine) is JsonMachine and (
                machine.budget is None
                or machine.budget > machine.budget_bucket):
            # Generic JSON: vectorized full-vocab sweep (guided_mask.py) —
            # ~max_token_len numpy passes instead of ~vocab Python replays.
            if self._vector is None:
                from runbookai_tpu.model.guided_mask import VectorJsonMasker

                self._vector = VectorJsonMasker(table)
            out = self._vector.mask(machine)
            for tid in self._special:
                out[tid] = False
        else:
            # Schema machines — and generic machines inside the wrap-up
            # boundary — keep the scalar prober, pre-filtered by
            # admissible first byte: forced-key/enum states admit a
            # handful of first bytes, so 256 one-byte probes eliminate
            # most of the vocab before any full replay.
            out = np.zeros(self.tokenizer.vocab_size, dtype=bool)
            first_ok = np.zeros(256, dtype=bool)
            for b in range(256):
                if machine.copy().advance(b):
                    first_ok[b] = True
            for tid in self._first_byte_groups(first_ok):
                bts = table[tid]
                if tid in self._special:
                    continue
                probe = machine.copy()
                if probe.advance_bytes(bts):
                    out[tid] = True
        # Steering tightening: in structural positions, suppress tokens that
        # are *pure whitespace* (kept only if nothing else is admissible).
        # JSON allows unlimited inter-token whitespace, so a greedy model
        # whose argmax is "\t" pads forever and the document never completes
        # within its token budget; banning ws-only tokens outside strings
        # keeps every admitted token making progress. String *content*
        # whitespace is untouched (mixed tokens like ",\n" stay admissible).
        if not _in_string(machine):
            ws = self._ws_only_ids()
            if ws.size and out[ws].any():
                trimmed = out.copy()
                trimmed[ws] = False
                if trimmed.any():
                    out = trimmed
        # Once the JSON value is complete, the stop token ends generation.
        if machine.is_complete:
            out[self.tokenizer.eot_id] = True
            out[self.tokenizer.eos_id] = True
        if not out.any():
            # Dead automaton (shouldn't happen): allow stop so we terminate.
            out[self.tokenizer.eot_id] = True
        self._cache[sig] = out
        return out

    def _ws_only_ids(self) -> np.ndarray:
        """Token ids whose byte expansion is entirely JSON whitespace."""
        ids = getattr(self, "_ws_ids", None)
        if ids is None:
            table = self._bytes_table()
            ids = np.array(
                [tid for tid, bts in enumerate(table)
                 if bts and all(b in _WS for b in bts)], dtype=np.int64)
            self._ws_ids = ids
        return ids

    def _first_byte_groups(self, first_ok: np.ndarray):
        """Token ids whose first byte is admissible, per precomputed
        first-byte buckets (built once per provider)."""
        if self._by_first is None:
            table = self._bytes_table()
            buckets: list[list[int]] = [[] for _ in range(256)]
            for tid, bts in enumerate(table):
                if bts:
                    buckets[bts[0]].append(tid)
            self._by_first = [np.array(b, dtype=np.int64) for b in buckets]
        for b in np.nonzero(first_ok)[0]:
            yield from self._by_first[int(b)].tolist()

    def advance(self, req, token: int) -> bool:
        """Feed a sampled token; True when the grammar is complete (stop)."""
        machine = self.machine_for(req)
        if token in (self.tokenizer.eot_id, self.tokenizer.eos_id):
            return machine.is_complete
        machine.advance_bytes(self.tokenizer.id_to_bytes(token))
        # Completion alone doesn't stop generation (whitespace may follow);
        # the mask above steers toward the stop token once complete.
        return False
