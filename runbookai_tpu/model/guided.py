"""Guided decoding: byte-level JSON automaton → token-level logit masks.

SURVEY.md §7 hard part 2: the product depends on schema-valid JSON from the
model (the reference's zod schemas in ``src/agent/llm-parser.ts:21-210`` were
parsed tolerantly because hosted models drift). Serving in-tree lets us do
better: a pushdown automaton over UTF-8 bytes accepts exactly the JSON
language, and per-step token masks admit only tokens whose *entire* byte
sequence keeps the automaton alive. The tolerant parser remains downstream as
a belt-and-suspenders fallback.

Masks are cached by automaton state signature — states repeat heavily (e.g.
"inside a string"), so even 128k-vocab tokenizers amortize to a handful of
mask computations per generation.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

# Automaton modes
_VALUE = 0  # expecting start of a value
_STRING = 1  # inside a string
_STR_ESC = 2  # after backslash in string
_NUMBER = 3  # inside a number
_LITERAL = 4  # inside true/false/null
_AFTER = 5  # after a complete value (expecting , } ] or end)
_OBJ_KEY = 6  # expecting object key string or '}'
_OBJ_COLON = 7  # expecting ':'

_WS = b" \t\n\r"
_DIGITS = b"0123456789"
_NUM_CONT = b"0123456789.eE+-"
_LITERALS = {b"true", b"false", b"null"}


class JsonMachine:
    """Incremental JSON validator over bytes."""

    def __init__(self, max_depth: int = 32):
        self.mode = _VALUE
        self.stack: list[int] = []  # 123 for '{', 91 for '['
        self.literal: bytes = b""
        self.lit_pos = 0
        self.max_depth = max_depth
        self.complete = False
        self.dead = False
        self.num_has_digit = False

    @property
    def is_complete(self) -> bool:
        """True when the bytes so far form a complete JSON document. A
        top-level number qualifies once it has a digit (numbers have no
        terminator byte)."""
        return self.complete or (
            self.mode == _NUMBER and not self.stack and self.num_has_digit
        )

    def signature(self) -> tuple:
        return (self.mode, tuple(self.stack), self.literal, self.lit_pos,
                self.complete, self.num_has_digit)

    def copy(self) -> "JsonMachine":
        m = JsonMachine(self.max_depth)
        m.mode, m.stack = self.mode, list(self.stack)
        m.literal, m.lit_pos = self.literal, self.lit_pos
        m.complete, m.dead = self.complete, self.dead
        m.num_has_digit = self.num_has_digit
        return m

    # ------------------------------------------------------------------ core

    def _close_value(self) -> None:
        """A value just finished; decide what comes next."""
        if not self.stack:
            self.mode = _AFTER
            self.complete = True
        else:
            self.mode = _AFTER

    def advance(self, byte: int) -> bool:
        """Consume one byte; returns False (and goes dead) on violation."""
        if self.dead:
            return False
        b = byte
        mode = self.mode

        if mode == _STRING:
            if b == 0x5C:  # backslash
                self.mode = _STR_ESC
            elif b == 0x22:  # closing quote
                if self.stack and self.stack[-1] == -1:
                    # This string was an object key: pop marker, expect colon.
                    self.stack.pop()
                    self.mode = _OBJ_COLON
                else:
                    self._close_value()
            elif b < 0x20:
                return self._die()
            return True
        if mode == _STR_ESC:
            # Accept any printable escape continuation (full \uXXXX validation
            # is intentionally lax — invalid escapes are caught by json.loads).
            self.mode = _STRING
            return True
        if mode == _NUMBER:
            if b in _NUM_CONT:
                if b in _DIGITS:
                    self.num_has_digit = True
                return True
            # Number ended; reinterpret this byte in AFTER mode.
            self._close_value()
            self.complete = not self.stack and self.mode == _AFTER
            return self.advance(b)
        if mode == _LITERAL:
            if self.lit_pos < len(self.literal) and b == self.literal[self.lit_pos]:
                self.lit_pos += 1
                if self.lit_pos == len(self.literal):
                    self._close_value()
                return True
            return self._die()

        if b in _WS:
            return True

        if mode == _VALUE:
            if b == 0x22:  # '"'
                self.mode = _STRING
                return True
            if b == 0x7B:  # '{'
                if len(self.stack) >= self.max_depth:
                    return self._die()
                self.stack.append(0x7B)
                self.mode = _OBJ_KEY
                return True
            if b == 0x5B:  # '['
                if len(self.stack) >= self.max_depth:
                    return self._die()
                self.stack.append(0x5B)
                self.mode = _VALUE
                return True
            if b == 0x5D and self.stack and self.stack[-1] == 0x5B:  # empty array
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            if b in _DIGITS or b == 0x2D:  # digit or '-'
                self.mode = _NUMBER
                self.num_has_digit = b in _DIGITS
                return True
            for lit in _LITERALS:
                if b == lit[0]:
                    self.mode = _LITERAL
                    self.literal, self.lit_pos = lit, 1
                    return True
            return self._die()

        if mode == _OBJ_KEY:
            if b == 0x22:
                self.stack.append(-1)  # marker: string being read is a key
                self.mode = _STRING
                return True
            if b == 0x7D:  # '}' — empty object
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            return self._die()

        if mode == _OBJ_COLON:
            if b == 0x3A:  # ':'
                self.mode = _VALUE
                return True
            return self._die()

        if mode == _AFTER:
            if not self.stack:
                return self._die()  # trailing garbage after a complete value
            top = self.stack[-1]
            if b == 0x2C:  # ','
                self.mode = _OBJ_KEY if top == 0x7B else _VALUE
                return True
            if b == 0x7D and top == 0x7B:
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            if b == 0x5D and top == 0x5B:
                self.stack.pop()
                self._close_value()
                self.complete = not self.stack
                return True
            return self._die()

        return self._die()

    def _die(self) -> bool:
        self.dead = True
        return False

    def advance_bytes(self, data: bytes) -> bool:
        for b in data:
            if not self.advance(b):
                return False
        return True


class JsonMaskProvider:
    """Builds per-step allowed-token masks for an engine + tokenizer pair."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        self._token_bytes: Optional[list[bytes]] = None
        self._cache: dict[tuple, np.ndarray] = {}

    def _bytes_table(self) -> list[bytes]:
        if self._token_bytes is None:
            self._token_bytes = [
                self.tokenizer.id_to_bytes(t) for t in range(self.tokenizer.vocab_size)
            ]
        return self._token_bytes

    def machine_for(self, req) -> JsonMachine:
        if req.guided_state is None:
            req.guided_state = JsonMachine()
        return req.guided_state

    def mask(self, req) -> np.ndarray:
        machine = self.machine_for(req)
        sig = machine.signature()
        cached = self._cache.get(sig)
        if cached is not None:
            return cached
        table = self._bytes_table()
        out = np.zeros(self.tokenizer.vocab_size, dtype=bool)
        for tid, bts in enumerate(table):
            if not bts:
                continue
            probe = machine.copy()
            if probe.advance_bytes(bts):
                out[tid] = True
        # Once the JSON value is complete, the stop token ends generation.
        if machine.is_complete:
            out[self.tokenizer.eot_id] = True
            out[self.tokenizer.eos_id] = True
        if not out.any():
            # Dead automaton (shouldn't happen): allow stop so we terminate.
            out[self.tokenizer.eot_id] = True
        self._cache[sig] = out
        return out

    def advance(self, req, token: int) -> bool:
        """Feed a sampled token; True when the grammar is complete (stop)."""
        machine = self.machine_for(req)
        if token in (self.tokenizer.eot_id, self.tokenizer.eos_id):
            return machine.is_complete
        machine.advance_bytes(self.tokenizer.id_to_bytes(token))
        # Completion alone doesn't stop generation (whitespace may follow);
        # the mask above steers toward the stop token once complete.
        return False
