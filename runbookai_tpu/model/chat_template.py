"""Llama-3 chat template + tool-calling prompt adapter and output parser.

SURVEY.md §7 step 3 "tool-calling adapter": the reference converts its tool
schema into each hosted provider's native tool format
(``src/model/llm.ts:208-235``) and gets structured tool-call blocks back. An
open model served in-tree has no native tool channel, so tools are formatted
into the system prompt and tool calls are parsed from the output with the
same tolerant JSON extraction strategy the reference uses for structured
responses (``src/agent/llm-parser.ts:215``: raw → fenced → brace matching).
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

from runbookai_tpu.agent.types import ToolCall

BEGIN = "<|begin_of_text|>"
H_START = "<|start_header_id|>"
H_END = "<|end_header_id|>"
EOT = "<|eot_id|>"

TOOL_INSTRUCTIONS = """\

# Tool calling

You have access to the following tools, described as JSON schemas:

{tool_schemas}

To call tools, respond with ONLY a JSON object of this exact shape (no prose
before or after it):

{{"tool_calls": [{{"name": "<tool name>", "args": {{<arguments>}}}}]}}

You may request several tool calls in one response. When you have enough
information to answer, respond with plain text instead (no JSON wrapper).\
"""


def render_message(role: str, content: str) -> str:
    return f"{H_START}{role}{H_END}\n\n{content}{EOT}"


_FAMILY_FORMATS = {"llama": "llama3", "qwen2": "chatml", "mistral": "mistral",
                   "mixtral": "mistral"}


def format_for_model(model_name: str, family: str | None = None) -> str:
    """Prompt format by model family: ``llama3`` (default), ``chatml``
    (Qwen2), ``mistral`` ([INST] wrapping).

    ``family`` — the loaded config's authoritative family (from HF
    ``model_type``) — wins; the name sniff is the fallback for bare names
    (e.g. a fine-tune served under an arbitrary name)."""
    if family in _FAMILY_FORMATS:
        return _FAMILY_FORMATS[family]
    n = model_name.lower()
    if "qwen" in n:
        return "chatml"
    if "mistral" in n or "mixtral" in n:
        return "mistral"
    return "llama3"


def _render_llama3(system: str, history, user_prompt: str) -> str:
    parts = [BEGIN, render_message("system", system)]
    for role, content in history or []:
        parts.append(render_message(role, content))
    parts.append(render_message("user", user_prompt))
    parts.append(f"{H_START}assistant{H_END}\n\n")
    return "".join(parts)


def _render_chatml(system: str, history, user_prompt: str) -> str:
    def msg(role, content):
        return f"<|im_start|>{role}\n{content}<|im_end|>\n"

    parts = [msg("system", system)]
    for role, content in history or []:
        parts.append(msg(role, content))
    parts.append(msg("user", user_prompt))
    parts.append("<|im_start|>assistant\n")
    return "".join(parts)


def _render_mistral(system: str, history, user_prompt: str) -> str:
    # Mistral-instruct: system folded into the first user turn; assistant
    # turns closed with </s>.
    turns = list(history or []) + [("user", user_prompt)]
    out = ["<s>"]
    first_user = True
    for role, content in turns:
        if role == "user":
            if first_user and system:
                content = f"{system}\n\n{content}"
                first_user = False
            out.append(f"[INST] {content} [/INST]")
        else:
            out.append(f" {content}</s>")
    return "".join(out)


_RENDERERS = {"llama3": _render_llama3, "chatml": _render_chatml,
              "mistral": _render_mistral}


def build_chat_prompt(
    system_prompt: str,
    user_prompt: str,
    tools: Optional[list[dict[str, Any]]] = None,
    history: Optional[list[tuple[str, str]]] = None,
    fmt: str = "llama3",
) -> str:
    """Render the full chat prompt ending at the assistant turn opener."""
    system = system_prompt or "You are a helpful assistant."
    if tools:
        schemas = json.dumps(tools, indent=2)
        system += TOOL_INSTRUCTIONS.format(tool_schemas=schemas)
    return _RENDERERS[fmt](system, history, user_prompt)


def build_completion_prompt(prompt: str, fmt: str = "llama3") -> str:
    """The orchestrator's ``complete(prompt)`` path: single user turn."""
    return build_chat_prompt("", prompt, fmt=fmt)


# --------------------------------------------------------------------------- #
# output parsing                                                              #
# --------------------------------------------------------------------------- #

_FENCE_RE = re.compile(r"```(?:json)?\s*(.*?)```", re.DOTALL)


def extract_json(text: str) -> Optional[Any]:
    """Tolerant JSON extraction: raw parse → fenced block → brace matching
    (reference ``llm-parser.ts:215`` strategy)."""
    text = text.strip()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        pass
    for match in _FENCE_RE.finditer(text):
        try:
            return json.loads(match.group(1).strip())
        except json.JSONDecodeError:
            continue
    # Brace matching: first balanced {...} or [...] that parses.
    for opener, closer in (("{", "}"), ("[", "]")):
        start = text.find(opener)
        while start != -1:
            depth = 0
            in_str = False
            esc = False
            for i in range(start, len(text)):
                ch = text[i]
                if esc:
                    esc = False
                    continue
                if ch == "\\":
                    esc = in_str
                    continue
                if ch == '"':
                    in_str = not in_str
                    continue
                if in_str:
                    continue
                if ch == opener:
                    depth += 1
                elif ch == closer:
                    depth -= 1
                    if depth == 0:
                        try:
                            return json.loads(text[start : i + 1])
                        except json.JSONDecodeError:
                            break
            start = text.find(opener, start + 1)
    return None


def parse_assistant_output(text: str) -> tuple[str, list[ToolCall], Optional[str]]:
    """Split raw assistant output into (content, tool_calls, thinking).

    ``<thinking>...</thinking>`` blocks (if the prompt elicits them) are
    captured separately, mirroring the reference's thinking-block parsing
    (``src/model/llm.ts:240-274``).
    """
    thinking = None
    m = re.search(r"<thinking>(.*?)</thinking>", text, re.DOTALL)
    if m:
        thinking = m.group(1).strip()
        text = (text[: m.start()] + text[m.end() :]).strip()

    payload = extract_json(text)
    if isinstance(payload, dict) and isinstance(payload.get("tool_calls"), list):
        calls = []
        for item in payload["tool_calls"]:
            if not isinstance(item, dict) or "name" not in item:
                continue
            args = item.get("args") or item.get("arguments") or {}
            if not isinstance(args, dict):
                args = {}
            calls.append(ToolCall.new(str(item["name"]), args))
        if calls:
            content = payload.get("content") or ""
            return str(content), calls, thinking
    return text.strip(), [], thinking
