"""The ``jax-tpu`` LLM provider: agent seam → in-tree serving engine.

This is THE replacement seam (SURVEY.md §2.2): where the reference's
``PiAIClient`` posts to hosted provider HTTP APIs, this client renders the
Llama-3 chat template, submits to the continuous-batching engine, and parses
tool calls / JSON out of the decoded text. ``complete()`` uses guided JSON
decoding so the structured orchestrator receives schema-parseable output.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from runbookai_tpu.agent.types import LLMResponse
from runbookai_tpu.engine.async_engine import AsyncEngine
from runbookai_tpu.engine.engine import (
    EngineConfig,
    EngineCore,
    resolve_kv_dtype,
)
from runbookai_tpu.engine.request import SamplingParams
from runbookai_tpu.model.chat_template import (
    build_chat_prompt,
    build_completion_prompt,
    format_for_model,
    parse_assistant_output,
)
from runbookai_tpu.model.client import BaseLLMClient
from runbookai_tpu.model.guided import JsonMaskProvider
from runbookai_tpu.model.schema_guided import orchestrator_schemas
from runbookai_tpu.models.hf_loader import load_or_init
from runbookai_tpu.utils.tokens import load_tokenizer


async def stream_text(engine, tokenizer, prompt_ids, sampling,
                      state: Optional[dict] = None, priority: int = 0,
                      adapter: Optional[str] = None,
                      request_sink: Optional[list] = None,
                      request_id: Optional[str] = None):
    """Token stream -> text-piece stream, shared by every streaming surface
    (client ``chat_stream``, OpenAI SSE endpoint): incremental UTF-8 decode
    over per-token bytes (multi-byte chars split across tokens never yield
    mojibake) and stop-token skipping, mirroring ``EngineCore.output_for``.
    ``state`` (optional dict) receives ``n_tokens`` / ``saw_stop`` for
    finish-reason reporting."""
    import codecs

    stop_ids = {tokenizer.eot_id, tokenizer.eos_id}
    decoder = codecs.getincrementaldecoder("utf-8")("replace")
    async for tok in engine.generate_stream(prompt_ids, sampling,
                                            priority=priority,
                                            adapter=adapter,
                                            request_sink=request_sink,
                                            request_id=request_id):
        if state is not None:
            state["n_tokens"] = state.get("n_tokens", 0) + 1
        if tok in stop_ids:
            if state is not None:
                state["saw_stop"] = True
            continue
        piece = decoder.decode(tokenizer.id_to_bytes(tok))
        if piece:
            yield piece
    tail = decoder.decode(b"", final=True)
    if tail:
        yield tail


class JaxTpuClient(BaseLLMClient):
    def __init__(
        self,
        core: "EngineCore | list[EngineCore]",
        tokenizer,
        temperature: float = 0.0,
        top_p: float = 1.0,
        top_k: int = 0,
        max_new_tokens: int = 1024,
        guided_json: bool = True,
        chat_format: str = "llama3",
        fleet_cfg=None,
        slo_monitor=None,
        tenants=None,
    ):
        # ``core`` may be a data-parallel fleet (list of replicas, built by
        # engine/fleet.build_engine_fleet when EngineConfig.dp_replicas > 1):
        # the client then serves through an AsyncFleet with the same
        # generate/generate_stream surface, and ``self.core`` stays replica
        # 0 for surfaces that need the shared pieces (LoRA registry names,
        # tokenizer-adjacent config) — fleet-wide state goes through
        # ``self.engine.health_snapshot()``. ``fleet_cfg`` (a
        # fleet.FleetConfig) carries the router policy knobs.
        cores = list(core) if isinstance(core, (list, tuple)) else [core]
        self.cores = cores
        self.core = cores[0]
        if len(cores) > 1:
            from runbookai_tpu.engine.fleet import AsyncFleet

            self.engine = AsyncFleet(cores, fleet_cfg)
        else:
            self.engine = AsyncEngine(self.core)
        self.tokenizer = tokenizer
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        self.max_new_tokens = max_new_tokens
        self.guided_json = guided_json
        self.chat_format = chat_format
        # SLO monitor (utils/slo.py, built by from_config from llm.slo):
        # /healthz reads it for the live burn-ratio block; None when no
        # objective is configured (zero SLO surface).
        self.slo_monitor = slo_monitor
        # Tenant admission governor (sched/tenants.py, built by
        # from_config from llm.tenants): the OpenAI server gates every
        # chat/completions request through it BEFORE enqueue. None = no
        # tenant surface.
        self.tenants = tenants

    # ------------------------------------------------------------- factories

    @classmethod
    def from_config(cls, llm_cfg) -> "JaxTpuClient":
        """Build engine + client from an ``LLMConfig`` (utils/config.py).

        A real checkpoint is discovered automatically: configured
        ``model_path`` first, else ``$RUNBOOK_WEIGHTS`` (utils/weights.py)
        — so live eval banks pass@1 the moment weights exist (VERDICT r4
        #3) with no config change.

        ``llm.plan`` makes a ``runbook tune`` serving-plan artifact a
        first-class config input: the plan's engine block supplies every
        knob the sweep decided, while keys the operator set EXPLICITLY in
        YAML keep winning (``autotune.plan.apply_plan_to_llm`` reads
        pydantic's ``model_fields_set`` for exactly that precedence), and
        plan keys with no YAML spelling (speculative, mixed_token_budget,
        …) land directly on the built EngineConfig."""
        from runbookai_tpu.utils.weights import discover_weights

        serving_plan = None
        if getattr(llm_cfg, "plan", None):
            from runbookai_tpu.autotune.plan import (
                apply_plan_to_llm,
                load_plan,
            )

            serving_plan = load_plan(llm_cfg.plan)
            if serving_plan.model != llm_cfg.model:
                raise ValueError(
                    f"llm.plan {serving_plan.plan_id!r} was tuned for "
                    f"model {serving_plan.model!r}, not {llm_cfg.model!r} "
                    f"— plans are per model×topology; re-run "
                    f"`runbook tune`")
            llm_cfg = apply_plan_to_llm(llm_cfg, serving_plan)

        model_path = discover_weights(llm_cfg.model, llm_cfg.model_path)
        tokenizer = load_tokenizer(llm_cfg.tokenizer_path or model_path)
        mesh = None
        shardings = None
        model_cfg_name = llm_cfg.model
        # int8 = weight-only quantization; activations and KV stay bf16.
        quantize = llm_cfg.dtype == "int8"
        dtype = jnp.float32 if llm_cfg.dtype == "float32" else jnp.bfloat16
        dp_replicas = max(1, getattr(llm_cfg, "dp_replicas", 1))
        if dp_replicas > 1 and llm_cfg.mesh.device_count > 1:
            # Replicas are single-slice engines; sharding a model WITHIN a
            # replica on top of dp is a later composition — refuse loudly
            # rather than silently building N full-mesh engines that all
            # claim the same devices.
            raise ValueError(
                "llm.dp_replicas > 1 requires llm.mesh.data/model = 1 "
                "(each fleet replica owns its own device slice)")
        if llm_cfg.mesh.device_count > 1:
            from runbookai_tpu.models.llama import CONFIGS
            from runbookai_tpu.parallel.kv_split import plan_kv_split
            from runbookai_tpu.parallel.mesh import build_mesh
            from runbookai_tpu.parallel.sharding import param_shardings

            # KV layout planning: tp past the GQA head count factors onto
            # (model=kv_shards, seq=pg_shards) so the page pool shards by
            # the FULL tp (parallel/kv_split.py) instead of replicating.
            plan = (plan_kv_split(CONFIGS[llm_cfg.model],
                                  llm_cfg.mesh.model)
                    if llm_cfg.model in CONFIGS else None)
            if plan is not None and plan.split:
                mesh = build_mesh(llm_cfg.mesh.data, model=plan.kv_shards,
                                  seq=plan.pg_shards)
            else:
                mesh = build_mesh(llm_cfg.mesh.data, llm_cfg.mesh.model)
            if model_cfg_name in CONFIGS:
                shardings = param_shardings(CONFIGS[model_cfg_name], mesh)
                if quantize:
                    from runbookai_tpu.models.quant import shardings_with_quant

                    shardings = shardings_with_quant(shardings)
        cfg, params = load_or_init(
            model_cfg_name, model_path, dtype=dtype, shardings=shardings,
            quantize_int8=quantize,
        )
        kv_dtype = resolve_kv_dtype(llm_cfg.kv_cache_dtype, dtype)
        ecfg = EngineConfig(
            page_size=llm_cfg.page_size,
            num_pages=llm_cfg.num_pages,
            max_batch_slots=llm_cfg.max_batch_slots,
            prefill_chunk=llm_cfg.prefill_chunk,
            max_seq_len=min(llm_cfg.max_seq_len, cfg.max_seq_len),
            kv_dtype=kv_dtype,
            decode_steps_per_dispatch=llm_cfg.decode_steps,
            # The Pallas ragged-paged kernels are the TPU hot path (VERDICT r1
            # weak #3); the XLA gather path stays the portable fallback. On a
            # TP mesh the kernels run per head-shard via shard_map
            # (ops/paged_attention_pallas.py) — forward_impl itself falls
            # back to XLA attention only when GQA heads don't divide the
            # model axis (where the pool replicates anyway).
            attn_impl=(llm_cfg.attn_impl if llm_cfg.attn_impl != "auto"
                       else ("pallas"
                             if jax.default_backend() in ("tpu", "axon")
                             else "xla")),
            # The Pallas quantized matmul streams int8 weight tiles (half
            # the bf16 HBM bytes, the decode bound) — on-TPU default for
            # int8 weights; meaningless for unquantized ones.
            qmm_impl=(llm_cfg.qmm_impl if llm_cfg.qmm_impl != "auto"
                      else ("pallas"
                            if quantize and jax.default_backend()
                            in ("tpu", "axon")
                            else "xla")),
            dp_replicas=dp_replicas,
            kv_spill_pages=getattr(llm_cfg, "kv_spill_pages", 0),
        )
        sched_cfg = getattr(llm_cfg, "sched", None)
        if sched_cfg is not None:
            # Priority-class scheduling policy (llm.sched → sched/wdrr.py):
            # the weighted-deficit interleave by default, with the two
            # canonical class weights from config.
            import dataclasses as _dc

            from runbookai_tpu.sched import (
                PRIORITY_BATCH,
                PRIORITY_INTERACTIVE,
            )

            ecfg = _dc.replace(
                ecfg, sched_policy=sched_cfg.policy,
                sched_weights={
                    PRIORITY_BATCH: sched_cfg.batch_weight,
                    PRIORITY_INTERACTIVE: sched_cfg.interactive_weight,
                })
        if serving_plan is not None:
            from runbookai_tpu.autotune.plan import engine_only_overrides

            # Plan keys with no llm.* spelling (speculative,
            # mixed_token_budget, prefill_batch, block_pages, …) apply
            # straight onto the engine config. (Named serving_plan: the
            # TP branch above rebinds `plan` to a KVSplitPlan.)
            overrides = engine_only_overrides(serving_plan)
            if overrides:
                import dataclasses as _dc

                ecfg = _dc.replace(ecfg, **overrides)
        lora_registry = None
        if getattr(llm_cfg, "lora_adapters", None):
            from runbookai_tpu.models.lora import LoraRegistry

            lora_registry = LoraRegistry(
                cfg, rank=llm_cfg.lora_rank,
                targets=tuple(llm_cfg.lora_targets), dtype=dtype)
            for name, path in llm_cfg.lora_adapters.items():
                lora_registry.load_peft_dir(name, path)
        draft_factory = None
        if llm_cfg.draft_model:
            from runbookai_tpu.engine.draft import DraftWorker

            dcfg, dparams = load_or_init(
                llm_cfg.draft_model, llm_cfg.draft_model_path, dtype=dtype)

            def draft_factory(_idx: int) -> "DraftWorker":
                # One worker per replica: its slot/page state is
                # per-engine and cannot be shared across cores.
                return DraftWorker(
                    dcfg, dparams, max_batch_slots=ecfg.max_batch_slots,
                    max_seq_len=ecfg.max_seq_len, page_size=ecfg.page_size,
                    attn_impl=ecfg.attn_impl)
        masker = JsonMaskProvider(tokenizer, schemas=orchestrator_schemas())
        fleet_cfg = None
        if dp_replicas > 1:
            from runbookai_tpu.engine.fleet import (
                FleetConfig,
                build_engine_fleet,
            )

            router = getattr(llm_cfg, "fleet", None)
            if router is not None:
                disagg = getattr(router, "disagg", None)
                disagg_n = (disagg.prefill_replicas
                            if disagg is not None and disagg.enabled else 0)
                fleet_cfg = FleetConfig(
                    affinity=router.affinity,
                    affinity_load_slack=router.affinity_load_slack,
                    shed_queue_depth=router.shed_queue_depth,
                    max_retries=router.max_retries,
                    kv_share=getattr(router, "kv_share", False),
                    kv_share_min_pages=getattr(router, "kv_share_min_pages",
                                               1),
                    disagg_prefill_replicas=disagg_n,
                    disagg_min_prompt_pages=(disagg.min_prompt_pages
                                             if disagg_n else 1))
            # Pod scale-out: each process builds only ITS replicas over
            # its local chips — replicas never span hosts (their device
            # slices must stay in one ICI domain). Single process owns
            # the whole fleet over the (== local) global device list.
            replica_indices = None
            fleet_devices = None
            if jax.process_count() > 1:
                from runbookai_tpu.parallel.multihost import (
                    local_replica_range,
                )

                replica_indices = list(local_replica_range(dp_replicas))
                fleet_devices = jax.local_devices()
            core = build_engine_fleet(
                cfg, params, tokenizer, ecfg,
                mask_fn=masker.mask, advance_fn=masker.advance,
                lora_registry=lora_registry,
                draft_worker_factory=draft_factory,
                devices=fleet_devices,
                replica_indices=replica_indices,
            )
        else:
            core = EngineCore(
                cfg, params, tokenizer, ecfg,
                mask_fn=masker.mask, advance_fn=masker.advance, mesh=mesh,
                lora_registry=lora_registry,
                draft_worker=draft_factory(0) if draft_factory else None,
            )
        slo_monitor = None
        if getattr(llm_cfg, "slo", None) is not None:
            from runbookai_tpu.utils.slo import SLOMonitor

            # None when llm.slo sets no objective: an unconfigured run
            # must export zero runbook_slo_* series.
            slo_monitor = SLOMonitor.from_config(llm_cfg.slo)
        if sched_cfg is not None and getattr(sched_cfg, "feedback", False):
            # SLO feedback (llm.sched.feedback → sched/feedback.py): one
            # controller per core — each core's prefill share is its own
            # actuator, all read the same process-wide TPOT burn. A
            # feedback config without the tpot_p95_ms objective raises
            # here (an open loop labeled closed is worse than failing).
            from runbookai_tpu.sched import MixedBudgetController

            for c in (core if isinstance(core, list) else [core]):
                c.feedback = MixedBudgetController.for_core(sched_cfg,
                                                            slo_monitor)
        tenants = None
        if getattr(llm_cfg, "tenants", None) is not None:
            from runbookai_tpu.sched import TenantGovernor

            # None when llm.tenants is absent/disabled: zero tenant
            # surface, the server admits everything exactly as before.
            tenants = TenantGovernor.from_config(llm_cfg.tenants)
        return cls(
            core, tokenizer,
            temperature=llm_cfg.temperature, top_p=llm_cfg.top_p,
            top_k=llm_cfg.top_k,
            max_new_tokens=llm_cfg.max_new_tokens, guided_json=llm_cfg.guided_json,
            chat_format=format_for_model(model_cfg_name, cfg.family),
            fleet_cfg=fleet_cfg,
            slo_monitor=slo_monitor,
            tenants=tenants,
        )

    @classmethod
    def for_testing(cls, model_name: str = "llama3-test",
                    temperature: float = 0.0, max_new_tokens: int = 32,
                    max_seq_len: int = 256, schema_limits=None,
                    lora_registry=None, **engine_kw) -> "JaxTpuClient":
        """Tiny random-init client on the byte tokenizer (CPU tests)."""
        tokenizer = load_tokenizer(None)
        cfg, params = load_or_init(model_name, None, dtype=jnp.float32)
        ecfg_kw = dict(page_size=4, num_pages=256, max_batch_slots=4,
                       prefill_chunk=32, max_seq_len=max_seq_len,
                       kv_dtype=jnp.float32)
        ecfg_kw.update(engine_kw)  # tests may override any default
        ecfg = EngineConfig(**ecfg_kw)
        masker = JsonMaskProvider(tokenizer, schemas=orchestrator_schemas(),
                                  limits=schema_limits)
        if ecfg.dp_replicas > 1:
            from runbookai_tpu.engine.fleet import build_engine_fleet

            core = build_engine_fleet(
                cfg, params, tokenizer, ecfg,
                mask_fn=masker.mask, advance_fn=masker.advance,
                lora_registry=lora_registry)
        else:
            core = EngineCore(cfg, params, tokenizer, ecfg,
                              mask_fn=masker.mask, advance_fn=masker.advance,
                              lora_registry=lora_registry)
        return cls(core, tokenizer, temperature=temperature,
                   max_new_tokens=max_new_tokens,
                   chat_format=format_for_model(model_name, cfg.family))

    # ------------------------------------------------------------------- API

    def _sampling(self, guided: Optional[str] = None, max_new: Optional[int] = None) -> SamplingParams:
        return SamplingParams(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            max_new_tokens=max_new or self.max_new_tokens,
            stop_token_ids=(self.tokenizer.eot_id, self.tokenizer.eos_id),
            guided=guided,
        )

    async def chat(self, system_prompt, user_prompt, tools=None) -> LLMResponse:
        prompt = build_chat_prompt(system_prompt, user_prompt, tools,
                                   fmt=self.chat_format)
        ids = self.tokenizer.encode(prompt)
        out = await self.engine.generate(ids, self._sampling())
        content, tool_calls, thinking = parse_assistant_output(out.text)
        return LLMResponse(
            content=content,
            tool_calls=tool_calls,
            thinking=thinking,
            usage={
                "prompt_tokens": len(ids),
                "completion_tokens": out.decode_tokens,
                "ttft_ms": int(out.ttft_ms or 0),
            },
        )

    async def chat_stream(self, system_prompt, user_prompt, tools=None):
        """TRUE token streaming override of the BaseLLMClient fallback
        (which chunks a completed response). Yields the same event-dict
        protocol: ``{"type": "text", "delta"}`` per decoded piece, then
        parsed ``tool_call`` events, then ``{"type": "done", "response"}``.

        Divergence from the fallback, by design: text deltas are the RAW
        model output as sampled (tool-call/thinking markup included — it
        cannot be parsed out until the document completes), while
        ``done.response.content`` is the parsed content, exactly as
        :meth:`chat` returns it. Consumers that must render only parsed
        content should buffer until ``done``.

        Text decoding/stop handling is the shared :func:`stream_text`
        (also behind the OpenAI SSE endpoint).
        """
        prompt = build_chat_prompt(system_prompt, user_prompt, tools,
                                   fmt=self.chat_format)
        ids = self.tokenizer.encode(prompt)
        state: dict = {}
        parts: list[str] = []
        async for piece in stream_text(self.engine, self.tokenizer, ids,
                                       self._sampling(), state=state):
            parts.append(piece)
            yield {"type": "text", "delta": piece}
        content, tool_calls, thinking = parse_assistant_output("".join(parts))
        for call in tool_calls:
            yield {"type": "tool_call", "call": call}
        yield {"type": "done", "response": LLMResponse(
            content=content, tool_calls=tool_calls, thinking=thinking,
            usage={"prompt_tokens": len(ids),
                   "completion_tokens": state.get("n_tokens", 0)})}

    def _completion_request(self, prompt: str, guided: Optional[bool],
                            schema: Optional[str]):
        """(ids, sampling) for a completion — ONE place for the guided
        default / prompt build / grammar pick, so the buffered and
        streaming paths cannot drift (their text must stay identical)."""
        use_guided = self.guided_json if guided is None else guided
        ids = self.tokenizer.encode(
            build_completion_prompt(prompt, fmt=self.chat_format))
        grammar = (schema or "json") if use_guided else None
        return ids, self._sampling(guided=grammar)

    async def complete(self, prompt: str, guided: Optional[bool] = None,
                       schema: Optional[str] = None) -> str:
        """Plain completion; guided JSON masking on by default (config) since
        every orchestrator prompt expects a JSON document back. ``schema``
        names a compiled grammar (``"triage"``, ``"evaluation"``, … — see
        :func:`~runbookai_tpu.model.schema_guided.orchestrator_schemas`)
        that constrains the output to exactly that document shape."""
        ids, sampling = self._completion_request(prompt, guided, schema)
        out = await self.engine.generate(ids, sampling)
        return out.text

    async def complete_stream(self, prompt: str,
                              guided: Optional[bool] = None,
                              schema: Optional[str] = None):
        """Streaming twin of :meth:`complete`: yields text deltas as the
        engine samples (grammar fast-forwarded runs arrive as one burst).
        The orchestrator uses it to paint phase documents live under the
        hypothesis tree."""
        ids, sampling = self._completion_request(prompt, guided, schema)
        async for piece in stream_text(self.engine, self.tokenizer, ids,
                                       sampling):
            yield piece

    async def shutdown(self) -> None:
        await self.engine.stop()
