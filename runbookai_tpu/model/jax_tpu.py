"""The ``jax-tpu`` LLM provider: agent seam → in-tree serving engine.

This is THE replacement seam (SURVEY.md §2.2): where the reference's
``PiAIClient`` posts to hosted provider HTTP APIs, this client renders the
Llama-3 chat template, submits to the continuous-batching engine, and parses
tool calls / JSON out of the decoded text. ``complete()`` uses guided JSON
decoding so the structured orchestrator receives schema-parseable output.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from runbookai_tpu.agent.types import LLMResponse
from runbookai_tpu.engine.async_engine import AsyncEngine
from runbookai_tpu.engine.engine import (
    EngineConfig,
    EngineCore,
)
from runbookai_tpu.engine.request import SamplingParams
from runbookai_tpu.model.chat_template import (
    build_chat_prompt,
    build_completion_prompt,
    format_for_model,
    parse_assistant_output,
)
from runbookai_tpu.model.client import BaseLLMClient
from runbookai_tpu.model.guided import JsonMaskProvider
from runbookai_tpu.model.schema_guided import orchestrator_schemas
from runbookai_tpu.models.hf_loader import load_or_init
from runbookai_tpu.utils.tokens import load_tokenizer


async def stream_text(engine, tokenizer, prompt_ids, sampling,
                      state: Optional[dict] = None, priority: int = 0,
                      adapter: Optional[str] = None,
                      request_sink: Optional[list] = None,
                      request_id: Optional[str] = None):
    """Token stream -> text-piece stream, shared by every streaming surface
    (client ``chat_stream``, OpenAI SSE endpoint): incremental UTF-8 decode
    over per-token bytes (multi-byte chars split across tokens never yield
    mojibake) and stop-token skipping, mirroring ``EngineCore.output_for``.
    ``state`` (optional dict) receives ``n_tokens`` / ``saw_stop`` for
    finish-reason reporting."""
    import codecs

    stop_ids = {tokenizer.eot_id, tokenizer.eos_id}
    decoder = codecs.getincrementaldecoder("utf-8")("replace")
    async for tok in engine.generate_stream(prompt_ids, sampling,
                                            priority=priority,
                                            adapter=adapter,
                                            request_sink=request_sink,
                                            request_id=request_id):
        if state is not None:
            state["n_tokens"] = state.get("n_tokens", 0) + 1
        if tok in stop_ids:
            if state is not None:
                state["saw_stop"] = True
            continue
        piece = decoder.decode(tokenizer.id_to_bytes(tok))
        if piece:
            yield piece
    tail = decoder.decode(b"", final=True)
    if tail:
        yield tail


def _wire_supervisors(client, llm_cfg, fleets) -> None:
    """Attach + start one FleetSupervisor per AsyncFleet when
    ``llm.fleet.supervisor.enabled`` (chaos/supervisor.py): dead/wedged
    replicas are quarantined, their in-flight requests failed over
    through the router's retry path, the engine rebuilt online and
    rejoined with hysteresis. ``client.supervisors`` holds the running
    supervisors (daemon threads; ``/healthz`` reads their snapshots
    through each fleet's ``supervisor`` attach point)."""
    client.supervisors = []
    sup_cfg = getattr(getattr(llm_cfg, "fleet", None), "supervisor",
                      None)
    if sup_cfg is None or not getattr(sup_cfg, "enabled", False):
        return
    from runbookai_tpu.chaos import FleetSupervisor

    for fleet in fleets:
        client.supervisors.append(FleetSupervisor(
            fleet,
            poll_interval_s=sup_cfg.poll_interval_s,
            wedge_timeout_s=sup_cfg.wedge_timeout_s,
            rejoin_hysteresis_s=sup_cfg.rejoin_hysteresis_s,
            max_consecutive_rebuilds=sup_cfg.max_consecutive_rebuilds,
        ).start())


def _wire_tsdb(client, llm_cfg) -> None:
    """Attach + start the embedded time-series store (obs/tsdb.py) when
    ``llm.obs.tsdb.enabled``: a bounded ring over every exported
    ``runbook_*`` series, sampled from the live registry.
    ``GET /debug/query``, the ``/healthz`` ``history`` block and
    ``runbook query`` read it; the incident monitor (wired after this)
    derives its trend readings and bundle lookback from it. None when
    the obs layer or the store is disabled — zero ``runbook_tsdb_*``
    series and every surface on top reports itself absent."""
    from runbookai_tpu.obs.tsdb import MetricsTSDB

    store = MetricsTSDB.from_config(llm_cfg)
    if store is not None:
        client.tsdb = store.start()


def _wire_incidents(client, llm_cfg) -> None:
    """Attach + start the incident monitor (obs/incident.py) over every
    fleet the client serves through: it folds the exported signals (SLO
    burn, workload drift, replica health, supervisor states, router
    sheds/stale pulls, queue-wait percentiles) into an incident
    lifecycle and captures a content-hashed evidence bundle on every
    open (``llm.obs.incident_dir``). ``GET /debug/incidents``, the
    ``/healthz`` ``incidents`` block and ``runbook incident`` all read
    it; None when ``llm.obs`` (or ``incidents_enabled``) is off."""
    from runbookai_tpu.obs.incident import IncidentMonitor

    mm = client.multi_model
    fleets = ([g.fleet for g in mm.groups.values()] if mm is not None
              else [client.engine])
    monitor = IncidentMonitor.from_config(
        llm_cfg, fleets=fleets, cores=client.cores,
        slo_monitor=client.slo_monitor,
        workload_monitor=client.workload_monitor,
        tsdb=getattr(client, "tsdb", None))
    if monitor is not None:
        client.incident_monitor = monitor.start()


class JaxTpuClient(BaseLLMClient):
    def __init__(
        self,
        core: "EngineCore | list[EngineCore]",
        tokenizer,
        temperature: float = 0.0,
        top_p: float = 1.0,
        top_k: int = 0,
        max_new_tokens: int = 1024,
        guided_json: bool = True,
        chat_format: str = "llama3",
        fleet_cfg=None,
        slo_monitor=None,
        tenants=None,
        engine=None,
        workload_monitor=None,
    ):
        # ``core`` may be a data-parallel fleet (list of replicas, built by
        # engine/fleet.build_engine_fleet when EngineConfig.dp_replicas > 1):
        # the client then serves through an AsyncFleet with the same
        # generate/generate_stream surface, and ``self.core`` stays replica
        # 0 for surfaces that need the shared pieces (LoRA registry names,
        # tokenizer-adjacent config) — fleet-wide state goes through
        # ``self.engine.health_snapshot()``. ``fleet_cfg`` (a
        # fleet.FleetConfig) carries the router policy knobs.
        #
        # ``engine`` (prebuilt) overrides the construction below — the
        # multi-model path (llm.models) passes its MultiModelFleet here;
        # ``core``/``tokenizer``/``chat_format`` then describe the
        # DEFAULT group (what agent-side chat()/complete() serve against).
        cores = list(core) if isinstance(core, (list, tuple)) else [core]
        self.cores = cores
        self.core = cores[0]
        if engine is not None:
            self.engine = engine
        elif len(cores) > 1:
            from runbookai_tpu.engine.fleet import AsyncFleet

            self.engine = AsyncFleet(cores, fleet_cfg)
        else:
            self.engine = AsyncEngine(self.core)
        self.tokenizer = tokenizer
        self.temperature = temperature
        self.top_p = top_p
        self.top_k = top_k
        self.max_new_tokens = max_new_tokens
        self.guided_json = guided_json
        self.chat_format = chat_format
        # SLO monitor (utils/slo.py, built by from_config from llm.slo):
        # /healthz reads it for the live burn-ratio block; None when no
        # objective is configured (zero SLO surface).
        self.slo_monitor = slo_monitor
        # Tenant admission governor (sched/tenants.py, built by
        # from_config from llm.tenants): the OpenAI server gates every
        # chat/completions request through it BEFORE enqueue. None = no
        # tenant surface.
        self.tenants = tenants
        # Workload monitor (runbookai_tpu/obs, built by from_config from
        # llm.obs): live fingerprints + plan-drift + replica health.
        # /debug/workload, the /healthz workload block and the `runbook
        # workload` CLI all read it; None = zero workload surface.
        self.workload_monitor = workload_monitor
        # Incident monitor (obs/incident.py, wired by _wire_incidents in
        # from_config): detection + black-box capture. None = zero
        # incident surface (/debug/incidents reports itself disabled).
        self.incident_monitor = None
        # Embedded time-series store (obs/tsdb.py, wired by _wire_tsdb
        # in from_config): metric history + PromQL-lite queries. None =
        # zero history surface (/debug/query reports itself disabled,
        # /healthz has no history block, bundles no lookback).
        self.tsdb = None

    # --------------------------------------------------------- model groups

    @property
    def multi_model(self):
        """The :class:`~runbookai_tpu.fleet.multimodel.MultiModelFleet`
        when this client serves ``llm.models``, else ``None`` — the
        server's duck-typing seam for model-field routing."""
        from runbookai_tpu.fleet.multimodel import MultiModelFleet

        return (self.engine
                if isinstance(self.engine, MultiModelFleet) else None)

    def engine_for(self, model=None):
        """The engine a resolved model group serves through (the group's
        AsyncFleet under ``llm.models``; the one engine otherwise)."""
        mm = self.multi_model
        return mm.engine_for(model) if mm is not None else self.engine

    def tokenizer_for(self, model=None):
        """Per-group tokenizer — multi-model requests must encode with
        the tokenizer of the model they route to."""
        mm = self.multi_model
        return (mm.group(model).tokenizer if mm is not None
                else self.tokenizer)

    def chat_format_for(self, model=None) -> str:
        mm = self.multi_model
        return (mm.group(model).chat_format if mm is not None
                else self.chat_format)

    # ------------------------------------------------------------- factories

    @classmethod
    def from_config(cls, llm_cfg) -> "JaxTpuClient":
        """Build engine + client from an ``LLMConfig`` (utils/config.py).

        The engine-construction path itself lives in
        ``runbookai_tpu.fleet.build.build_group`` — ONE place for plan
        application, weight discovery (configured ``model_path`` first,
        else ``$RUNBOOK_WEIGHTS``), mesh planning and core construction,
        shared with the multi-model fleet so the two cannot drift.

        ``llm.plan`` makes a ``runbook tune`` serving-plan artifact a
        first-class config input: the plan's engine block supplies every
        knob the sweep decided, while keys the operator set EXPLICITLY in
        YAML keep winning (``autotune.plan.apply_plan_to_llm`` reads
        pydantic's ``model_fields_set`` for exactly that precedence), and
        plan keys with no YAML spelling (speculative, mixed_token_budget,
        …) land directly on the built EngineConfig.

        ``llm.models`` switches to the multi-model fleet
        (``runbookai_tpu/fleet``): one client whose ``engine`` is a
        :class:`~runbookai_tpu.fleet.multimodel.MultiModelFleet`; the
        agent-side ``chat``/``complete`` surface serves against the
        FIRST group (the default model), while the OpenAI server routes
        every request by its ``model`` field."""
        from runbookai_tpu.fleet.build import (
            build_group,
            build_multi_model_fleet,
            wire_feedback,
        )

        slo_monitor = None
        if getattr(llm_cfg, "slo", None) is not None:
            from runbookai_tpu.utils.slo import SLOMonitor

            # None when llm.slo sets no objective: an unconfigured run
            # must export zero runbook_slo_* series.
            slo_monitor = SLOMonitor.from_config(llm_cfg.slo)
        tenants = None
        if getattr(llm_cfg, "tenants", None) is not None:
            from runbookai_tpu.sched import TenantGovernor

            # None when llm.tenants is absent/disabled: zero tenant
            # surface, the server admits everything exactly as before.
            tenants = TenantGovernor.from_config(llm_cfg.tenants)
        def build_workload_monitor(cores=None, multi_model=None):
            # llm.obs (runbookai_tpu/obs): None when disabled — zero
            # workload surface, no runbook_workload_* series.
            from runbookai_tpu.obs import WorkloadMonitor

            return WorkloadMonitor.from_config(
                llm_cfg, cores=cores, multi_model=multi_model,
                slo_monitor=slo_monitor, tenants=tenants)

        if getattr(llm_cfg, "models", None):
            engine = build_multi_model_fleet(llm_cfg,
                                             slo_monitor=slo_monitor)
            default = engine.groups[engine.default]
            client = cls(
                engine.cores, default.tokenizer,
                temperature=llm_cfg.temperature, top_p=llm_cfg.top_p,
                top_k=llm_cfg.top_k,
                max_new_tokens=llm_cfg.max_new_tokens,
                guided_json=llm_cfg.guided_json,
                chat_format=default.chat_format,
                slo_monitor=slo_monitor, tenants=tenants, engine=engine,
                workload_monitor=build_workload_monitor(multi_model=engine))
            _wire_supervisors(client, llm_cfg,
                              [g.fleet for g in engine.groups.values()])
            _wire_tsdb(client, llm_cfg)
            _wire_incidents(client, llm_cfg)
            return client
        built = build_group(llm_cfg)
        wire_feedback(built.cores, built.llm_cfg, slo_monitor)
        client = cls(
            built.cores if len(built.cores) > 1 else built.cores[0],
            built.tokenizer,
            temperature=llm_cfg.temperature, top_p=llm_cfg.top_p,
            top_k=llm_cfg.top_k,
            max_new_tokens=llm_cfg.max_new_tokens,
            guided_json=llm_cfg.guided_json,
            chat_format=built.chat_format,
            fleet_cfg=built.fleet_cfg,
            slo_monitor=slo_monitor,
            tenants=tenants,
            workload_monitor=build_workload_monitor(cores=built.cores),
        )
        from runbookai_tpu.engine.fleet import AsyncFleet

        if isinstance(client.engine, AsyncFleet):
            _wire_supervisors(client, llm_cfg, [client.engine])
        _wire_tsdb(client, llm_cfg)
        _wire_incidents(client, llm_cfg)
        return client

    @classmethod
    def for_testing(cls, model_name: str = "llama3-test",
                    temperature: float = 0.0, max_new_tokens: int = 32,
                    max_seq_len: int = 256, schema_limits=None,
                    lora_registry=None, **engine_kw) -> "JaxTpuClient":
        """Tiny random-init client on the byte tokenizer (CPU tests)."""
        tokenizer = load_tokenizer(None)
        cfg, params = load_or_init(model_name, None, dtype=jnp.float32)
        ecfg_kw = dict(page_size=4, num_pages=256, max_batch_slots=4,
                       prefill_chunk=32, max_seq_len=max_seq_len,
                       kv_dtype=jnp.float32)
        ecfg_kw.update(engine_kw)  # tests may override any default
        ecfg = EngineConfig(**ecfg_kw)
        masker = JsonMaskProvider(tokenizer, schemas=orchestrator_schemas(),
                                  limits=schema_limits)
        if ecfg.dp_replicas > 1:
            from runbookai_tpu.engine.fleet import build_engine_fleet

            core = build_engine_fleet(
                cfg, params, tokenizer, ecfg,
                mask_fn=masker.mask, advance_fn=masker.advance,
                lora_registry=lora_registry)
        else:
            core = EngineCore(cfg, params, tokenizer, ecfg,
                              mask_fn=masker.mask, advance_fn=masker.advance,
                              lora_registry=lora_registry)
        return cls(core, tokenizer, temperature=temperature,
                   max_new_tokens=max_new_tokens,
                   chat_format=format_for_model(model_name, cfg.family))

    # ------------------------------------------------------------------- API

    def _sampling(self, guided: Optional[str] = None, max_new: Optional[int] = None) -> SamplingParams:
        return SamplingParams(
            temperature=self.temperature,
            top_p=self.top_p,
            top_k=self.top_k,
            max_new_tokens=max_new or self.max_new_tokens,
            stop_token_ids=(self.tokenizer.eot_id, self.tokenizer.eos_id),
            guided=guided,
        )

    async def chat(self, system_prompt, user_prompt, tools=None) -> LLMResponse:
        prompt = build_chat_prompt(system_prompt, user_prompt, tools,
                                   fmt=self.chat_format)
        ids = self.tokenizer.encode(prompt)
        out = await self.engine.generate(ids, self._sampling())
        content, tool_calls, thinking = parse_assistant_output(out.text)
        return LLMResponse(
            content=content,
            tool_calls=tool_calls,
            thinking=thinking,
            usage={
                "prompt_tokens": len(ids),
                "completion_tokens": out.decode_tokens,
                "ttft_ms": int(out.ttft_ms or 0),
            },
        )

    async def chat_stream(self, system_prompt, user_prompt, tools=None):
        """TRUE token streaming override of the BaseLLMClient fallback
        (which chunks a completed response). Yields the same event-dict
        protocol: ``{"type": "text", "delta"}`` per decoded piece, then
        parsed ``tool_call`` events, then ``{"type": "done", "response"}``.

        Divergence from the fallback, by design: text deltas are the RAW
        model output as sampled (tool-call/thinking markup included — it
        cannot be parsed out until the document completes), while
        ``done.response.content`` is the parsed content, exactly as
        :meth:`chat` returns it. Consumers that must render only parsed
        content should buffer until ``done``.

        Text decoding/stop handling is the shared :func:`stream_text`
        (also behind the OpenAI SSE endpoint).
        """
        prompt = build_chat_prompt(system_prompt, user_prompt, tools,
                                   fmt=self.chat_format)
        ids = self.tokenizer.encode(prompt)
        state: dict = {}
        parts: list[str] = []
        async for piece in stream_text(self.engine, self.tokenizer, ids,
                                       self._sampling(), state=state):
            parts.append(piece)
            yield {"type": "text", "delta": piece}
        content, tool_calls, thinking = parse_assistant_output("".join(parts))
        for call in tool_calls:
            yield {"type": "tool_call", "call": call}
        yield {"type": "done", "response": LLMResponse(
            content=content, tool_calls=tool_calls, thinking=thinking,
            usage={"prompt_tokens": len(ids),
                   "completion_tokens": state.get("n_tokens", 0)})}

    def _completion_request(self, prompt: str, guided: Optional[bool],
                            schema: Optional[str]):
        """(ids, sampling) for a completion — ONE place for the guided
        default / prompt build / grammar pick, so the buffered and
        streaming paths cannot drift (their text must stay identical)."""
        use_guided = self.guided_json if guided is None else guided
        ids = self.tokenizer.encode(
            build_completion_prompt(prompt, fmt=self.chat_format))
        grammar = (schema or "json") if use_guided else None
        return ids, self._sampling(guided=grammar)

    async def complete(self, prompt: str, guided: Optional[bool] = None,
                       schema: Optional[str] = None) -> str:
        """Plain completion; guided JSON masking on by default (config) since
        every orchestrator prompt expects a JSON document back. ``schema``
        names a compiled grammar (``"triage"``, ``"evaluation"``, … — see
        :func:`~runbookai_tpu.model.schema_guided.orchestrator_schemas`)
        that constrains the output to exactly that document shape."""
        ids, sampling = self._completion_request(prompt, guided, schema)
        out = await self.engine.generate(ids, sampling)
        return out.text

    async def complete_stream(self, prompt: str,
                              guided: Optional[bool] = None,
                              schema: Optional[str] = None):
        """Streaming twin of :meth:`complete`: yields text deltas as the
        engine samples (grammar fast-forwarded runs arrive as one burst).
        The orchestrator uses it to paint phase documents live under the
        hypothesis tree."""
        ids, sampling = self._completion_request(prompt, guided, schema)
        async for piece in stream_text(self.engine, self.tokenizer, ids,
                                       sampling):
            yield piece

    async def shutdown(self) -> None:
        tsdb = getattr(self, "tsdb", None)
        if tsdb is not None:
            tsdb.stop()
        await self.engine.stop()
