"""Vectorized token-mask construction for the generic JSON automaton.

The scalar path in :class:`~runbookai_tpu.model.guided.JsonMaskProvider`
copies the byte machine once per token and replays the token's bytes —
O(vocab x token_len) Python work per novel automaton state. Fine at the
262-token test vocab; Llama-3's 128,256-token vocab makes a first-miss
mask build cost hundreds of milliseconds. This module advances **all
tokens at once**, one byte position per sweep, over length-sorted token
arrays — a full-vocab mask is ~max_token_len small numpy sweeps instead
of ~vocab Python replays.

The automaton is factored into:

- a **finite packed state** per token: (stack-free substate) x
  (top-of-stack symbol class) x (depth class: empty / mid / at-max).
  There are ~37 substates (string content, each UTF-8 continuation
  window, escape/hex progress, the number DFA, literal progress, the
  six structural modes), giving a few hundred packed states.
- a **transition table** ``TABLE[state, byte] -> (action, payload)``
  built by probing the scalar :class:`JsonMachine` itself — one probe
  per (state, byte), cached per process — so the table *cannot drift*
  from the scalar semantics it accelerates. Bytes that neither push nor
  pop resolve entirely in the table (including the scalar machine's
  internal re-dispatches: a number terminated by ``,`` lands directly
  in the right AFTER-mode successor). Pushes and pops are the only
  runtime fixups: small subset updates against a per-token shadow
  stack.
- the **pushdown stack**: the machine's starting stack (shared by all
  tokens) shadowed by per-token absolute-depth arrays ``own``/``ownv``.
  A push writes the symbol at its absolute depth; a pop re-reads the
  symbol below from the token's shadow where written, else the shared
  stack. Stack height moves by ±1, so any return to depth *d* passes
  through a push at *d* — shadow entries are never stale.

Each sweep is then: one table gather, a masked state assignment, a dead
update, and subset push/pop fixups. Dead tokens are compacted away when
they outnumber the living, so restrictive states (e.g. expecting ``:``)
collapse the active set after the first byte.

Exactness is the contract: any divergence from the scalar machine would
steer sampling toward bytes the engine later rejects. The differential
test (``tests/test_guided_vectorized.py``) compares full masks against
the scalar prober across states drawn from real JSON prefixes.

No reference counterpart (the reference parses model output post-hoc:
``src/agent/llm-parser.ts:215``); this is serving-side machinery.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from runbookai_tpu.model.guided import JsonMachine

# Mode encodings — identical values to guided.py's module constants.
_VALUE, _STRING, _STR_ESC, _NUMBER, _LITERAL = 0, 1, 2, 3, 4
_AFTER, _OBJ_KEY, _OBJ_COLON, _OBJ_KEY_REQ, _ARR_FIRST = 5, 6, 7, 8, 9

# stack symbol classes: 0 empty/none, 1 = '{', 2 = '[', 3 = key marker
_SYM_CLASS = {0x7B: 1, 0x5B: 2, -1: 3}
_CLASS_SYM = {1: 0x7B, 2: 0x5B, 3: -1}

# depth classes
_D_EMPTY, _D_MID, _D_MAX = 0, 1, 2

# actions
_A_NONE, _A_DIE, _A_PUSH, _A_POP = 0, 1, 2, 3


def _substate_key(m: JsonMachine) -> tuple:
    """Canonical stack-free substate of a scalar machine (mode-relevant
    fields only; stale fields from earlier modes are normalized away)."""
    if m.mode == _STRING:
        if m.u8_need:
            return ("str_u8", m.u8_need, m.u8_lo, m.u8_hi)
        return ("str",)
    if m.mode == _STR_ESC:
        return ("esc", m.hex_rem)
    if m.mode == _NUMBER:
        return ("num", m.num_state)
    if m.mode == _LITERAL:
        return ("lit", bytes(m.literal), m.lit_pos)
    return ("mode", m.mode)


def _apply_substate(m: JsonMachine, key: tuple) -> None:
    """Materialize a substate key onto a scalar machine (inverse of
    :func:`_substate_key`)."""
    kind = key[0]
    if kind == "str":
        m.mode = _STRING
    elif kind == "str_u8":
        m.mode = _STRING
        m.u8_need, m.u8_lo, m.u8_hi = key[1], key[2], key[3]
    elif kind == "esc":
        m.mode = _STR_ESC
        m.hex_rem = key[1]
    elif kind == "num":
        m.mode = _NUMBER
        m.num_state = key[1]
    elif kind == "lit":
        m.mode = _LITERAL
        m.literal, m.lit_pos = key[1], key[2]
    else:
        m.mode = key[1]


def _enumerate_substates() -> list[tuple]:
    subs: list[tuple] = [("str",)]
    for need, lo, hi in ((1, 0x80, 0xBF), (2, 0xA0, 0xBF), (2, 0x80, 0x9F),
                         (2, 0x80, 0xBF), (3, 0x90, 0xBF), (3, 0x80, 0x8F),
                         (3, 0x80, 0xBF)):
        subs.append(("str_u8", need, lo, hi))
    for hx in (0, 1, 2, 3, 4):
        subs.append(("esc", hx))
    for ns in ("neg", "zero", "int", "frac0", "frac", "exp0", "exp1", "exp"):
        subs.append(("num", ns))
    for lit in (b"true", b"false", b"null"):
        for pos in range(1, len(lit)):
            subs.append(("lit", lit, pos))
    for mode in (_VALUE, _AFTER, _OBJ_KEY, _OBJ_COLON, _OBJ_KEY_REQ,
                 _ARR_FIRST):
        subs.append(("mode", mode))
    return subs


_PROBE_MAX_DEPTH = 8  # depth classes make the actual limit irrelevant


def _probe_machine(sub: tuple, top: int, depth: int) -> JsonMachine:
    m = JsonMachine(max_depth=_PROBE_MAX_DEPTH)
    if depth == _D_EMPTY:
        stack: list[int] = []
    else:
        n = 2 if depth == _D_MID else _PROBE_MAX_DEPTH
        # filler below the top never influences a single-byte transition's
        # substate (pops are repacked from runtime-gathered tops)
        stack = [0x5B] * (n - 1) + [_CLASS_SYM[top]]
    m.stack = stack
    _apply_substate(m, sub)
    return m


@lru_cache(maxsize=1)
def _build_tables():
    """(TABLE [n_packed*256] uint32, REPACK [n_sub,4,3] uint16, sub_index,
    packed index helpers). TABLE entry = action<<24 | sym<<16 | payload
    where payload is a packed state (NONE) or substate id (PUSH/POP)."""
    subs = _enumerate_substates()
    sub_id = {s: i for i, s in enumerate(subs)}
    n_sub = len(subs)

    # packed id = (sub * 4 + top) * 3 + depth; not all combos are
    # reachable (top=0 ⇔ depth=empty) but the space is tiny.
    def pack(si: int, top: int, depth: int) -> int:
        return (si * 4 + top) * 3 + depth

    n_packed = n_sub * 4 * 3
    table = np.zeros(n_packed * 256, dtype=np.uint32)
    repack = np.zeros((n_sub, 4, 3), dtype=np.uint16)
    for si in range(n_sub):
        for top in range(4):
            for depth in range(3):
                repack[si, top, depth] = pack(si, top, depth)

    for si, sub in enumerate(subs):
        for top in range(4):
            for depth in range(3):
                if (top == 0) != (depth == _D_EMPTY):
                    continue  # unreachable combo
                ps = pack(si, top, depth)
                for b in range(256):
                    m = _probe_machine(sub, top, depth)
                    before = len(m.stack)
                    try:
                        ok = m.advance(b)
                    except IndexError:
                        # combo unreachable in practice (e.g. OBJ_KEY with
                        # an empty stack): the scalar machine's invariants
                        # don't hold there — mark dead
                        ok = False
                    if not ok:
                        table[ps * 256 + b] = _A_DIE << 24
                        continue
                    after = len(m.stack)
                    nsub = sub_id.get(_substate_key(m))
                    if nsub is None:
                        raise AssertionError(
                            f"substate closure violated: {_substate_key(m)} "
                            f"from {sub} byte {b}")
                    if after == before:
                        code = (_A_NONE << 24) | (pack(nsub, top, depth) << 8)
                    elif after == before + 1:
                        sym = _SYM_CLASS[m.stack[-1]]
                        code = (_A_PUSH << 24) | (sym << 16) | nsub
                    else:
                        code = (_A_POP << 24) | nsub
                    table[ps * 256 + b] = code
    return table, repack, sub_id


def packed_state_of(machine: JsonMachine, sub_id: dict) -> int:
    si = sub_id[_substate_key(machine)]
    depth = len(machine.stack)
    top = _SYM_CLASS[machine.stack[-1]] if depth else 0
    dclass = (_D_EMPTY if depth == 0
              else _D_MAX if depth >= machine.max_depth else _D_MID)
    return (si * 4 + top) * 3 + dclass


class VectorJsonMasker:
    """Full-vocab admissibility masks for :class:`JsonMachine` states.

    Precomputes the vocab's token bytes as padded arrays sorted by length
    (descending), so each byte sweep touches only the prefix of tokens
    that still have bytes — total element work is ~sum(token lengths),
    not vocab x max_len.
    """

    def __init__(self, token_bytes: list[bytes]):
        self.vocab_size = len(token_bytes)
        lens = np.array([len(t) for t in token_bytes], dtype=np.int32)
        order = np.argsort(-lens, kind="stable")
        self.order = order.astype(np.int64)
        self.lens = lens[order]
        self.max_len = int(self.lens[0]) if self.vocab_size else 0
        buf = np.zeros((max(self.max_len, 1), self.vocab_size), dtype=np.uint32)
        for row, tid in enumerate(order):
            t = token_bytes[tid]
            if t:
                buf[: len(t), row] = np.frombuffer(t, dtype=np.uint8)
        self.tok = buf  # [max_len, vocab]: each sweep reads a contiguous row
        self.table, self.repack, self.sub_id = _build_tables()

    # ------------------------------------------------------------------ mask

    def mask(self, machine: JsonMachine) -> np.ndarray:
        """Boolean [vocab] array: token admissible from ``machine``'s state.

        Empty tokens are inadmissible (the provider also excludes special
        ids). The machine is not mutated.
        """
        n = self.vocab_size
        out = np.zeros(n, dtype=bool)
        if n == 0 or machine.dead:
            return out

        st = _State(self, machine)
        for p in range(self.max_len):
            if not st.begin_sweep(p):
                break
            st.sweep(p)
            st.maybe_compact(p)

        rows = st.rowid[st.alive & (st.lens > 0)]
        out[self.order[rows]] = True
        return out


class _State:
    """Compactable per-token packed-automaton state + the one-byte sweep."""

    def __init__(self, masker: VectorJsonMasker, machine: JsonMachine):
        n = masker.vocab_size
        self.table = masker.table
        self.repack = masker.repack
        self.tok = masker.tok
        self.lens = masker.lens
        self.rowid = np.arange(n, dtype=np.int64)

        ps0 = packed_state_of(machine, masker.sub_id)
        self.ps = np.full(n, ps0 << 8, dtype=np.uint32)  # pre-shifted
        self.alive = np.ones(n, dtype=bool)
        self._scratch = np.empty(n, dtype=np.uint32)
        self.compacted = False
        self._recount(masker.max_len)

        self.start_len = len(machine.stack)
        self.max_depth = machine.max_depth
        # start_top[d] = shared-stack symbol class at absolute depth d
        self.start_top = np.zeros(self.start_len + 1, dtype=np.int8)
        for d, s in enumerate(machine.stack):
            self.start_top[d + 1] = _SYM_CLASS[s]
        self.rel = np.zeros(n, dtype=np.int32)  # signed height delta
        # shadow stack: 2 bits per absolute depth (1-based), packed into
        # one uint64 per token — symbol 0 means "not written here", which
        # defers to the shared starting stack. JsonMachine's depth cap is
        # 32, which is exactly what 64 bits hold.
        if self.max_depth > 32:
            raise ValueError("packed shadow stack supports max_depth <= 32")
        self.own = np.zeros(n, dtype=np.uint64)

        self.na = 0

    # -------------------------------------------------------------- helpers

    def _recount(self, max_len: int) -> None:
        # na_at[p] = #tokens with len > p, one vectorized binary search
        self.na_at = np.searchsorted(
            -self.lens, -np.arange(1, max_len + 1), side="right")

    def begin_sweep(self, p: int) -> bool:
        self.na = int(self.na_at[p]) if p < len(self.na_at) else 0
        return self.na > 0 and bool(self.alive[: self.na].any())

    def _depth_class(self, d: np.ndarray) -> np.ndarray:
        return np.where(d <= 0, _D_EMPTY,
                        np.where(d >= self.max_depth, _D_MAX, _D_MID))

    def maybe_compact(self, p: int) -> None:
        """Drop dead rows once they dominate, so later sweeps run over
        survivors only. Boolean-mask compaction preserves the
        length-descending order, keeping the active-prefix rule."""
        na = self.na
        n_keep = int(np.count_nonzero(self.alive[:na])) + (len(self.lens) - na)
        if n_keep * 2 > len(self.lens):
            return
        keep = np.ones(len(self.lens), dtype=bool)
        keep[:na] = self.alive[:na]
        for name in ("ps", "rel", "alive", "lens", "own", "rowid"):
            setattr(self, name, getattr(self, name)[keep])
        self.compacted = True
        self._recount(self.tok.shape[0])

    # ---------------------------------------------------------------- sweep

    def sweep(self, p: int) -> None:
        """Advance the active prefix by one byte via the packed DFA."""
        na = self.na
        alive = self.alive[:na]
        ps = self.ps[:na]
        idx = self._scratch[:na]
        if self.compacted:
            # post-compaction rows are a sparse subset of the sorted
            # order: gather their byte column (na is small by now)
            np.add(ps, self.tok[p].take(self.rowid[:na]), out=idx)
        else:
            np.add(ps, self.tok[p, :na], out=idx)
        code = self.table.take(idx)
        act = code >> 24

        alive &= act != _A_DIE
        # NONE payloads are pre-shifted packed states; PUSH/POP payloads
        # are substate ids whose rows get overwritten by the fixups below,
        # and dead tokens' states are don't-cares (payloads stay in-range
        # for next sweep's gather) — so the assignment is unconditional.
        np.bitwise_and(code, 0xFFFFFF, out=ps)

        stacky = act >= _A_PUSH
        if not stacky.any():
            return
        stacky &= alive
        sidx = np.nonzero(stacky)[0]
        pushes = sidx[act[sidx] == _A_PUSH]
        pops = sidx[act[sidx] == _A_POP]
        if pushes.size:
            c = code[pushes]
            sym = ((c >> 16) & 0xFF).astype(np.uint64)
            sub = (c & 0xFFFF).astype(np.int64)
            rel = self.rel[pushes] + 1
            self.rel[pushes] = rel
            d_new = self.start_len + rel
            shift = ((d_new - 1) * 2).astype(np.uint64)
            own = self.own[pushes]
            own &= ~(np.uint64(3) << shift)
            own |= sym << shift
            self.own[pushes] = own
            self.ps[pushes] = self.repack[
                sub, sym.astype(np.int64), self._depth_class(d_new)
            ].astype(np.uint32) << 8

        # ---- pops: subset fixup, re-deriving the new top -------------- #
        if pops.size:
            sub = (code[pops] & 0xFFFF).astype(np.int64)
            rel = self.rel[pops] - 1
            self.rel[pops] = rel
            d = self.start_len + rel
            shift = (np.maximum(d - 1, 0) * 2).astype(np.uint64)
            own = ((self.own[pops] >> shift) & np.uint64(3)).astype(np.int64)
            shared = self.start_top[np.clip(d, 0, self.start_len)]
            top = np.where(own != 0, own,
                           np.where(d <= self.start_len, shared, 0))
            top = np.where(d > 0, top, 0).astype(np.int64)
            self.ps[pops] = self.repack[
                sub, top, self._depth_class(d)].astype(np.uint32) << 8
