"""Schema-constrained guided decoding: pydantic models → byte grammars.

SURVEY.md §7 ("the zod schemas in ``llm-parser.ts:21-210`` become the
grammars"): the generic JSON automaton in :mod:`runbookai_tpu.model.guided`
guarantees *well-formed* output, but an 8B model can still emit a
syntactically-valid, schema-invalid triage object. This module compiles each
orchestrator schema (:mod:`runbookai_tpu.agent.llm_parser`) into a byte-level
automaton that admits exactly the documents the pydantic model validates:

- objects emit **all** fields, in declaration order, with forced key bytes;
- ``Literal[...]`` fields become enum tries (``"high"|"medium"|"low"`` …);
- strings are strict-UTF-8 with valid JSON escapes and a length cap;
- numbers follow the full JSON number grammar (no ``01``, no dangling ``1e``);
- ``dict``/``Any`` fields fall back to the generic JSON value machine.

Fixed key order is a deliberate tightening (jsonformer-style): the model
never spends probability mass deciding which key comes next, and the parse
is deterministic. The tolerant parser downstream remains as a fallback for
unguided providers.

The machines duck-type :class:`~runbookai_tpu.model.guided.JsonMachine`
(``advance``/``advance_bytes``/``copy``/``signature``/``is_complete``/
``dead``) so :class:`~runbookai_tpu.model.guided.JsonMaskProvider` caches
their token masks identically.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import Any, Literal, Optional, get_args, get_origin

from pydantic import BaseModel

from runbookai_tpu.model.guided import JsonMachine, utf8_lead

_WS = b" \t\n\r"
_DIGITS = frozenset(b"0123456789")
_HEX = frozenset(b"0123456789abcdefABCDEF")
_ESC_SIMPLE = frozenset(b'"\\/bfnrt')

# advance() results
_CONT = 0
_DONE = 1  # frame finished, byte consumed
_REDO = 2  # frame finished BEFORE this byte; re-offer to parent
_DEAD = 3
# (PUSH, subnode): delegate this (unconsumed) byte to a child frame
_PUSH = 4


# --------------------------------------------------------------------------- #
# schema nodes                                                                #
# --------------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class SNode:
    uid: int  # unique within one compiled schema (stable across machines)


@dataclasses.dataclass(frozen=True)
class SObject(SNode):
    # ((b'"key"', subnode), ...) in emission order
    fields: tuple[tuple[bytes, SNode], ...]


@dataclasses.dataclass(frozen=True)
class SArray(SNode):
    item: SNode
    min_items: int = 0


@dataclasses.dataclass(frozen=True)
class SString(SNode):
    pass


@dataclasses.dataclass(frozen=True)
class SEnum(SNode):
    # full byte literals including quotes: (b'"high"', b'"medium"', ...)
    options: tuple[bytes, ...]


@dataclasses.dataclass(frozen=True)
class SNumber(SNode):
    pass


@dataclasses.dataclass(frozen=True)
class SBool(SNode):
    pass


@dataclasses.dataclass(frozen=True)
class SAny(SNode):
    require_object: bool = False  # True for dict-typed fields


class _Uid:
    def __init__(self):
        self.n = 0

    def __call__(self) -> int:
        self.n += 1
        return self.n


def compile_model(model: type[BaseModel]) -> SObject:
    """Pydantic model → schema tree with stable node uids."""
    return _compile_object(model, _Uid())


def _compile_object(model: type[BaseModel], uid: _Uid) -> SObject:
    fields = []
    for name, info in model.model_fields.items():
        node = _compile_annotation(info.annotation, uid)
        # Honor pydantic list-length constraints (Field(min_length=N)) so
        # e.g. "generate 3-5 hypotheses" can forbid an empty array at the
        # grammar level, not just in post-hoc validation.
        min_len = next((m.min_length for m in info.metadata
                        if hasattr(m, "min_length")), None)
        if min_len and isinstance(node, SArray):
            node = dataclasses.replace(node, min_items=min_len)
        fields.append((b'"' + name.encode() + b'"', node))
    return SObject(uid(), tuple(fields))


def _compile_annotation(ann: Any, uid: _Uid) -> SNode:
    origin = get_origin(ann)
    if origin is Literal:
        return SEnum(uid(), tuple(b'"' + str(a).encode() + b'"'
                                  for a in get_args(ann)))
    if ann is str:
        return SString(uid())
    if ann is bool:
        return SBool(uid())
    if ann in (int, float):
        return SNumber(uid())
    if origin is list:
        (item,) = get_args(ann) or (Any,)
        return SArray(uid(), _compile_annotation(item, uid))
    if origin is dict:
        return SAny(uid(), require_object=True)
    if isinstance(ann, type) and issubclass(ann, BaseModel):
        return _compile_object(ann, uid)
    # Any / Optional[...] / Union / unsupported → generic JSON value, and
    # numeric range constraints (ge/le) are NOT encoded in the grammar: a
    # document can be grammar-valid yet pydantic-invalid (e.g. a confidence
    # of 7.5). That is a deliberate degradation — the byte automaton stays
    # regular and the tolerant parser downstream (llm_parser) clamps or
    # defaults out-of-range fields. Covered by
    # test_schema_guided.test_grammar_admits_pydantic_invalid_numbers.
    return SAny(uid())


# --------------------------------------------------------------------------- #
# frames                                                                      #
# --------------------------------------------------------------------------- #


class _ObjectFrame:
    __slots__ = ("node", "phase", "idx", "kpos")
    # phases: 0 '{', 1 key literal, 2 ':', 3 value, 4 after value, 5 empty '}'

    def __init__(self, node: SObject):
        self.node = node
        self.phase = 0
        self.idx = 0
        self.kpos = 0

    def advance(self, b: int, lim):
        ph = self.phase
        if ph == 0:
            if b in _WS:
                return _CONT
            if b == 0x7B:  # '{'
                self.phase = 1 if self.node.fields else 5
                return _CONT
            return _DEAD
        if ph == 1:
            key = self.node.fields[self.idx][0]
            if self.kpos == 0 and b in _WS:
                return _CONT
            if self.kpos < len(key) and b == key[self.kpos]:
                self.kpos += 1
                if self.kpos == len(key):
                    self.phase = 2
                return _CONT
            return _DEAD
        if ph == 2:
            if b in _WS:
                return _CONT
            if b == 0x3A:  # ':'
                self.phase = 3
                return _CONT
            return _DEAD
        if ph == 3:
            if b in _WS:
                return _CONT
            return (_PUSH, self.node.fields[self.idx][1])
        if ph == 4:
            if b in _WS:
                return _CONT
            if self.idx < len(self.node.fields) - 1:
                if b == 0x2C:  # ','
                    self.idx += 1
                    self.kpos = 0
                    self.phase = 1
                    return _CONT
                return _DEAD
            if b == 0x7D:  # '}'
                return _DONE
            return _DEAD
        # ph == 5: empty object
        if b in _WS:
            return _CONT
        return _DONE if b == 0x7D else _DEAD

    def child_done(self):
        self.phase = 4

    def sig(self):
        return ("o", self.node.uid, self.phase, self.idx, self.kpos)

    def copy(self):
        f = _ObjectFrame.__new__(_ObjectFrame)
        f.node, f.phase, f.idx, f.kpos = self.node, self.phase, self.idx, self.kpos
        return f


class _ArrayFrame:
    __slots__ = ("node", "phase", "count")
    # phases: 0 '[', 1 first value or ']', 2 after value, 3 next value

    def __init__(self, node: SArray):
        self.node = node
        self.phase = 0
        self.count = 0

    def advance(self, b: int, lim):
        ph = self.phase
        if ph == 0:
            if b in _WS:
                return _CONT
            if b == 0x5B:  # '['
                self.phase = 1
                return _CONT
            return _DEAD
        if ph == 1:
            if b in _WS:
                return _CONT
            if b == 0x5D and self.node.min_items == 0:  # ']'
                return _DONE
            return (_PUSH, self.node.item)
        if ph == 2:
            if b in _WS:
                return _CONT
            if b == 0x2C and self.count < lim.max_array_items:  # ','
                self.phase = 3
                return _CONT
            if b == 0x5D and self.count >= self.node.min_items:
                return _DONE
            return _DEAD
        # ph == 3
        if b in _WS:
            return _CONT
        return (_PUSH, self.node.item)

    def child_done(self):
        self.count += 1
        self.phase = 2

    def sig(self):
        # Count matters to the mask only near the min bound and at the cap
        # (the cap flag is appended by SchemaMachine.signature, which owns
        # the limits); bucketing keeps the mask cache small.
        return ("a", self.node.uid, self.phase,
                min(self.count, self.node.min_items + 1))

    def copy(self):
        f = _ArrayFrame.__new__(_ArrayFrame)
        f.node, f.phase, f.count = self.node, self.phase, self.count
        return f


class _StringFrame:
    __slots__ = ("phase", "count", "need", "lo", "hi")
    # phases: 0 open quote, 1 content, 2 escape, 3-6 \uXXXX hex digits

    def __init__(self):
        self.phase = 0
        self.count = 0  # content bytes so far
        self.need = 0  # pending UTF-8 continuation bytes
        self.lo = 0x80
        self.hi = 0xBF

    def advance(self, b: int, lim):
        ph = self.phase
        maxlen = lim.max_str_len
        if ph == 0:
            if b in _WS:
                return _CONT
            if b == 0x22:
                self.phase = 1
                return _CONT
            return _DEAD
        if ph == 1:
            if self.need:
                if self.lo <= b <= self.hi:
                    self.need -= 1
                    self.lo, self.hi = 0x80, 0xBF
                    return _CONT
                return _DEAD
            if b == 0x22:  # closing quote
                return _DONE
            if self.count >= maxlen:
                return _DEAD  # only the close is admissible at the cap
            if b == 0x5C:  # backslash
                self.phase = 2
                return _CONT
            if b < 0x20:
                return _DEAD
            if b < 0x80:
                self.count += 1
                return _CONT
            # UTF-8 lead byte: whole character must fit under the cap.
            lead = utf8_lead(b)
            if lead is None:
                return _DEAD
            need, lo, hi = lead
            if self.count + need + 1 > maxlen:
                return _DEAD
            self.count += need + 1
            self.need, self.lo, self.hi = need, lo, hi
            return _CONT
        if ph == 2:
            if b in _ESC_SIMPLE:
                self.phase = 1
                self.count += 1
                return _CONT
            if b == 0x75:  # 'u'
                self.phase = 3
                return _CONT
            return _DEAD
        # hex digits of \uXXXX
        if b in _HEX:
            if ph == 6:
                self.phase = 1
                self.count += 1
            else:
                self.phase = ph + 1
            return _CONT
        return _DEAD

    def child_done(self):  # pragma: no cover - strings have no children
        raise AssertionError

    def sig(self, remaining: int = 0, bucket: int = 16):
        # The mask depends on head-room only up to the longest token's byte
        # length (`bucket`, sized by the provider from the real vocab);
        # bucketing keeps cache entries O(bucket), not one per character.
        return ("s", self.phase, self.need, self.lo, self.hi,
                min(remaining, bucket))

    def copy(self):
        f = _StringFrame.__new__(_StringFrame)
        f.phase, f.count = self.phase, self.count
        f.need, f.lo, f.hi = self.need, self.lo, self.hi
        return f


class _NumberFrame:
    __slots__ = ("state", "count")
    # states: start, neg (after '-'), zero (leading 0), int, frac0, frac,
    #         exp0 (after e/E), exp1 (after exp sign), exp

    def __init__(self):
        self.state = "start"
        self.count = 0  # consumed bytes; capped by limits.max_num_len

    def advance(self, b: int, lim):
        s = self.state
        # Numbers are otherwise an UNBOUNDED sink: digits stay admissible
        # forever, so a high-temperature model can burn its whole token
        # budget inside one numeric field (caught by the schema fuzz
        # sweep). Once the cap is reached in a state where the number can
        # legally END, further digits are rejected as _REDO — the byte is
        # re-offered to the parent, which admits only structural bytes, so
        # generation must move on. Non-terminating states (start/neg/
        # frac0/exp0/exp1) stay exempt: refusing digits there would kill
        # the machine.
        if (b in _DIGITS and self.count >= lim.max_num_len
                and s in ("zero", "int", "frac", "exp")):
            return _REDO
        if b not in _WS:
            self.count += 1
        if s == "start":
            if b in _WS:
                return _CONT
            if b == 0x2D:  # '-'
                self.state = "neg"
                return _CONT
            if b == 0x30:  # '0'
                self.state = "zero"
                return _CONT
            if b in _DIGITS:
                self.state = "int"
                return _CONT
            return _DEAD
        if s == "neg":
            if b == 0x30:
                self.state = "zero"
                return _CONT
            if b in _DIGITS:
                self.state = "int"
                return _CONT
            return _DEAD
        if s in ("zero", "int"):
            if b in _DIGITS:
                if s == "zero":
                    return _DEAD  # no leading zeros (json.loads rejects 01)
                return _CONT
            if b == 0x2E:  # '.'
                self.state = "frac0"
                return _CONT
            if b in (0x65, 0x45):  # e/E
                self.state = "exp0"
                return _CONT
            return _REDO  # number complete; byte belongs to the parent
        if s == "frac0":
            if b in _DIGITS:
                self.state = "frac"
                return _CONT
            return _DEAD
        if s == "frac":
            if b in _DIGITS:
                return _CONT
            if b in (0x65, 0x45):
                self.state = "exp0"
                return _CONT
            return _REDO
        if s == "exp0":
            if b in (0x2B, 0x2D):  # '+'/'-'
                self.state = "exp1"
                return _CONT
            if b in _DIGITS:
                self.state = "exp"
                return _CONT
            return _DEAD
        if s == "exp1":
            if b in _DIGITS:
                self.state = "exp"
                return _CONT
            return _DEAD
        # s == "exp"
        if b in _DIGITS:
            return _CONT
        return _REDO

    def child_done(self):  # pragma: no cover
        raise AssertionError

    def sig(self):
        return ("n", self.state)

    def copy(self):
        f = _NumberFrame.__new__(_NumberFrame)
        f.state = self.state
        f.count = self.count
        return f


class _LiteralSetFrame:
    """Match one of a set of byte literals (enums, true/false)."""

    __slots__ = ("options", "pos", "alive")

    def __init__(self, options: tuple[bytes, ...]):
        self.options = options
        self.pos = 0
        self.alive = (1 << len(options)) - 1  # bitmask of candidates

    def advance(self, b: int, lim):
        if self.pos == 0 and b in _WS:
            return _CONT
        nxt = 0
        done = False
        for i, opt in enumerate(self.options):
            if not (self.alive >> i) & 1:
                continue
            if self.pos < len(opt) and opt[self.pos] == b:
                if self.pos + 1 == len(opt):
                    done = True
                else:
                    nxt |= 1 << i
        if done:
            return _DONE
        if not nxt:
            return _DEAD
        self.alive = nxt
        self.pos += 1
        return _CONT

    def child_done(self):  # pragma: no cover
        raise AssertionError

    def sig(self):
        return ("l", self.options, self.pos, self.alive)

    def copy(self):
        f = _LiteralSetFrame.__new__(_LiteralSetFrame)
        f.options, f.pos, f.alive = self.options, self.pos, self.alive
        return f


_BOOL_OPTIONS = (b"true", b"false")


class _AnyFrame:
    """Free JSON value via a nested generic :class:`JsonMachine`."""

    __slots__ = ("m", "started", "require_object")

    def __init__(self, require_object: bool = False,
                 budget: int | None = None,
                 budget_bucket: int | None = None):
        self.m = JsonMachine(budget=budget, budget_bucket=budget_bucket)
        self.started = False
        self.require_object = require_object

    def advance(self, b: int, lim):
        if not self.started and b not in _WS:
            if self.require_object and b != 0x7B:
                return _DEAD
            self.started = True
        if self.m.advance(b):
            return _CONT
        # The nested machine died: if its value had completed, this byte is
        # the parent's terminator (',', '}', ']'); re-offer it.
        return _REDO if self.m.is_complete else _DEAD

    def child_done(self):  # pragma: no cover
        raise AssertionError

    def sig(self):
        return ("y", self.require_object, self.started, self.m.signature())

    def copy(self):
        f = _AnyFrame.__new__(_AnyFrame)
        f.m = self.m.copy()
        f.started = self.started
        f.require_object = self.require_object
        return f


def _make_frame(node: SNode, lim=None):
    if isinstance(node, SObject):
        return _ObjectFrame(node)
    if isinstance(node, SArray):
        return _ArrayFrame(node)
    if isinstance(node, SEnum):
        return _LiteralSetFrame(node.options)
    if isinstance(node, SBool):
        return _LiteralSetFrame(_BOOL_OPTIONS)
    if isinstance(node, SString):
        return _StringFrame()
    if isinstance(node, SNumber):
        return _NumberFrame()
    if isinstance(node, SAny):
        return _AnyFrame(node.require_object,
                         budget=lim.max_any_bytes if lim else None,
                         budget_bucket=lim.max_token_bytes if lim else None)
    raise TypeError(node)


# --------------------------------------------------------------------------- #
# machine                                                                     #
# --------------------------------------------------------------------------- #


@dataclasses.dataclass(frozen=True)
class SchemaLimits:
    """Generation-side bounds (not part of the JSON schema): they keep a
    random/underconfident model from rambling inside an unbounded string or
    array. Large enough to never bind on real orchestrator outputs."""

    max_str_len: int = 512  # content bytes per string
    max_array_items: int = 32
    max_num_len: int = 24  # bytes per number (wider than any float repr)
    # Free-form (dict/Any) fields embed a generic JsonMachine; this byte
    # budget flips it into wrap-up mode (close out, no new elements) so
    # one unbounded field can't absorb the whole token budget.
    max_any_bytes: int = 768
    # Longest token byte-expansion in the vocab — the mask-cache bucket for
    # string head-room. The provider overrides this from the real table; a
    # too-small value would cache a mask admitting a token that overflows
    # max_str_len mid-string and kills the machine.
    max_token_bytes: int = 16


class SchemaMachine:
    """Incremental validator for one compiled schema; JsonMachine-duck-typed."""

    def __init__(self, schema: SNode, name: str,
                 limits: Optional[SchemaLimits] = None):
        self.schema = schema
        self.name = name
        self.limits = limits or SchemaLimits()
        self.stack: list = [_make_frame(schema, self.limits)]
        self.complete = False
        self.dead = False

    @property
    def is_complete(self) -> bool:
        return self.complete

    def signature(self) -> tuple:
        sigs = []
        for fr in self.stack:
            if isinstance(fr, _StringFrame):
                sigs.append(fr.sig(self.limits.max_str_len - fr.count,
                                   self.limits.max_token_bytes))
            elif isinstance(fr, _ArrayFrame):
                s = fr.sig()
                sigs.append(s + (fr.count >= self.limits.max_array_items,))
            elif isinstance(fr, _NumberFrame):
                # Head-room bucketing (like strings): a mask cached at one
                # head-room must never be reused where a multi-digit token
                # could cross the cap mid-token.
                room = max(0, self.limits.max_num_len - fr.count)
                sigs.append(fr.sig()
                            + (min(room, self.limits.max_token_bytes),))
            else:
                sigs.append(fr.sig())
        return ("schema", self.name, self.complete, self.dead, tuple(sigs))

    def copy(self) -> "SchemaMachine":
        m = SchemaMachine.__new__(SchemaMachine)
        m.schema, m.name, m.limits = self.schema, self.name, self.limits
        m.stack = [fr.copy() for fr in self.stack]
        m.complete, m.dead = self.complete, self.dead
        return m

    @property
    def in_string(self) -> bool:
        """Inside string content (part of the mask-provider contract —
        see ``guided._in_string``): a string frame on top, or a nested
        generic machine that is itself inside a string."""
        if not self.stack:
            return False
        top = self.stack[-1]
        if isinstance(top, _StringFrame):
            return True
        if isinstance(top, _AnyFrame):
            return top.m.in_string
        return False

    def advance(self, byte: int) -> bool:
        if self.dead:
            return False
        if not self.stack:  # complete document: trailing whitespace only
            if byte in _WS:
                return True
            return self._die()
        while True:
            res = self.stack[-1].advance(byte, self.limits)
            if res == _CONT:
                return True
            if res == _DEAD:
                return self._die()
            if isinstance(res, tuple) and res[0] == _PUSH:
                self.stack.append(_make_frame(res[1], self.limits))
                continue  # re-offer the byte to the new child
            if res == _DONE:
                self.stack.pop()
                if self.stack:
                    self.stack[-1].child_done()
                    return True
                self.complete = True
                return True
            # _REDO: frame finished before this byte
            self.stack.pop()
            if self.stack:
                self.stack[-1].child_done()
                continue  # re-offer to parent
            self.complete = True
            if byte in _WS:
                return True
            return self._die()

    def _die(self) -> bool:
        self.dead = True
        return False

    def advance_bytes(self, data: bytes) -> bool:
        for b in data:
            if not self.advance(b):
                return False
        return True


# --------------------------------------------------------------------------- #
# orchestrator schema registry                                                #
# --------------------------------------------------------------------------- #


@lru_cache(maxsize=None)
def orchestrator_schemas() -> dict[str, SObject]:
    """The six structured-investigation grammars, compiled once. Names match
    the prompt templates in :mod:`runbookai_tpu.agent.llm_parser` and are the
    values accepted by ``SamplingParams.guided`` / ``complete(schema=...)``."""
    from runbookai_tpu.agent import llm_parser as lp

    return {
        "triage": compile_model(lp.TriageResult),
        "hypotheses": compile_model(lp.HypothesisGeneration),
        "evaluation": compile_model(lp.EvidenceEvaluation),
        "conclusion": compile_model(lp.Conclusion),
        "remediation": compile_model(lp.RemediationPlan),
        "log_analysis": compile_model(lp.LogAnalysis),
    }
