"""Built-in skills (reference ``src/skills/builtin/`` — 8 workflows)."""

from __future__ import annotations

from runbookai_tpu.skills.types import SkillDefinition

BUILTIN_SKILLS: list[dict] = [
    {
        "id": "investigate-incident",
        "name": "Investigate incident",
        "description": "Gather alarms, logs, and recent changes for an incident.",
        "tags": ["incident", "investigation"],
        "params": [
            {"name": "incident_id", "required": True,
             "description": "Incident id (PD-…)"},
            {"name": "log_group", "default": "",
             "description": "Primary log group to inspect"},
        ],
        "steps": [
            {"id": "incident", "action": "pagerduty_get_incident",
             "parameters": {"incident_id": "{{incident_id}}"},
             "on_error": "continue"},
            {"id": "alarms", "action": "cloudwatch_alarms",
             "parameters": {"state": "ALARM"}, "on_error": "continue"},
            {"id": "logs", "action": "cloudwatch_logs", "condition": "{{log_group}}",
             "parameters": {"log_group": "{{log_group}}",
                            "filter_pattern": "error"},
             "on_error": "continue"},
            {"id": "summary", "action": "prompt",
             "prompt": "Summarize the incident evidence for {{incident_id}}: "
                       "incident={{steps.incident}} alarms={{steps.alarms}} "
                       "logs={{steps.logs}}"},
        ],
    },
    {
        "id": "deploy-service",
        "name": "Deploy service",
        "description": "Deploy a service revision with verification.",
        "tags": ["deploy"],
        "risk": "high",
        "params": [
            {"name": "service", "required": True},
            {"name": "revision", "required": True},
            {"name": "dry_run", "default": "false"},
        ],
        "steps": [
            {"id": "pre", "action": "aws_query",
             "parameters": {"service": "ecs"}, "on_error": "abort"},
            {"id": "deploy", "action": "aws_mutate",
             "condition": "{{dry_run}} != true",
             "parameters": {"operation": "update_service",
                            "service": "{{service}}",
                            "params": {"revision": "{{revision}}"}},
             "requires_approval": True, "on_error": "abort"},
            {"id": "verify", "action": "aws_query",
             "parameters": {"service": "ecs"}, "on_error": "continue"},
        ],
    },
    {
        "id": "scale-service",
        "name": "Scale service",
        "description": "Change desired count for a service.",
        "tags": ["scale"],
        "risk": "high",
        "params": [
            {"name": "service", "required": True},
            {"name": "desired_count", "required": True, "type": "number"},
        ],
        "steps": [
            {"id": "scale", "action": "aws_mutate",
             "parameters": {"operation": "scale", "service": "{{service}}",
                            "params": {"desired_count": "{{desired_count}}"}},
             "requires_approval": True, "on_error": "abort"},
            {"id": "verify", "action": "aws_query",
             "parameters": {"service": "ecs"}, "on_error": "continue"},
        ],
    },
    {
        "id": "troubleshoot-service",
        "name": "Troubleshoot service",
        "description": "Standard triage for a degraded service.",
        "tags": ["troubleshoot"],
        "params": [
            {"name": "service", "required": True},
            {"name": "namespace", "default": "prod"},
        ],
        "steps": [
            {"id": "pods", "action": "kubernetes_query",
             "parameters": {"action": "pods", "namespace": "{{namespace}}"},
             "on_error": "continue"},
            {"id": "events", "action": "kubernetes_query",
             "parameters": {"action": "events"}, "on_error": "continue"},
            {"id": "alarms", "action": "cloudwatch_alarms",
             "parameters": {"state": "ALARM"}, "on_error": "continue"},
            {"id": "diagnose", "action": "prompt",
             "prompt": "Diagnose {{service}} from pods={{steps.pods}} "
                       "events={{steps.events}} alarms={{steps.alarms}}"},
        ],
    },
    {
        "id": "rollback-deployment",
        "name": "Rollback deployment",
        "description": "Roll a service back to its previous revision.",
        "tags": ["deploy", "rollback"],
        "risk": "high",
        "params": [{"name": "service", "required": True}],
        "steps": [
            {"id": "rollback", "action": "aws_mutate",
             "parameters": {"operation": "rollback", "service": "{{service}}"},
             "requires_approval": True, "on_error": "retry", "max_retries": 1},
            {"id": "verify", "action": "aws_query",
             "parameters": {"service": "ecs"}, "on_error": "continue"},
        ],
    },
    {
        "id": "cost-analysis",
        "name": "Cost analysis",
        "description": "Inventory resources by service for cost review.",
        "tags": ["cost"],
        "params": [{"name": "service", "default": "all"}],
        "steps": [
            {"id": "inventory", "action": "aws_query",
             "parameters": {"service": "{{service}}"}, "on_error": "continue"},
            {"id": "report", "action": "prompt",
             "prompt": "Review this inventory for cost hot-spots: {{steps.inventory}}"},
        ],
    },
    {
        "id": "investigate-cost-spike",
        "name": "Investigate cost spike",
        "description": "Correlate a cost spike with deploys and scaling events.",
        "tags": ["cost", "investigation"],
        "params": [{"name": "timeframe", "default": "7d"}],
        "steps": [
            {"id": "inventory", "action": "aws_query",
             "parameters": {"service": "all"}, "on_error": "continue"},
            {"id": "events", "action": "datadog",
             "parameters": {"action": "events"}, "on_error": "continue"},
            {"id": "analysis", "action": "prompt",
             "prompt": "Find likely causes of a cost spike in the last "
                       "{{timeframe}}: inventory={{steps.inventory}} "
                       "events={{steps.events}}"},
        ],
    },
    {
        "id": "security-audit",
        "name": "Security audit",
        "description": "Read-only security posture sweep.",
        "tags": ["security"],
        "params": [],
        "steps": [
            {"id": "iam", "action": "aws_query",
             "parameters": {"service": "iam"}, "on_error": "continue"},
            {"id": "network", "action": "aws_query",
             "parameters": {"service": "vpc"}, "on_error": "continue"},
            {"id": "report", "action": "prompt",
             "prompt": "Write a short security posture summary: "
                       "iam={{steps.iam}} network={{steps.network}}"},
        ],
    },
]


def builtin_definitions() -> list[SkillDefinition]:
    return [SkillDefinition.from_dict(raw) for raw in BUILTIN_SKILLS]
