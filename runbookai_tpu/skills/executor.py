"""Skill executor: param validation, templating, conditions, retries, approvals.

Parity target: reference ``src/skills/executor.ts`` — ``execute`` (:46): param
validation/defaults (:53-61), condition evaluation (:82), approval callback
(:96-102), step execution with retry policy (:112-134). Steps resolve
``{{param}}`` templates and call registry tools or the LLM.
"""

from __future__ import annotations

import re
from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.skills.types import (
    SkillDefinition,
    SkillResult,
    SkillStep,
    StepResult,
)

_TEMPLATE_RE = re.compile(r"\{\{\s*([\w.]+)\s*\}\}")


def render_template(value: Any, params: dict[str, Any]) -> Any:
    """Resolve {{param}} placeholders recursively. A string that is exactly
    one placeholder keeps the parameter's native type."""
    if isinstance(value, str):
        exact = _TEMPLATE_RE.fullmatch(value.strip())
        if exact:
            return params.get(exact.group(1), value)
        return _TEMPLATE_RE.sub(lambda m: str(params.get(m.group(1), "")), value)
    if isinstance(value, dict):
        return {k: render_template(v, params) for k, v in value.items()}
    if isinstance(value, list):
        return [render_template(v, params) for v in value]
    return value


def evaluate_condition(condition: Optional[str], params: dict[str, Any]) -> bool:
    """Tiny condition language: '{{a}} == x', '{{a}} != x', or a bare
    {{flag}} truthiness check. Malformed conditions default to True
    (graceful-limits philosophy)."""
    if not condition:
        return True
    rendered = render_template(condition, params)
    if isinstance(rendered, bool):
        return rendered
    text = str(rendered).strip()
    for op in ("==", "!="):
        if op in text:
            left, right = (part.strip().strip("'\"") for part in text.split(op, 1))
            truthy = {"true": "true", "false": "false"}
            left_n = truthy.get(left.lower(), left)
            right_n = truthy.get(right.lower(), right)
            return (left_n == right_n) if op == "==" else (left_n != right_n)
    return text.lower() not in ("", "false", "none", "0")


class SkillExecutor:
    def __init__(
        self,
        tools: dict[str, Any],  # name -> Tool
        llm=None,  # optional, for action == "prompt" steps
        approval_callback: Optional[Callable[[SkillStep, dict], Awaitable[bool]]] = None,
    ):
        self.tools = tools
        self.llm = llm
        self.approval_callback = approval_callback

    def validate_params(self, skill: SkillDefinition,
                        args: dict[str, Any]) -> dict[str, Any]:
        params: dict[str, Any] = {}
        missing = []
        for p in skill.params:
            if p.name in args:
                params[p.name] = args[p.name]
            elif p.default is not None:
                params[p.name] = p.default
            elif p.required:
                missing.append(p.name)
        if missing:
            raise ValueError(f"missing required params: {', '.join(missing)}")
        # pass through extras
        for k, v in args.items():
            params.setdefault(k, v)
        return params

    async def execute(self, skill: SkillDefinition,
                      args: Optional[dict[str, Any]] = None) -> SkillResult:
        try:
            params = self.validate_params(skill, args or {})
        except ValueError as exc:
            return SkillResult(skill_id=skill.id, status="failed", error=str(exc))

        result = SkillResult(skill_id=skill.id, status="completed")
        for step in skill.steps:
            if not evaluate_condition(step.condition, params):
                result.steps.append(StepResult(step_id=step.id, status="skipped"))
                continue
            if step.requires_approval and self.approval_callback is not None:
                approved = await self.approval_callback(step, params)
                if not approved:
                    result.steps.append(StepResult(step_id=step.id, status="rejected"))
                    if step.on_error == "abort":
                        result.status = "aborted"
                        return result
                    continue

            step_result = await self._run_step(step, params)
            result.steps.append(step_result)
            if step_result.status == "failed":
                if step.on_error == "abort":
                    result.status = "aborted"
                    result.error = step_result.error
                    return result
                # on_error == continue: carry on
            else:
                # expose step output to later templates as {{steps.<id>}}
                params[f"steps.{step.id}"] = step_result.result
        return result

    async def _run_step(self, step: SkillStep, params: dict[str, Any]) -> StepResult:
        attempts = 0
        max_attempts = 1 + (step.max_retries if step.on_error == "retry" else 0)
        last_error: Optional[str] = None
        while attempts < max_attempts:
            attempts += 1
            try:
                if step.action == "prompt":
                    if self.llm is None:
                        raise RuntimeError("prompt step but no LLM configured")
                    prompt = render_template(step.prompt or step.description, params)
                    output = await self.llm.complete(str(prompt))
                    return StepResult(step_id=step.id, status="executed",
                                      result=output, attempts=attempts)
                tool = self.tools.get(step.action)
                if tool is None:
                    raise KeyError(f"tool {step.action!r} not available")
                rendered = render_template(step.parameters, params)
                output = await tool.execute(rendered)
                return StepResult(step_id=step.id, status="executed",
                                  result=output, attempts=attempts)
            except Exception as exc:  # noqa: BLE001 — step errors become results
                last_error = f"{type(exc).__name__}: {exc}"
        return StepResult(step_id=step.id, status="failed", error=last_error,
                          attempts=attempts)
