"""Skill registry: builtin + user skills with lookup by id/tag/service.

Parity target: reference ``src/skills/registry.ts`` — builtin registration,
``loadUserSkills`` (:55 — YAML from ``.runbook/skills/``, user skills loaded
first so they can shadow builtins), singleton accessor (:152).
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import yaml

from runbookai_tpu.skills.builtin import builtin_definitions
from runbookai_tpu.skills.types import SkillDefinition
from runbookai_tpu.tools.registry import ToolRegistry, object_schema


class SkillRegistry:
    def __init__(self) -> None:
        self._skills: dict[str, SkillDefinition] = {}
        for skill in builtin_definitions():
            self._skills[skill.id] = skill

    def register(self, skill: SkillDefinition) -> None:
        self._skills[skill.id] = skill  # user skills may shadow builtins

    def load_user_skills(self, root: str | Path = ".runbook/skills") -> int:
        loaded = 0
        root = Path(root)
        if not root.is_dir():
            return 0
        for f in sorted([*root.glob("*.yaml"), *root.glob("*.yml")]):
            try:
                raw = yaml.safe_load(f.read_text())
            except yaml.YAMLError:
                continue
            if isinstance(raw, dict) and "id" in raw:
                self.register(SkillDefinition.from_dict(raw))
                loaded += 1
        return loaded

    def get(self, skill_id: str) -> Optional[SkillDefinition]:
        return self._skills.get(skill_id)

    def all(self) -> list[SkillDefinition]:
        return list(self._skills.values())

    def by_tag(self, tag: str) -> list[SkillDefinition]:
        return [s for s in self._skills.values() if tag in s.tags]

    def by_service(self, service: str) -> list[SkillDefinition]:
        return [s for s in self._skills.values() if service in s.services]


_singleton: Optional[SkillRegistry] = None


def skill_registry() -> SkillRegistry:
    global _singleton
    if _singleton is None:
        _singleton = SkillRegistry()
    return _singleton


def register_skill_tool(reg: ToolRegistry, registry: SkillRegistry,
                        executor) -> None:
    """The ``skill`` tool (reference registry.ts:1057): run a workflow."""

    async def run_skill(args):
        skill_id = str(args.get("skill_id", ""))
        skill = registry.get(skill_id)
        if skill is None:
            return {"error": f"unknown skill {skill_id!r}",
                    "available": [s.id for s in registry.all()]}
        result = await executor.execute(skill, args.get("params") or {})
        return {
            "skill_id": result.skill_id,
            "status": result.status,
            "error": result.error,
            "steps": [
                {"id": s.step_id, "status": s.status, "error": s.error,
                 "result": s.result if not isinstance(s.result, (dict, list))
                 else s.result}
                for s in result.steps
            ],
        }

    async def list_skills(args):
        return {"skills": [
            {"id": s.id, "name": s.name, "description": s.description,
             "tags": s.tags, "risk": s.risk,
             "params": [{"name": p.name, "required": p.required,
                         "default": p.default} for p in s.params]}
            for s in registry.all()
        ]}

    reg.define(
        "skill",
        "Execute a predefined operational workflow (skill) by id with params. "
        "Use list_skills to discover available skills.",
        object_schema({"skill_id": {"type": "string"},
                       "params": {"type": "object"}}, ["skill_id"]),
        run_skill, category="skills",
    )
    reg.define(
        "list_skills",
        "List available operational workflows (skills).",
        object_schema({}),
        list_skills, category="skills",
    )
