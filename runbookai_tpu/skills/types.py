"""Skill definitions: declarative multi-step workflows.

Parity target: reference ``src/skills/types.ts`` (:7-78) — ``SkillDefinition``
(params, steps), ``SkillStep`` (action = tool name or ``prompt``, templated
``parameters``, ``condition``, ``requiresApproval``, ``onError``
continue|abort|retry + maxRetries), execution context/result types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class SkillParam:
    name: str
    description: str = ""
    required: bool = False
    default: Any = None
    type: str = "string"


@dataclass
class SkillStep:
    id: str
    action: str  # tool name, or "prompt" for an LLM step
    description: str = ""
    parameters: dict[str, Any] = field(default_factory=dict)  # {{param}} templates
    condition: Optional[str] = None  # e.g. "{{dry_run}} != true"
    requires_approval: bool = False
    on_error: str = "abort"  # continue | abort | retry
    max_retries: int = 2
    prompt: Optional[str] = None  # for action == "prompt"


@dataclass
class SkillDefinition:
    id: str
    name: str
    description: str = ""
    tags: list[str] = field(default_factory=list)
    services: list[str] = field(default_factory=list)
    params: list[SkillParam] = field(default_factory=list)
    steps: list[SkillStep] = field(default_factory=list)
    risk: str = "low"

    @classmethod
    def from_dict(cls, raw: dict[str, Any]) -> "SkillDefinition":
        return cls(
            id=str(raw["id"]),
            name=str(raw.get("name", raw["id"])),
            description=str(raw.get("description", "")),
            tags=[str(t) for t in raw.get("tags", [])],
            services=[str(s) for s in raw.get("services", [])],
            risk=str(raw.get("risk", "low")),
            params=[
                SkillParam(
                    name=str(p["name"]), description=str(p.get("description", "")),
                    required=bool(p.get("required", False)),
                    default=p.get("default"), type=str(p.get("type", "string")),
                )
                for p in raw.get("params", [])
            ],
            steps=[
                SkillStep(
                    id=str(s.get("id", f"step-{i}")),
                    action=str(s["action"]),
                    description=str(s.get("description", "")),
                    parameters=dict(s.get("parameters", {})),
                    condition=s.get("condition"),
                    requires_approval=bool(s.get("requires_approval",
                                                 s.get("requiresApproval", False))),
                    on_error=str(s.get("on_error", s.get("onError", "abort"))),
                    max_retries=int(s.get("max_retries", s.get("maxRetries", 2))),
                    prompt=s.get("prompt"),
                )
                for i, s in enumerate(raw.get("steps", []))
            ],
        )


@dataclass
class StepResult:
    step_id: str
    status: str  # executed | skipped | failed | rejected
    result: Any = None
    error: Optional[str] = None
    attempts: int = 1


@dataclass
class SkillResult:
    skill_id: str
    status: str  # completed | aborted | failed
    steps: list[StepResult] = field(default_factory=list)
    error: Optional[str] = None
