"""Serving-plan autotuner (ROADMAP item 3).

Searches the coupled engine-knob space — ``(page_size, num_pages,
max_batch_slots, prefill_chunk, mixed_token_budget,
decode_steps_per_dispatch, kv_dtype, speculative, dp_replicas, tp)`` — in
the AIConfigurator / FlashInfer-Bench style (PAPERS.md): an analytical
cost model prunes the space, short measured runs refine the survivors, and
the result ships as a schema-versioned *plan artifact* that
``JaxTpuClient.from_config`` (``llm.plan``) and ``bench.py --plan``
consume directly.

- :mod:`~runbookai_tpu.autotune.cost_model` — residency (delegating to
  :mod:`runbookai_tpu.engine.memory_plan`, pinned equal by test) composed
  with an HLO-bytes roofline per dispatch kind.
- :mod:`~runbookai_tpu.autotune.search` — analytic prune (feasibility +
  dominated-point elimination) then measured refinement reusing bench.py's
  harness in-process.
- :mod:`~runbookai_tpu.autotune.plan` — the versioned JSON artifact with
  provenance (cost-model scores, measured figures, git sha).

CLI: ``runbook tune`` / ``runbook plan show|validate`` (docs/autotune.md).
"""

from runbookai_tpu.autotune.plan import (  # noqa: F401
    PLAN_SCHEMA_VERSION,
    PlanArtifact,
    load_plan,
    save_plan,
    validate_plan,
)
