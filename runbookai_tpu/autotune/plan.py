"""Versioned serving-plan artifacts: the autotuner's shippable output.

A plan is one JSON document per model×topology that pins every engine knob
the sweep decided, with provenance (cost-model scores, measured figures,
git sha) so a banked bench number can always be traced back to the exact
config that produced it — the FlashInfer-Bench artifact-driven loop
(PAPERS.md) applied to this engine's knob space.

Consumers:

- ``JaxTpuClient.from_config`` via the ``llm.plan`` config key — plan
  values become the defaults; keys the operator set explicitly in YAML
  still win (:func:`apply_plan_to_llm` reads pydantic's
  ``model_fields_set`` for exactly that precedence).
- ``bench.py --plan PATH`` — every bench arm can pin its exact config and
  records the plan id/hash in its artifact.
- ``runbook plan show|validate`` — operator inspection; tier-1 validates
  every checked-in ``plans/*.json`` against this schema.

Tamper evidence: ``plan_id`` ends in the content hash of
``(model, topology, engine)`` — editing a knob by hand without re-hashing
fails ``validate_plan``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Optional

PLAN_SCHEMA_VERSION = 1

# Engine-block keys a plan may carry, mapped 1:1 onto EngineConfig fields
# (kv_dtype travels as a string; EngineConfig.from_plan resolves it).
# Slot/page values are PER REPLICA when dp_replicas > 1 — the EngineConfig
# / llm.* contract, honored identically by the tuner's measured arms,
# bench --plan, and from_config.
ENGINE_PLAN_KEYS = frozenset({
    "page_size", "num_pages", "max_batch_slots", "prefill_chunk",
    "max_seq_len", "block_pages", "decode_steps_per_dispatch",
    "prefill_batch", "mixed_token_budget", "mixed_dispatch",
    "overlap_decode", "speculative", "kv_dtype", "attn_impl", "qmm_impl",
    "dp_replicas", "kv_spill_pages",
})

# kv_dtype spellings a plan may use ("auto" = follow the activation dtype,
# exactly llm.kv_cache_dtype's contract).
KV_DTYPE_NAMES = ("auto", "bf16", "fp8", "int8")

# attn_impl / qmm_impl spellings — LLMConfig's Literal set. The schema is
# the gate: apply_plan_to_llm injects via pydantic ``model_copy`` which
# skips Literal validation, and a bad value there would silently serve
# the XLA fallback path.
IMPL_NAMES = ("auto", "pallas", "xla")

# plan engine key -> LLMConfig field, for keys YAML can also spell. The
# rest (ENGINE_PLAN_KEYS - this - {"kv_dtype"}) apply straight onto
# EngineConfig (engine_only_overrides).
_PLAN_TO_LLM = {
    "page_size": "page_size",
    "num_pages": "num_pages",
    "max_batch_slots": "max_batch_slots",
    "prefill_chunk": "prefill_chunk",
    "max_seq_len": "max_seq_len",
    "decode_steps_per_dispatch": "decode_steps",
    "attn_impl": "attn_impl",
    "qmm_impl": "qmm_impl",
    "dp_replicas": "dp_replicas",
    "kv_spill_pages": "kv_spill_pages",
}


@dataclass
class PlanArtifact:
    """One serving plan: model × topology × engine knobs + provenance."""

    model: str
    topology: dict[str, Any]
    engine: dict[str, Any]
    workload: dict[str, Any] = field(default_factory=dict)
    provenance: dict[str, Any] = field(default_factory=dict)
    schema_version: int = PLAN_SCHEMA_VERSION
    plan_id: str = ""

    def __post_init__(self) -> None:
        if not self.plan_id:
            self.plan_id = default_plan_id(
                self.model, self.topology, self.engine)

    @property
    def content_hash(self) -> str:
        return plan_hash(self.model, self.topology, self.engine)

    def to_dict(self) -> dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "plan_id": self.plan_id,
            "model": self.model,
            "topology": self.topology,
            "engine": self.engine,
            "workload": self.workload,
            "provenance": self.provenance,
        }

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "PlanArtifact":
        problems = validate_plan(data)
        if problems:
            raise ValueError(
                "invalid plan artifact: " + "; ".join(problems))
        return cls(
            schema_version=data["schema_version"], plan_id=data["plan_id"],
            model=data["model"], topology=dict(data["topology"]),
            engine=dict(data["engine"]),
            workload=dict(data.get("workload") or {}),
            provenance=dict(data.get("provenance") or {}),
        )


def plan_hash(model: str, topology: dict, engine: dict) -> str:
    """Content hash over what the plan *decides* (not its provenance), so
    re-running a sweep that lands on the same config yields the same id."""
    canonical = json.dumps({"model": model, "topology": topology,
                            "engine": engine}, sort_keys=True)
    return hashlib.sha256(canonical.encode()).hexdigest()[:12]


def default_plan_id(model: str, topology: dict, engine: dict) -> str:
    tp = int(topology.get("tp", 1) or 1)
    dp = int(engine.get("dp_replicas", topology.get("dp_replicas", 1)) or 1)
    kind = str(topology.get("device_kind", "unknown")).replace(" ", "-")
    return (f"{model}.{kind}.tp{tp}dp{dp}."
            f"{plan_hash(model, topology, engine)}")


def validate_plan(data: Any) -> list[str]:
    """Human-readable schema problems (empty = valid).

    Unknown schema versions are REJECTED — a v2 plan must never be
    half-read by v1 code and silently serve the keys it understood.
    """
    problems: list[str] = []
    if not isinstance(data, dict):
        return ["plan is not a JSON object"]
    version = data.get("schema_version")
    if version != PLAN_SCHEMA_VERSION:
        return [f"unknown schema_version {version!r} "
                f"(this build reads version {PLAN_SCHEMA_VERSION})"]
    for key in ("plan_id", "model", "topology", "engine"):
        if key not in data:
            problems.append(f"missing required key {key!r}")
    if problems:
        return problems
    if not isinstance(data["model"], str) or not data["model"]:
        problems.append("model must be a non-empty string")
    if not isinstance(data["topology"], dict):
        problems.append("topology must be an object")
    engine = data["engine"]
    if not isinstance(engine, dict):
        problems.append("engine must be an object")
        return problems
    unknown = sorted(set(engine) - ENGINE_PLAN_KEYS)
    if unknown:
        problems.append(f"unknown engine keys: {', '.join(unknown)} "
                        f"(allowed: {', '.join(sorted(ENGINE_PLAN_KEYS))})")
    for key in ("page_size", "num_pages", "max_batch_slots",
                "prefill_chunk", "max_seq_len", "block_pages",
                "decode_steps_per_dispatch", "prefill_batch",
                "dp_replicas"):
        if key in engine and (not isinstance(engine[key], int)
                              or isinstance(engine[key], bool)
                              or engine[key] < 1):
            problems.append(f"engine.{key} must be a positive integer")
    if "mixed_token_budget" in engine and engine["mixed_token_budget"] \
            is not None and (not isinstance(engine["mixed_token_budget"],
                                            int)
                             or engine["mixed_token_budget"] < 1):
        problems.append("engine.mixed_token_budget must be a positive "
                        "integer or null")
    # v1-compatible optional keys (absent in pre-PR-8 plans — they still
    # validate; present means a host spill tier / disagg deployment).
    if "kv_spill_pages" in engine and (
            not isinstance(engine["kv_spill_pages"], int)
            or isinstance(engine["kv_spill_pages"], bool)
            or engine["kv_spill_pages"] < 0):
        problems.append("engine.kv_spill_pages must be a non-negative "
                        "integer (0 = spill tier disabled)")
    topo = data.get("topology")
    if isinstance(topo, dict) and "disagg_prefill_replicas" in topo:
        n_pf = topo["disagg_prefill_replicas"]
        dp = engine.get("dp_replicas", topo.get("dp_replicas", 1)) or 1
        if (not isinstance(n_pf, int) or isinstance(n_pf, bool)
                or n_pf < 0):
            problems.append("topology.disagg_prefill_replicas must be a "
                            "non-negative integer")
        elif n_pf and isinstance(dp, int) and n_pf >= dp:
            problems.append(
                f"topology.disagg_prefill_replicas={n_pf} leaves no "
                f"decode tier (dp_replicas={dp})")
    if "kv_dtype" in engine and engine["kv_dtype"] not in KV_DTYPE_NAMES:
        problems.append(f"engine.kv_dtype must be one of "
                        f"{'/'.join(KV_DTYPE_NAMES)}")
    for key in ("attn_impl", "qmm_impl"):
        if key in engine and engine[key] not in IMPL_NAMES:
            problems.append(f"engine.{key} must be one of "
                            f"{'/'.join(IMPL_NAMES)}")
    for key in ("speculative", "overlap_decode"):
        if key in engine and not isinstance(engine[key], bool):
            problems.append(f"engine.{key} must be a boolean")
    if "mixed_dispatch" in engine and engine["mixed_dispatch"] is not None \
            and not isinstance(engine["mixed_dispatch"], bool):
        problems.append("engine.mixed_dispatch must be a boolean or null")
    if isinstance(data.get("topology"), dict):
        expect = plan_hash(data["model"], data["topology"], engine)
        if not str(data["plan_id"]).endswith(expect):
            problems.append(
                f"plan_id does not end in the content hash {expect} — "
                f"the plan was edited without re-hashing (regenerate via "
                f"`runbook tune` or fix the id)")
    return problems


def load_plan(path: str | Path) -> PlanArtifact:
    """Read + validate a plan file; raises ValueError with the problems."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"could not read plan {path}: {e}") from e
    return PlanArtifact.from_dict(data)


def save_plan(plan: PlanArtifact, path: str | Path) -> Path:
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(plan.to_dict(), indent=2, sort_keys=False)
                 + "\n")
    return p


# ----------------------------------------------------------- consumption


def apply_plan_to_llm(llm_cfg, plan: PlanArtifact):
    """Plan values become the llm-config defaults; explicitly-set YAML
    keys keep winning (precedence read off pydantic ``model_fields_set``,
    so only the operator's own lines override the sweep's decision).

    Returns a COPY of ``llm_cfg``; the caller's object is never mutated.
    """
    explicit = set(llm_cfg.model_fields_set)
    updates: dict[str, Any] = {}
    for plan_key, llm_key in _PLAN_TO_LLM.items():
        if plan_key in plan.engine and llm_key not in explicit:
            updates[llm_key] = plan.engine[plan_key]
    if "kv_dtype" in plan.engine and "kv_cache_dtype" not in explicit:
        # 1:1 spelling — llm.kv_cache_dtype accepts the full plan set,
        # and engine.resolve_kv_dtype gives every consumer (llm.plan,
        # bench --plan, from_plan) the same pool for the same string
        # ("bf16" pins bfloat16 even on float32 activations; "auto"
        # follows them).
        updates["kv_cache_dtype"] = plan.engine["kv_dtype"]
    tp = int(plan.topology.get("tp", 1) or 1)
    if tp > 1 and "mesh" not in explicit:
        mesh_cls = type(llm_cfg.mesh)
        updates["mesh"] = mesh_cls(data=1, model=tp)
    return llm_cfg.model_copy(update=updates) if updates else \
        llm_cfg.model_copy()


def engine_only_overrides(plan: PlanArtifact) -> dict[str, Any]:
    """Plan engine keys that have NO LLMConfig spelling — they apply
    directly onto the built EngineConfig (from_config threads them through
    ``dataclasses.replace``). kv_dtype is excluded: it routes through
    ``llm.kv_cache_dtype`` so the activation-dtype default keeps working.
    """
    skip = set(_PLAN_TO_LLM) | {"kv_dtype"}
    return {k: v for k, v in plan.engine.items() if k not in skip}


def engine_config_dict(ecfg) -> dict[str, Any]:
    """JSON-safe dump of a resolved EngineConfig (bench artifacts, plan
    provenance): every dataclass field, kv_dtype as its dtype name."""
    import jax.numpy as jnp

    out: dict[str, Any] = {}
    for f in dataclasses.fields(ecfg):
        value = getattr(ecfg, f.name)
        if f.name == "kv_dtype":
            value = str(jnp.dtype(value).name)
        out[f.name] = value
    return out


def git_sha(repo_root: Optional[str | Path] = None) -> Optional[str]:
    """Best-effort provenance sha; None outside a git checkout."""
    import subprocess

    root = Path(repo_root) if repo_root else Path(__file__).parents[2]
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None
