"""Autotune search driver: analytic prune → measured refinement → plan.

AIConfigurator's two-stage loop (PAPERS.md) over this engine's knobs:

1. **Analytic prune** — score every candidate in the
   :class:`~runbookai_tpu.autotune.cost_model.SearchSpace` with the cost
   model, drop infeasible points (residency via memory_plan) and
   dominated points (worse on BOTH predicted throughput and TTFT), keep
   the top-K survivors. Pure arithmetic: thousands of points per second.

2. **Measured refinement** — run each survivor (plus the hand-picked
   baseline, so a shipped plan can never regress it) through a short
   in-process serving run reusing bench.py's harness: same warmup-then-
   reset protocol, same counters, same deterministic prompt stream. The
   best *measured* candidate becomes the plan.

The output is a :class:`~runbookai_tpu.autotune.plan.PlanArtifact` with
full provenance: cost-model scores, per-candidate measured figures, the
baseline figure it had to beat, and the git sha of the tree that ran the
sweep.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

from runbookai_tpu.autotune.cost_model import (
    Candidate,
    CostEstimate,
    CostModel,
    Hardware,
    SearchSpace,
    Workload,
    smoke_space,
)
from runbookai_tpu.autotune.plan import (
    PlanArtifact,
    engine_config_dict,
    git_sha,
    save_plan,
)


def _bench_module():
    """bench.py's harness helpers, importable both from a repo checkout
    (tests put the root on sys.path) and an installed package."""
    try:
        import bench  # repo root on sys.path (tests, source checkouts)

        return bench
    except ImportError:
        import importlib.util

        path = Path(__file__).resolve().parents[2] / "bench.py"
        spec = importlib.util.spec_from_file_location("bench", path)
        if spec is None or spec.loader is None:
            raise ImportError(f"bench.py not found at {path}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod


# ------------------------------------------------------------- analytic


def pareto_front(estimates: list[CostEstimate]) -> list[CostEstimate]:
    """Dominated-point elimination on (predicted throughput, TTFT): a
    point loses only when another is at least as good on both axes and
    strictly better on one — the two axes a serving operator actually
    trades."""
    front: list[CostEstimate] = []
    for e in estimates:
        dominated = any(
            o.decode_tok_s >= e.decode_tok_s and o.ttft_ms <= e.ttft_ms
            and (o.decode_tok_s > e.decode_tok_s or o.ttft_ms < e.ttft_ms)
            for o in estimates)
        if not dominated:
            front.append(e)
    return front


def analytic_prune(estimates: list[CostEstimate],
                   top_k: int = 4) -> list[CostEstimate]:
    """Feasibility filter, Pareto elimination, then throughput rank. When
    the front is thinner than ``top_k`` the next-best dominated points
    fill the budget — measurement, not the model, gets the last word."""
    feasible = [e for e in estimates if e.feasible]
    front = pareto_front(feasible)
    ranked = sorted(front, key=lambda e: e.decode_tok_s, reverse=True)
    if len(ranked) < top_k:
        rest = sorted((e for e in feasible if e not in front),
                      key=lambda e: e.decode_tok_s, reverse=True)
        ranked += rest[:top_k - len(ranked)]
    return ranked[:top_k]


# ------------------------------------------------------------- measured


def measure_candidate(model_cfg, params, tokenizer, cand: Candidate,
                      workload: Workload, *, n_requests: int = 4,
                      new_tokens: int = 16, seed: int = 0,
                      attn_impl: str = "xla",
                      qmm_impl: str = "xla") -> dict[str, Any]:
    """One short measured serving run of ``cand`` — bench.py's protocol
    in-process: deterministic prompts, warmup to compile every program
    shape, counter reset (``bench.reset_warmup_metrics``), then the
    measured window. Returns the figures a plan's provenance records."""
    import numpy as np

    from runbookai_tpu.engine.engine import EngineConfig, EngineCore
    from runbookai_tpu.engine.request import EngineRequest, SamplingParams

    bench = _bench_module()
    ecfg = EngineConfig.from_plan(
        cand.engine_plan_block(),
        default_kv_dtype=params["embed"].dtype,
        attn_impl=attn_impl, qmm_impl=qmm_impl)
    prompt_len = min(workload.prompt_len, max(8, cand.max_seq_len
                                              - new_tokens - 1))
    rng = np.random.default_rng(seed)

    def make_req():
        return EngineRequest(
            prompt_ids=rng.integers(0, 256, size=prompt_len).tolist(),
            sampling=SamplingParams(temperature=0.0,
                                    max_new_tokens=new_tokens,
                                    stop_token_ids=()))

    if cand.dp_replicas > 1:
        return _measure_fleet(model_cfg, params, tokenizer, ecfg,
                              make_req, bench, n_requests=n_requests)

    core = EngineCore(model_cfg, params, tokenizer, ecfg)
    for _ in range(min(ecfg.max_batch_slots, n_requests)):
        core.submit(make_req())
    core.run_until_idle()
    bench.reset_warmup_metrics(core)

    reqs = [make_req() for _ in range(n_requests)]
    t0 = time.perf_counter()
    for r in reqs:
        core.submit(r)
    core.run_until_idle()
    wall = time.perf_counter() - t0
    m = core.metrics
    ttfts = sorted(r.ttft_ms for r in reqs if r.ttft_ms is not None)
    total = m["decode_tokens"] + m["prefill_tokens"]
    return {
        "decode_tok_s": round(
            m["decode_tokens"] / max(m["decode_time_s"]
                                     + m.get("mixed_time_s", 0.0), 1e-9),
            2),
        "total_tok_s": round(total / max(wall, 1e-9), 2),
        "p50_ttft_ms": (round(ttfts[len(ttfts) // 2], 1)
                        if ttfts else None),
        "wall_s": round(wall, 3),
        "requests": n_requests,
        "dispatches": {
            "prefill_steps": m.get("prefill_steps", 0),
            "decode_dispatches": m.get("decode_dispatches", 0),
            "mixed_steps": m.get("mixed_steps", 0),
        },
        "preemptions": m.get("preemptions", 0),
        "engine_config": engine_config_dict(core.ecfg),
    }


def _measure_fleet(model_cfg, params, tokenizer, ecfg, make_req, bench,
                   *, n_requests: int) -> dict[str, Any]:
    """The dp>1 measured arm: a candidate's slots/pages are PER REPLICA
    (the same contract as ``llm.*`` config and ``EngineConfig`` — so a
    plan applied via ``llm.plan`` serves exactly the budget the sweep
    measured), and the request set serves through an AsyncFleet."""
    import asyncio

    from runbookai_tpu.engine.fleet import AsyncFleet, build_engine_fleet

    per_replica = ecfg
    cores = build_engine_fleet(model_cfg, params, tokenizer, per_replica)
    # EVERY replica warms (compiles its programs) regardless of
    # n_requests — an unwarmed replica would pay multi-second compiles
    # inside the measured window and systematically understate high-dp
    # candidates.
    warm_per_core = max(1, min(per_replica.max_batch_slots, n_requests))
    for core in cores:
        for _ in range(warm_per_core):
            core.submit(make_req())
    for core in cores:
        core.run_until_idle()
        bench.reset_warmup_metrics(core)

    fleet = AsyncFleet(cores)
    reqs = [make_req() for _ in range(n_requests)]

    async def _run():
        outs = await asyncio.gather(*[
            fleet.generate(r.prompt_ids, r.sampling) for r in reqs])
        await fleet.stop()
        return outs

    t0 = time.perf_counter()
    outs = asyncio.run(_run())
    wall = time.perf_counter() - t0
    decode = sum(c.metrics["decode_tokens"] for c in cores)
    decode_t = max(c.metrics["decode_time_s"]
                   + c.metrics.get("mixed_time_s", 0.0) for c in cores)
    ttfts = sorted(o.ttft_ms for o in outs if o.ttft_ms is not None)
    total = decode + sum(c.metrics["prefill_tokens"] for c in cores)
    return {
        "decode_tok_s": round(decode / max(decode_t, 1e-9), 2),
        "total_tok_s": round(total / max(wall, 1e-9), 2),
        "p50_ttft_ms": (round(ttfts[len(ttfts) // 2], 1)
                        if ttfts else None),
        "wall_s": round(wall, 3),
        "requests": n_requests,
        "dispatches": {
            "prefill_steps": sum(c.metrics.get("prefill_steps", 0)
                                 for c in cores),
            "decode_dispatches": sum(c.metrics.get("decode_dispatches", 0)
                                     for c in cores),
            "mixed_steps": sum(c.metrics.get("mixed_steps", 0)
                               for c in cores),
        },
        "preemptions": sum(c.metrics.get("preemptions", 0)
                           for c in cores),
        "engine_config": engine_config_dict(per_replica),
    }


# ------------------------------------------------------------------ tune


@dataclass
class TuneResult:
    """Everything a sweep produced (the plan is the shippable part)."""

    plan: PlanArtifact
    estimates: list[CostEstimate] = field(default_factory=list)
    survivors: list[CostEstimate] = field(default_factory=list)
    measured: list[dict[str, Any]] = field(default_factory=list)
    baseline_measured: Optional[dict[str, Any]] = None


def tune(model_name: str, workload: Workload, hardware: Hardware,
         space: Optional[SearchSpace] = None, *,
         weights: str = "bf16", top_k: int = 3, measure: bool = True,
         baseline: Optional[Candidate] = None, n_requests: int = 4,
         new_tokens: int = 16, budget_s: float = 300.0,
         out: Optional[str | Path] = None,
         params=None, tokenizer=None,
         log: Callable[[str], None] = lambda s: None) -> TuneResult:
    """Run the full sweep and return the plan (optionally saved to
    ``out``).

    The hand-picked default (``baseline``, EngineConfig defaults when
    omitted) is ALWAYS measured alongside the survivors and competes for
    the plan — a tune run therefore cannot ship a regression over the
    config it replaces. ``budget_s`` bounds the measured phase: once
    exceeded, remaining survivors keep their analytic score only.
    """
    from runbookai_tpu.models.llama import CONFIGS

    model_cfg = CONFIGS[model_name]
    space = space or smoke_space()
    cm = CostModel(model_cfg, hardware, weights=weights)
    t0 = time.monotonic()

    candidates = space.candidates()
    estimates = cm.score_many(candidates, workload)
    survivors = analytic_prune(estimates, top_k=top_k)
    n_feasible = sum(e.feasible for e in estimates)
    log(f"scored {len(estimates)} candidates: {n_feasible} feasible, "
        f"{len(survivors)} kept for refinement")

    baseline = baseline or Candidate()
    base_est = cm.score(baseline, workload)
    arms: list[CostEstimate] = [base_est] + [
        e for e in survivors if e.candidate != baseline]

    def measurable(est: CostEstimate) -> bool:
        # The in-process harness serves a single unsharded engine (or a
        # CPU fleet): an infeasible baseline must not crash the sweep on
        # allocation, and tp>1 arms would measure a deployment the plan
        # does not describe — both keep their analytic scores only (the
        # measured tp sweep needs the sharded harness; hardware-window
        # work, see docs/autotune.md).
        if not est.feasible:
            return False
        return est.candidate.tp <= 1

    measured: list[dict[str, Any]] = []
    if measure:
        import jax

        # The measured arms must serve the WIDTH and kernel paths the
        # plan will actually deploy: int8 sweeps measure quantized trees
        # (a random float32 8B would be 4x the bytes the cost model
        # ranked — and would not even fit the chip), and on-accelerator
        # runs use the Pallas paths exactly like from_config resolves.
        on_accel = jax.default_backend() in ("tpu", "axon")
        attn_impl = "pallas" if on_accel else "xla"
        qmm_impl = "pallas" if (on_accel and weights == "int8") else "xla"
        if params is None or tokenizer is None:
            import jax.numpy as jnp

            from runbookai_tpu.models.llama import (
                init_params,
                init_params_quantized,
            )
            from runbookai_tpu.utils.tokens import ByteTokenizer

            dtype = jnp.bfloat16 if on_accel else jnp.float32
            if weights == "int8":
                params = init_params_quantized(
                    jax.random.PRNGKey(0), model_cfg, dtype=dtype)
            else:
                params = init_params(jax.random.PRNGKey(0), model_cfg,
                                     dtype=dtype)
            tokenizer = ByteTokenizer()
        for i, est in enumerate(arms):
            if not measurable(est):
                log(f"arm {i} ({'baseline' if i == 0 else 'survivor'}) "
                    f"not measurable in-process "
                    f"({'infeasible: ' + est.reason if not est.feasible else f'tp={est.candidate.tp}'})"
                    f" — keeps its analytic score")
                continue
            if i > 0 and time.monotonic() - t0 > budget_s:
                log(f"measurement budget ({budget_s:.0f}s) exhausted — "
                    f"{len(arms) - i} survivor(s) keep analytic scores "
                    f"only")
                break
            figs = measure_candidate(model_cfg, params, tokenizer,
                                     est.candidate, workload,
                                     n_requests=n_requests,
                                     new_tokens=new_tokens,
                                     attn_impl=attn_impl,
                                     qmm_impl=qmm_impl)
            figs["candidate"] = est.candidate.engine_plan_block()
            figs["predicted"] = est.to_dict()
            figs["is_baseline"] = i == 0
            figs["arm_index"] = i
            measured.append(figs)
            log(f"measured {'baseline ' if i == 0 else ''}candidate "
                f"{i}/{len(arms) - 1}: "
                f"{figs['decode_tok_s']} decode tok/s")

    if measured:
        best = max(measured, key=lambda f: f["decode_tok_s"])
        winner_est = arms[best["arm_index"]]
        winner = winner_est.candidate
        # The baseline may itself have been skipped as unmeasurable
        # (infeasible on this hardware) — measured[0] is then a survivor.
        baseline_measured = next(
            (f for f in measured if f["is_baseline"]), None)
    else:
        # Analytic-only: the baseline still competes on predicted score —
        # the no-regression contract holds with or without measurement.
        best, baseline_measured = None, None
        winner_est = max(arms, key=lambda e: e.decode_tok_s)
        winner = winner_est.candidate
    if not winner_est.feasible:
        # Every point (including the baseline) failed the memory plan —
        # emitting this artifact would ship a config that OOMs at engine
        # construction. Refuse instead of writing a plan that validates.
        raise ValueError(
            f"no feasible candidate in the sweep ({len(estimates)} "
            f"scored): the best point is infeasible — "
            f"{winner_est.reason or 'see cost-model feasibility checks'}")

    import jax

    topology = {
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "chips": len(jax.devices()),
        "tp": winner.tp,
        "dp_replicas": winner.dp_replicas,
        "hbm_bytes_per_chip": hardware.hbm_bytes,
        # Fleet-shape extras (disagg tier split) — empty for symmetric
        # fleets so pre-PR-8 plan hashes are reproducible.
        **winner.topology_extras(),
    }
    provenance: dict[str, Any] = {
        "tool": "runbook tune",
        "created_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "git_sha": git_sha(),
        "hardware_model": hardware.to_dict(),
        "weights": weights,
        "cost_model": {
            "winner": winner_est.to_dict(),
            "candidates_scored": len(estimates),
            "candidates_feasible": n_feasible,
            "survivors_refined": len(measured),
        },
    }
    if best is not None:
        provenance["measured"] = {
            k: best[k] for k in ("decode_tok_s", "total_tok_s",
                                 "p50_ttft_ms", "dispatches", "wall_s")}
        if baseline_measured is not None:
            provenance["measured"]["baseline_decode_tok_s"] = \
                baseline_measured["decode_tok_s"]
        provenance["measured"]["all_arms"] = [
            {"candidate": f["candidate"],
             "decode_tok_s": f["decode_tok_s"],
             "is_baseline": f["is_baseline"]} for f in measured]
    plan = PlanArtifact(model=model_name, topology=topology,
                        engine=winner.engine_plan_block(),
                        workload=workload.to_dict(),
                        provenance=provenance)
    if out is not None:
        save_plan(plan, out)
        log(f"wrote plan {plan.plan_id} -> {out}")
    return TuneResult(plan=plan, estimates=estimates,
                      survivors=survivors, measured=measured,
                      baseline_measured=baseline_measured)
