"""Analytical serving cost model: residency × HLO-bytes roofline.

Scores a candidate knob tuple against a workload descriptor WITHOUT
touching a device, in the AIConfigurator style (PAPERS.md): first predict
whether the config *fits* (delegating every residency number to
:func:`runbookai_tpu.engine.memory_plan.plan_serving` — the arithmetic
already cross-checked against live allocations to 0.35% by
``tests/test_hlo_bytes.py``), then predict how fast it *runs* from the
byte/flop movement of each dispatch kind:

- **decode**: HBM-bandwidth-bound — per step the program reads every
  weight matrix once at stored width plus the live KV pages (the
  ``hlo_bytes.decode_accounting`` contract), so batching is ~free until
  KV reads or compute catch up;
- **prefill**: MXU-bound — ``2 · matmul_params`` FLOPs per prompt token,
  dispatched per ``prefill_chunk`` with one host sync each;
- **mixed**: the PR-4 unified dispatch folds a prefill chunk into the
  decode step — one host sync where the split path pays two.

The model's absolute numbers are calibration-grade, not gospel — that is
why :mod:`~runbookai_tpu.autotune.search` refines the analytic top-K with
short measured runs. Its *relative* ordering is what prunes the space.

Parity contracts (pinned in tests/test_autotune.py): ``residency()``
returns exactly ``plan_serving``'s ServingPlan, and
``decode_dispatch_bytes()`` matches the compiled decode program's
resident argument bytes within the memory-plan tolerance.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Iterable, Optional

from runbookai_tpu.engine.memory_plan import GiB, ServingPlan, plan_serving

# kv_dtype name -> (bytes per value, extra scale bytes per (token, kv head))
# — the byte widths engine.resolve_kv_dtype's dtypes allocate ("bf16" pins
# a 2-byte bfloat16 pool; "auto" follows the activation dtype, which the
# model assumes is bf16 — the hardware deployments it targets; int8 adds
# f32 absmax rows).
KV_DTYPE_BYTES: dict[str, tuple[int, int]] = {
    "auto": (2, 0), "bf16": (2, 0), "fp8": (1, 0), "int8": (1, 4),
}


@dataclass(frozen=True)
class Workload:
    """What the traffic looks like — the tune target, not a knob."""

    prompt_len: int = 512
    output_len: int = 128
    concurrency: int = 8
    # Fraction of requests that are grammar-guided (forced-sync: no
    # overlap, single-token dispatches — agent tool-call traffic).
    guided_share: float = 0.0
    # Expected extra accepted tokens per decode dispatch from speculation
    # (0 = repetition-free traffic; agent workloads bank 0.3-0.8).
    spec_hit_rate: float = 0.0

    @property
    def context_len(self) -> int:
        return self.prompt_len + self.output_len

    def to_dict(self) -> dict[str, Any]:
        return {"prompt_len": self.prompt_len,
                "output_len": self.output_len,
                "concurrency": self.concurrency,
                "guided_share": self.guided_share,
                "spec_hit_rate": self.spec_hit_rate}

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Workload":
        """The inverse of :meth:`to_dict` — what ``runbook tune
        --workload`` reads, so a live descriptor emitted by ``runbook
        workload --emit-descriptor`` (runbookai_tpu/obs) round-trips into
        a sweep unchanged. Unknown keys are REJECTED: a typo'd or
        stale-schema descriptor must fail loudly, not tune against a
        half-read workload."""
        if not isinstance(data, dict):
            raise ValueError(
                f"workload descriptor must be a JSON object, got "
                f"{type(data).__name__}")
        known = {"prompt_len", "output_len", "concurrency",
                 "guided_share", "spec_hit_rate"}
        unknown = sorted(str(k) for k in set(data) - known)
        if unknown:
            raise ValueError(
                f"unknown workload descriptor keys: {', '.join(unknown)} "
                f"(expected a subset of {', '.join(sorted(known))})")
        base = cls()
        try:
            return cls(
                prompt_len=int(data.get("prompt_len", base.prompt_len)),
                output_len=int(data.get("output_len", base.output_len)),
                concurrency=int(data.get("concurrency",
                                         base.concurrency)),
                guided_share=float(data.get("guided_share",
                                            base.guided_share)),
                spec_hit_rate=float(data.get("spec_hit_rate",
                                             base.spec_hit_rate)))
        except (TypeError, ValueError) as e:
            # null / list / non-numeric values must surface as the same
            # ValueError contract unknown keys do — the CLI catches it
            # and prints the friendly message instead of a traceback.
            raise ValueError(
                f"bad workload descriptor value: {e}") from e


@dataclass(frozen=True)
class Hardware:
    """Per-chip envelope the roofline divides by. ``dispatch_overhead_s``
    is the host→device round-trip a dispatch pays regardless of payload
    (~70ms on tunneled TPU, ~0.1ms local)."""

    name: str
    hbm_bytes: int
    hbm_bw: float        # achievable bytes/s
    peak_flops: float
    dispatch_overhead_s: float
    # Host RAM available to the KV spill tier (kv_spill_pages feasibility
    # envelope — host bytes, never HBM). Spec-sheet default: serving hosts
    # carry at least this much.
    host_ram_bytes: int = 64 * GiB

    def to_dict(self) -> dict[str, Any]:
        return {"name": self.name, "hbm_bytes": self.hbm_bytes,
                "hbm_bw": self.hbm_bw, "peak_flops": self.peak_flops,
                "dispatch_overhead_s": self.dispatch_overhead_s,
                "host_ram_bytes": self.host_ram_bytes}


# Spec-sheet envelopes (bench.py carries the same peak-FLOPs table); "cpu"
# is deliberately pessimistic — it exists so the CPU smoke path orders
# candidates sanely, not to predict CPU tok/s.
HARDWARE: dict[str, Hardware] = {
    "v5e": Hardware("v5e", 16 * GiB, 8.1e11, 197e12, 1e-3),
    "v6e": Hardware("v6e", 32 * GiB, 1.6e12, 918e12, 1e-3),
    "v5e-tunnel": Hardware("v5e-tunnel", 16 * GiB, 8.1e11, 197e12, 7e-2),
    "cpu": Hardware("cpu", 16 * GiB, 2e10, 2e11, 2e-4),
}


@dataclass(frozen=True)
class Candidate:
    """One point of the coupled knob space the autotuner searches.

    ``num_pages`` / ``max_batch_slots`` are PER REPLICA when
    ``dp_replicas > 1`` — the same contract as ``llm.*`` config and
    ``EngineConfig``, so the budget a plan deploys through ``llm.plan``
    is exactly the budget the sweep scored and measured.
    """

    page_size: int = 16
    num_pages: int = 2048
    max_batch_slots: int = 8
    prefill_chunk: int = 256
    mixed_token_budget: Optional[int] = None
    decode_steps_per_dispatch: int = 8
    kv_dtype: str = "bf16"
    speculative: bool = True
    dp_replicas: int = 1
    tp: int = 1
    max_seq_len: int = 8192
    # Host-RAM spill tier pages (EngineConfig.kv_spill_pages; 0 = off).
    # Budgeted against host RAM via memory_plan.host_spill_bytes, never
    # HBM — feasibility checks the host envelope, not the pool budget.
    kv_spill_pages: int = 0
    # Prefill/decode disaggregation: replicas dedicated to the prefill
    # tier (FleetConfig.disagg_prefill_replicas; 0 = symmetric). Rides in
    # the plan's TOPOLOGY block, not the engine block — it is a fleet
    # deployment shape, not an EngineConfig knob.
    disagg_prefill_replicas: int = 0

    def engine_plan_block(self) -> dict[str, Any]:
        """The candidate as a plan artifact's ``engine`` block (tp and the
        disagg tier split ride in ``topology``)."""
        return {
            "page_size": self.page_size, "num_pages": self.num_pages,
            "max_batch_slots": self.max_batch_slots,
            "prefill_chunk": self.prefill_chunk,
            "mixed_token_budget": self.mixed_token_budget,
            "decode_steps_per_dispatch": self.decode_steps_per_dispatch,
            "kv_dtype": self.kv_dtype, "speculative": self.speculative,
            "dp_replicas": self.dp_replicas,
            "max_seq_len": self.max_seq_len,
            "kv_spill_pages": self.kv_spill_pages,
        }

    def topology_extras(self) -> dict[str, Any]:
        """Topology-block keys this candidate pins beyond tp/dp (empty
        for symmetric fleets, so existing plans hash unchanged)."""
        return ({"disagg_prefill_replicas": self.disagg_prefill_replicas}
                if self.disagg_prefill_replicas else {})

    @property
    def pool_tokens(self) -> int:
        return self.page_size * self.num_pages


@dataclass(frozen=True)
class CostEstimate:
    """The cost model's verdict on one candidate."""

    candidate: Candidate
    feasible: bool
    reason: str                      # why infeasible ("" when feasible)
    residency: Optional[ServingPlan]
    decode_tok_s: float              # predicted aggregate decode rate
    ttft_ms: float                   # predicted prompt-latency floor
    decode_step_bytes: float         # bytes one decode step moves per chip
    effective_batch: float

    def to_dict(self) -> dict[str, Any]:
        return {
            "feasible": self.feasible, "reason": self.reason,
            "decode_tok_s": round(self.decode_tok_s, 2),
            "ttft_ms": round(self.ttft_ms, 2),
            "decode_step_bytes": round(self.decode_step_bytes),
            "effective_batch": round(self.effective_batch, 2),
        }


class CostModel:
    """Analytic scorer for (model, hardware, weights-width) deployments."""

    def __init__(self, model_cfg, hardware: Hardware,
                 weights: str = "int8",
                 headroom_bytes: int = int(1.5 * GiB)):
        if weights not in ("int8", "bf16"):
            raise ValueError(f"weights must be int8|bf16, got {weights!r}")
        self.model_cfg = model_cfg
        self.hw = hardware
        self.weights = weights
        self.headroom_bytes = headroom_bytes

    # ------------------------------------------------------- residency

    def residency(self, cand: Candidate,
                  max_seq_len: Optional[int] = None) -> ServingPlan:
        """The candidate's HBM arithmetic — *delegated* to
        :func:`~runbookai_tpu.engine.memory_plan.plan_serving`, never
        re-derived, so the autotuner can't drift from the planner the
        engine and docs quote (pinned equal by test)."""
        kv_bytes, scale_bytes = KV_DTYPE_BYTES[cand.kv_dtype]
        return plan_serving(
            self.model_cfg,
            max_seq_len=max_seq_len or cand.max_seq_len,
            batch=cand.max_batch_slots, tp=cand.tp, weights=self.weights,
            kv_dtype_bytes=kv_bytes, kv_scale_bytes=scale_bytes,
            hbm_bytes=self.hw.hbm_bytes,
            headroom_bytes=self.headroom_bytes,
            kv_spill_pages=cand.kv_spill_pages,
            page_size=cand.page_size)

    def kv_pool_bytes(self, cand: Candidate,
                      plan: Optional[ServingPlan] = None) -> float:
        """Allocated pool bytes per chip for the candidate's page budget
        (pool token axis shards over pg_shards inside plan_serving's
        per-token figure). ``plan`` reuses an already-computed residency
        (weight/per-token bytes are max_seq_len-independent)."""
        plan = plan if plan is not None else self.residency(cand)
        return cand.pool_tokens * plan.kv_bytes_per_token_per_chip

    def decode_dispatch_bytes(self, cand: Candidate,
                              plan: Optional[ServingPlan] = None) -> float:
        """Resident argument bytes of one compiled decode step: weights at
        stored width + the KV pool + O(batch) small operands — the
        ``hlo_bytes.decode_accounting`` ``arguments_expected`` contract,
        predicted instead of measured."""
        plan = plan if plan is not None else self.residency(cand)
        small = 2048 * cand.max_batch_slots  # tokens/tables/sampling rows
        return (plan.weight_bytes_per_chip
                + self.kv_pool_bytes(cand, plan) + small)

    # ----------------------------------------------------- feasibility

    def check_feasible(self, cand: Candidate, workload: Workload,
                       plan: Optional[ServingPlan] = None) -> tuple[bool, str]:
        if plan is None:
            # A supplied plan proves the factorization already resolved.
            try:
                from runbookai_tpu.parallel.kv_split import plan_kv_split

                plan_kv_split(self.model_cfg, cand.tp)
            except ValueError as e:
                return False, f"tp factorization: {e}"
        if cand.dp_replicas > 1 and cand.tp > 1:
            return False, "dp_replicas > 1 requires tp == 1 (a replica is a single-slice engine)"
        if cand.kv_spill_pages < 0:
            return False, "kv_spill_pages must be >= 0"
        if cand.disagg_prefill_replicas < 0:
            return False, "disagg_prefill_replicas must be >= 0"
        if cand.disagg_prefill_replicas:
            if cand.disagg_prefill_replicas >= max(1, cand.dp_replicas):
                return False, (
                    f"disagg_prefill_replicas="
                    f"{cand.disagg_prefill_replicas} leaves no decode tier "
                    f"in a dp={cand.dp_replicas} fleet")
        ctx = min(workload.context_len, cand.max_seq_len)
        if workload.prompt_len >= cand.max_seq_len:
            return False, (f"prompt_len {workload.prompt_len} >= "
                           f"max_seq_len {cand.max_seq_len}")
        if cand.mixed_token_budget is not None and \
                cand.mixed_token_budget <= cand.max_batch_slots:
            return False, ("mixed_token_budget must exceed max_batch_slots "
                           "(decode slots alone consume the budget)")
        if plan is None:
            plan = self.residency(cand, max_seq_len=ctx)
        # Every co-resident replica pins its OWN tier; budget the worst
        # case of all dp replicas sharing one host (single-host fleets —
        # the CPU/bench shape — and the conservative bound for pods).
        spill_total = plan.host_spill_bytes * max(1, cand.dp_replicas)
        if spill_total > self.hw.host_ram_bytes // 2:
            return False, (
                f"spill tier {spill_total / GiB:.2f} GiB across "
                f"{max(1, cand.dp_replicas)} replica(s) exceeds half the "
                f"host RAM envelope "
                f"({self.hw.host_ram_bytes / GiB:.0f} GiB)")
        pool_bytes = cand.pool_tokens * plan.kv_bytes_per_token_per_chip
        if pool_bytes > plan.pool_budget_bytes:
            return False, (
                f"KV pool {pool_bytes / GiB:.2f} GiB exceeds the "
                f"post-weights budget {plan.pool_budget_bytes / GiB:.2f} "
                f"GiB ({plan.explain()})")
        if cand.pool_tokens < ctx + cand.prefill_chunk:
            return False, (f"pool holds {cand.pool_tokens} tokens < one "
                           f"{ctx}-token context + a prefill chunk")
        if not plan.fits:
            return False, plan.explain()
        return True, ""

    # --------------------------------------------------------- scoring

    def score(self, cand: Candidate, workload: Workload) -> CostEstimate:
        ctx = min(workload.context_len, cand.max_seq_len)
        # ONE plan_serving call per candidate, threaded through every
        # consumer (weight/per-token bytes are max_seq_len-independent).
        # Residency may be undefined (e.g. an unalignable tp
        # factorization) — an infeasible point scores zero, it doesn't
        # raise; check_feasible re-derives the reason from the probe.
        try:
            plan = self.residency(cand, max_seq_len=ctx)
        except ValueError:
            plan = None
        feasible, reason = self.check_feasible(cand, workload, plan=plan)
        if not feasible:
            return CostEstimate(cand, False, reason, None, 0.0,
                                float("inf"), 0.0, 0.0)
        step_bytes = self.decode_dispatch_bytes(cand, plan)
        cfg, hw = self.model_cfg, self.hw

        dp = max(1, cand.dp_replicas)
        # Disaggregation dedicates replicas to prefill: only the decode
        # tier contributes to the aggregate decode rate (its win — prompt
        # bursts off the decode path — shows up as TTFT stability in the
        # MEASURED arms, not in this roofline).
        dp_decode = max(1, dp - cand.disagg_prefill_replicas)
        # Effective decode batch per replica: bounded by slots, by the
        # share of traffic this replica sees, and by how many average
        # contexts the page pool actually holds.
        avg_ctx = workload.prompt_len + workload.output_len / 2
        pool_contexts = cand.pool_tokens / max(avg_ctx, 1)
        batch = min(cand.max_batch_slots, workload.concurrency / dp_decode,
                    pool_contexts)
        batch = max(batch, 1e-6)

        # One decode step over `batch` rows: every weight matrix read once
        # at stored width + the live KV pages + sampled-token output.
        live_kv = batch * avg_ctx * plan.kv_bytes_per_token_per_chip
        bytes_moved = plan.weight_bytes_per_chip + live_kv
        flops = 2.0 * cfg.matmul_params * batch / max(cand.tp, 1)
        device_s = max(bytes_moved / hw.hbm_bw, flops / hw.peak_flops)

        # Host-sync amortization: k tokens per dispatch, speculation
        # stretches the accepted run, guided traffic forces k=1 sync
        # dispatches (the classic path) for its share.
        k = max(1, cand.decode_steps_per_dispatch)
        if cand.speculative:
            k = k * (1.0 + max(0.0, workload.spec_hit_rate))
        sync_s = hw.dispatch_overhead_s
        per_step_overhead = (
            (1.0 - workload.guided_share) * sync_s / k
            + workload.guided_share * sync_s)
        step_s = device_s + per_step_overhead
        decode_tok_s = batch / step_s * dp_decode

        # TTFT floor: chunked prefill, one dispatch per chunk; the mixed
        # dispatch (budget permitting) folds each chunk into a decode step
        # it was going to pay for anyway — one sync instead of two.
        chunk = min(cand.prefill_chunk,
                    (cand.mixed_token_budget - cand.max_batch_slots)
                    if cand.mixed_token_budget else cand.prefill_chunk)
        chunk = max(1, chunk)
        n_chunks = -(-workload.prompt_len // chunk)
        chunk_flops = 2.0 * cfg.matmul_params * chunk / max(cand.tp, 1)
        chunk_bytes = plan.weight_bytes_per_chip
        chunk_s = max(chunk_flops / hw.peak_flops,
                      chunk_bytes / hw.hbm_bw)
        syncs_per_chunk = 1 if cand.mixed_token_budget is None else 0.5
        ttft_s = n_chunks * (chunk_s + syncs_per_chunk * sync_s)

        return CostEstimate(cand, True, "", plan, decode_tok_s,
                            ttft_s * 1e3, step_bytes, batch)

    def score_many(self, cands: Iterable[Candidate],
                   workload: Workload) -> list[CostEstimate]:
        return [self.score(c, workload) for c in cands]


# ------------------------------------------------------------ search space


@dataclass(frozen=True)
class SearchSpace:
    """Axis values the sweep enumerates (cartesian product, then pruned).
    Defaults cover the hand-picked regimes BENCHLOG has actually A/B'd."""

    page_size: tuple[int, ...] = (16,)
    num_pages: tuple[int, ...] = (1024, 2048, 4096)
    max_batch_slots: tuple[int, ...] = (4, 8, 16, 32)
    prefill_chunk: tuple[int, ...] = (128, 256, 512)
    mixed_token_budget: tuple[Optional[int], ...] = (None,)
    decode_steps_per_dispatch: tuple[int, ...] = (1, 4, 8)
    kv_dtype: tuple[str, ...] = ("bf16", "fp8")
    speculative: tuple[bool, ...] = (True, False)
    dp_replicas: tuple[int, ...] = (1,)
    tp: tuple[int, ...] = (1,)
    max_seq_len: tuple[int, ...] = (8192,)
    # Fleet-shape knobs (PR 8): off by default so existing sweeps and
    # their plan hashes are unchanged until a space opts in.
    kv_spill_pages: tuple[int, ...] = (0,)
    disagg_prefill_replicas: tuple[int, ...] = (0,)

    def candidates(self) -> list[Candidate]:
        axes = (self.page_size, self.num_pages, self.max_batch_slots,
                self.prefill_chunk, self.mixed_token_budget,
                self.decode_steps_per_dispatch, self.kv_dtype,
                self.speculative, self.dp_replicas, self.tp,
                self.max_seq_len, self.kv_spill_pages,
                self.disagg_prefill_replicas)
        return [Candidate(*values) for values in itertools.product(*axes)]


def smoke_space(max_seq_len: int = 256) -> SearchSpace:
    """A CPU-sized space for the tier-1 / `runbook tune --smoke` path:
    small enough that analytic prune + a couple of measured runs finish
    in seconds on the tiny test model."""
    return SearchSpace(
        page_size=(4,), num_pages=(64, 256),
        max_batch_slots=(2, 4), prefill_chunk=(16, 32),
        mixed_token_budget=(None,), decode_steps_per_dispatch=(4, 8),
        kv_dtype=("auto",), speculative=(True, False),
        dp_replicas=(1,), tp=(1,), max_seq_len=(max_seq_len,))
