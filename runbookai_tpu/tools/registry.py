"""Tool registry: categories, definition helper, runtime config gating.

Parity target: reference ``src/tools/registry.ts`` (``ToolRegistry`` +
``defineTool`` :109-212; category registration :2067-3685) and
``src/cli/runtime-tools.ts:19-69`` (config toggles select which categories/
tools an agent run exposes). The registry itself is dependency-free; tool
factories live in sibling modules and register on demand.
"""

from __future__ import annotations

from typing import Any, Awaitable, Callable, Optional

from runbookai_tpu.agent.types import RiskLevel, Tool

CATEGORIES = (
    "aws", "kubernetes", "code", "observability", "knowledge", "incident",
    "skills", "context", "diagram", "general",
)


class ToolRegistry:
    def __init__(self) -> None:
        self._tools: dict[str, Tool] = {}
        self._categories: dict[str, list[str]] = {c: [] for c in CATEGORIES}

    def register(self, tool: Tool) -> Tool:
        if tool.name in self._tools:
            raise ValueError(f"tool {tool.name!r} already registered")
        self._tools[tool.name] = tool
        self._categories.setdefault(tool.category, []).append(tool.name)
        return tool

    def define(
        self,
        name: str,
        description: str,
        parameters: dict[str, Any],
        execute: Callable[[dict[str, Any]], Awaitable[Any]],
        category: str = "general",
        risk: RiskLevel = RiskLevel.READ,
        call_limit: Optional[int] = None,
    ) -> Tool:
        """``defineTool`` equivalent (reference registry.ts:198-212)."""
        return self.register(Tool(
            name=name, description=description, parameters=parameters,
            execute=execute, category=category, risk=risk, call_limit=call_limit,
        ))

    def get(self, name: str) -> Optional[Tool]:
        return self._tools.get(name)

    def all(self) -> list[Tool]:
        return list(self._tools.values())

    def by_category(self, category: str) -> list[Tool]:
        return [self._tools[n] for n in self._categories.get(category, [])]

    def names(self) -> list[str]:
        return sorted(self._tools)


def object_schema(properties: dict[str, Any], required: Optional[list[str]] = None) -> dict[str, Any]:
    schema: dict[str, Any] = {"type": "object", "properties": properties}
    if required:
        schema["required"] = required
    return schema


def get_runtime_tools(config, registry: Optional[ToolRegistry] = None,
                      knowledge=None, safety=None, llm=None) -> list[Tool]:
    """Build the gated tool list for one agent run from config.

    Mirrors ``getRuntimeTools`` (runtime-tools.ts:19): each provider block's
    ``enabled``/``simulated`` flags select real or fixture-backed tools;
    context + diagram tools are always on.
    """
    reg = registry or ToolRegistry()

    from runbookai_tpu.tools import context as context_tools
    from runbookai_tpu.tools import diagram as diagram_tools
    from runbookai_tpu.tools import simulated as simulated_tools

    context_tools.register(reg)
    diagram_tools.register(reg)

    sim = simulated_tools.SimulatedCloud.from_config(config)
    aws_cfg = config.providers.aws
    if aws_cfg.enabled:
        if aws_cfg.simulated:
            simulated_tools.register_aws(reg, sim)
            # Deterministic cross-modality analysis over the same
            # fixtures (agent/signal_triage.py) — the stale/decoy/
            # dropout-aware layer the adversarial eval exercises.
            simulated_tools.register_triage(reg, sim)
        else:
            from runbookai_tpu.tools import aws as aws_tools

            aws_tools.register(reg, config, safety=safety)
    k8s_cfg = config.providers.kubernetes
    if k8s_cfg.enabled:
        if k8s_cfg.simulated:
            simulated_tools.register_kubernetes(reg, sim)
        else:
            from runbookai_tpu.tools import kubernetes as k8s_tools

            k8s_tools.register(reg, config, safety=safety)
    obs = config.observability
    if obs.datadog.enabled or obs.prometheus.enabled:
        if (obs.datadog.enabled and obs.datadog.simulated) or (
            obs.prometheus.enabled and obs.prometheus.simulated
        ):
            simulated_tools.register_observability(reg, sim, obs)
        else:
            from runbookai_tpu.tools import observability as obs_tools

            obs_tools.register(reg, config)
    inc = config.incident
    if inc.pagerduty.enabled or inc.opsgenie.enabled or inc.slack.enabled:
        if (inc.pagerduty.enabled and inc.pagerduty.simulated) or (
            inc.opsgenie.enabled and inc.opsgenie.simulated
        ):
            simulated_tools.register_incident(reg, sim, inc)
        else:
            from runbookai_tpu.tools import incident as incident_tools

            incident_tools.register(reg, config)
    if config.providers.github.enabled or config.providers.gitlab.enabled:
        if config.providers.github.enabled and config.providers.github.simulated:
            simulated_tools.register_code(reg, sim)
        else:
            from runbookai_tpu.tools import code as code_tools

            code_tools.register(reg, config)
    if knowledge is not None:
        from runbookai_tpu.tools import knowledge_tool

        knowledge_tool.register(reg, knowledge)

    # Skills last: the executor closes over the fully-populated tool set.
    from runbookai_tpu.skills.executor import SkillExecutor
    from runbookai_tpu.skills.registry import SkillRegistry, register_skill_tool

    skills = SkillRegistry()
    skills.load_user_skills(f"{getattr(config, 'runbook_dir', '.runbook')}/skills")
    tool_map = {t.name: t for t in reg.all()}

    approval = None
    if safety is not None:
        from runbookai_tpu.agent.safety import ApprovalRequest, RiskLevel
        from runbookai_tpu.agent.safety import classify_risk as _classify

        async def approval(step, params):  # noqa: F811 — skill approval seam
            decision = await safety.gate(ApprovalRequest(
                operation=step.action or step.id,
                risk=_classify(step.action or step.id, default=RiskLevel.HIGH),
                description=step.description or f"skill step {step.id}",
                params=params,
            ))
            return decision.approved

    executor = SkillExecutor(tool_map, llm=llm, approval_callback=approval)
    register_skill_tool(reg, skills, executor)
    return reg.all()
