"""Real AWS tools: declarative service catalog + generic boto3 executor.

Parity target: reference ``src/providers/aws/services.ts`` (49 service
definitions across 10 categories, each declaring sdk package, client class,
list/describe operations, pagination, formatter) + ``executor.ts`` (dynamic
import with cache :12-29, ``executeListOperation`` :98,
``executeMultiServiceQuery`` :195 parallel fan-out) + ``client.ts``
(credentials via profile/role/env, multi-region). boto3 replaces the
per-service SDK packages: one client factory, the catalog keeps the same
declarative shape. Gated: without boto3/credentials every call returns a
structured error instead of raising.

Also includes the ``aws_cli`` escape hatch (reference registry.ts:1534) with
the shell-operator rejection and read-only operation allowlist, and
``aws_mutate`` (registry.ts:542) risk-gated through the safety manager.
"""

from __future__ import annotations

import asyncio
import re
import shutil
import subprocess
from dataclasses import dataclass, field
from typing import Any, Optional

from runbookai_tpu.agent.types import RiskLevel
from runbookai_tpu.tools.registry import ToolRegistry, object_schema


@dataclass
class AWSServiceDef:
    service_id: str
    category: str
    client: str  # boto3 client name
    list_op: str  # python method name
    result_key: str
    name_keys: tuple[str, ...] = ("Name",)
    params: dict[str, Any] = field(default_factory=dict)


def _svc(sid, cat, client, op, key, names=("Name",), **params) -> AWSServiceDef:
    return AWSServiceDef(sid, cat, client, op, key, tuple(names), dict(params))


# The 49-service catalog (categories follow the reference's grouping).
AWS_SERVICES: list[AWSServiceDef] = [
    # compute
    _svc("ec2", "compute", "ec2", "describe_instances", "Reservations", ("InstanceId",)),
    _svc("ecs", "compute", "ecs", "list_clusters", "clusterArns", ()),
    _svc("eks", "compute", "eks", "list_clusters", "clusters", ()),
    _svc("lambda", "compute", "lambda", "list_functions", "Functions", ("FunctionName",)),
    _svc("lightsail", "compute", "lightsail", "get_instances", "instances", ("name",)),
    _svc("apprunner", "compute", "apprunner", "list_services", "ServiceSummaryList", ("ServiceName",)),
    _svc("amplify", "compute", "amplify", "list_apps", "apps", ("name",)),
    _svc("batch", "compute", "batch", "describe_job_queues", "jobQueues", ("jobQueueName",)),
    _svc("ecr", "compute", "ecr", "describe_repositories", "repositories", ("repositoryName",)),
    # database
    _svc("rds", "database", "rds", "describe_db_instances", "DBInstances", ("DBInstanceIdentifier",)),
    _svc("dynamodb", "database", "dynamodb", "list_tables", "TableNames", ()),
    _svc("elasticache", "database", "elasticache", "describe_cache_clusters", "CacheClusters", ("CacheClusterId",)),
    _svc("docdb", "database", "docdb", "describe_db_clusters", "DBClusters", ("DBClusterIdentifier",)),
    _svc("neptune", "database", "neptune", "describe_db_clusters", "DBClusters", ("DBClusterIdentifier",)),
    _svc("redshift", "database", "redshift", "describe_clusters", "Clusters", ("ClusterIdentifier",)),
    _svc("memorydb", "database", "memorydb", "describe_clusters", "Clusters", ("Name",)),
    # storage
    _svc("s3", "storage", "s3", "list_buckets", "Buckets", ("Name",)),
    _svc("efs", "storage", "efs", "describe_file_systems", "FileSystems", ("FileSystemId",)),
    _svc("fsx", "storage", "fsx", "describe_file_systems", "FileSystems", ("FileSystemId",)),
    _svc("backup", "storage", "backup", "list_backup_vaults", "BackupVaultList", ("BackupVaultName",)),
    # network
    _svc("vpc", "network", "ec2", "describe_vpcs", "Vpcs", ("VpcId",)),
    _svc("elb", "network", "elbv2", "describe_load_balancers", "LoadBalancers", ("LoadBalancerName",)),
    _svc("cloudfront", "network", "cloudfront", "list_distributions", "DistributionList", ("Id",)),
    _svc("route53", "network", "route53", "list_hosted_zones", "HostedZones", ("Name",)),
    _svc("apigateway", "network", "apigateway", "get_rest_apis", "items", ("name",)),
    _svc("apigwv2", "network", "apigatewayv2", "get_apis", "Items", ("Name",)),
    # security
    _svc("iam", "security", "iam", "list_roles", "Roles", ("RoleName",)),
    _svc("secretsmanager", "security", "secretsmanager", "list_secrets", "SecretList", ("Name",)),
    _svc("kms", "security", "kms", "list_keys", "Keys", ("KeyId",)),
    _svc("acm", "security", "acm", "list_certificates", "CertificateSummaryList", ("DomainName",)),
    _svc("waf", "security", "wafv2", "list_web_acls", "WebACLs", ("Name",), Scope="REGIONAL"),
    # messaging
    _svc("sqs", "messaging", "sqs", "list_queues", "QueueUrls", ()),
    _svc("sns", "messaging", "sns", "list_topics", "Topics", ("TopicArn",)),
    _svc("eventbridge", "messaging", "events", "list_rules", "Rules", ("Name",)),
    _svc("stepfunctions", "messaging", "stepfunctions", "list_state_machines", "stateMachines", ("name",)),
    _svc("kinesis", "messaging", "kinesis", "list_streams", "StreamNames", ()),
    # observability
    _svc("cloudwatch", "observability", "cloudwatch", "describe_alarms", "MetricAlarms", ("AlarmName",)),
    _svc("logs", "observability", "logs", "describe_log_groups", "logGroups", ("logGroupName",)),
    _svc("ssm", "observability", "ssm", "describe_instance_information", "InstanceInformationList", ("InstanceId",)),
    # devops
    _svc("cloudformation", "devops", "cloudformation", "describe_stacks", "Stacks", ("StackName",)),
    _svc("codepipeline", "devops", "codepipeline", "list_pipelines", "pipelines", ("name",)),
    _svc("codebuild", "devops", "codebuild", "list_projects", "projects", ()),
    _svc("codecommit", "devops", "codecommit", "list_repositories", "repositories", ("repositoryName",)),
    # analytics
    _svc("athena", "analytics", "athena", "list_work_groups", "WorkGroups", ("Name",)),
    _svc("glue", "analytics", "glue", "get_databases", "DatabaseList", ("Name",)),
    _svc("opensearch", "analytics", "opensearch", "list_domain_names", "DomainNames", ("DomainName",)),
    # ml
    _svc("sagemaker", "ml", "sagemaker", "list_endpoints", "Endpoints", ("EndpointName",)),
    _svc("bedrock", "ml", "bedrock", "list_foundation_models", "modelSummaries", ("modelId",)),
    _svc("comprehend", "ml", "comprehend", "list_entity_recognizers", "EntityRecognizerPropertiesList", ()),
]

SERVICES_BY_ID = {s.service_id: s for s in AWS_SERVICES}
CATEGORIES = sorted({s.category for s in AWS_SERVICES})


class AWSClientManager:
    """boto3 client cache with profile / role-assumption / region handling."""

    def __init__(self, profile: Optional[str] = None, role_arn: Optional[str] = None,
                 region: str = "us-east-1"):
        self.profile = profile
        self.role_arn = role_arn
        self.region = region
        self._session = None
        self._clients: dict[tuple[str, str], Any] = {}

    def available(self) -> bool:
        try:
            import boto3  # noqa: F401

            return True
        except ImportError:
            return False

    def _get_session(self):
        import boto3

        if self._session is None:
            session = boto3.Session(profile_name=self.profile) if self.profile \
                else boto3.Session()
            if self.role_arn:
                sts = session.client("sts")
                creds = sts.assume_role(
                    RoleArn=self.role_arn, RoleSessionName="runbookai-tpu"
                )["Credentials"]
                session = boto3.Session(
                    aws_access_key_id=creds["AccessKeyId"],
                    aws_secret_access_key=creds["SecretAccessKey"],
                    aws_session_token=creds["SessionToken"],
                )
            self._session = session
        return self._session

    def client(self, name: str, region: Optional[str] = None):
        key = (name, region or self.region)
        if key not in self._clients:
            self._clients[key] = self._get_session().client(
                name, region_name=region or self.region)
        return self._clients[key]


def _format_resources(defn: AWSServiceDef, payload: Any) -> list[Any]:
    items = payload.get(defn.result_key, []) if isinstance(payload, dict) else []
    if defn.service_id == "ec2":  # Reservations nest Instances
        items = [i for r in items for i in r.get("Instances", [])]
    if defn.service_id == "cloudfront" and isinstance(items, dict):
        items = items.get("Items", [])
    return items


async def execute_list_operation(
    manager: AWSClientManager, defn: AWSServiceDef, region: Optional[str] = None,
    max_items: int = 100,
) -> dict[str, Any]:
    """Generic paginated list with uniform formatting (executor.ts:98)."""

    def call() -> dict[str, Any]:
        client = manager.client(defn.client, region)
        items: list[Any] = []
        try:
            paginator = client.get_paginator(defn.list_op)
            for page in paginator.paginate(**defn.params):
                items.extend(_format_resources(defn, page))
                if len(items) >= max_items:
                    break
        except Exception:  # noqa: BLE001 — not all ops are paginatable
            payload = getattr(client, defn.list_op)(**defn.params)
            items = _format_resources(defn, payload)
        return {"service": defn.service_id, "category": defn.category,
                "count": len(items), "resources": items[:max_items]}

    return await asyncio.to_thread(call)


async def execute_multi_service_query(
    manager: AWSClientManager, service: Optional[str] = None,
    category: Optional[str] = None, region: Optional[str] = None,
) -> dict[str, Any]:
    """Service / category / all fan-out, concurrent (executor.ts:195)."""
    if service and service != "all":
        defn = SERVICES_BY_ID.get(service)
        if defn is None:
            return {"error": f"unknown AWS service {service!r}",
                    "available": sorted(SERVICES_BY_ID)}
        targets = [defn]
    elif category:
        targets = [s for s in AWS_SERVICES if s.category == category]
        if not targets:
            return {"error": f"unknown category {category!r}", "available": CATEGORIES}
    else:
        targets = AWS_SERVICES

    async def one(defn: AWSServiceDef) -> tuple[str, Any]:
        try:
            return defn.service_id, await execute_list_operation(manager, defn, region)
        except Exception as exc:  # noqa: BLE001 — per-service failures isolate
            return defn.service_id, {"error": f"{type(exc).__name__}: {exc}"}

    results = await asyncio.gather(*(one(d) for d in targets))
    return {sid: payload for sid, payload in results}


# --------------------------------------------------------------------------- #
# aws_cli escape hatch                                                        #
# --------------------------------------------------------------------------- #

_SHELL_OPERATORS = re.compile(r"[|&;<>`$(){}\\]")
# Read-only operation prefixes (reference registry.ts:1515 allowlist spirit).
_READONLY_PREFIXES = ("describe", "get", "list", "lookup", "search", "scan",
                      "query", "head", "batch-get", "test")


def validate_aws_cli_args(args: list[str]) -> Optional[str]:
    """Reject shell metacharacters and non-read-only operations."""
    for arg in args:
        if _SHELL_OPERATORS.search(arg):
            return f"shell operators are not allowed: {arg!r}"
    if len(args) < 2:
        return "expected: <service> <operation> [flags]"
    op = args[1]
    if not any(op.startswith(p) for p in _READONLY_PREFIXES):
        return (f"operation {op!r} is not read-only; use aws_mutate for "
                "mutations (approval-gated)")
    return None


async def run_aws_cli(args: list[str], timeout: float = 60.0) -> dict[str, Any]:
    problem = validate_aws_cli_args(args)
    if problem:
        return {"error": problem}
    if shutil.which("aws") is None:
        return {"error": "aws CLI not installed in this environment"}

    def call() -> dict[str, Any]:
        proc = subprocess.run(
            ["aws", *args, "--output", "json"],
            capture_output=True, text=True, timeout=timeout,
        )
        if proc.returncode != 0:
            return {"error": proc.stderr.strip()[:2000]}
        return {"output": proc.stdout[:20000]}

    return await asyncio.to_thread(call)


# --------------------------------------------------------------------------- #
# registration                                                                #
# --------------------------------------------------------------------------- #


def register(reg: ToolRegistry, config, safety=None) -> None:
    aws_cfg = config.providers.aws
    manager = AWSClientManager(
        profile=aws_cfg.profile, role_arn=aws_cfg.role_arn,
        region=aws_cfg.regions[0] if aws_cfg.regions else "us-east-1",
    )

    async def aws_query(args):
        if not manager.available():
            return {"error": "boto3 is not installed; enable simulated mode "
                             "(providers.aws.simulated: true) or install boto3"}
        return await execute_multi_service_query(
            manager, service=args.get("service"), category=args.get("category"),
            region=args.get("region"))

    async def aws_mutate(args):
        operation = str(args.get("operation", ""))
        if safety is not None:
            from runbookai_tpu.agent.safety import ApprovalRequest, classify_risk

            decision = await safety.gate(ApprovalRequest(
                operation=operation, risk=classify_risk(operation),
                description=f"AWS mutation on {args.get('service')}",
                params=args.get("params") or {},
                rollback_hint=args.get("rollback"),
            ))
            if not decision.approved:
                return {"status": "rejected", "reason": decision.reason}
        if not manager.available():
            return {"error": "boto3 is not installed"}

        def call() -> dict[str, Any]:
            service = str(args.get("service", ""))
            params = args.get("params") or {}
            if operation in ("scale", "update_service"):
                client = manager.client("ecs")
                return client.update_service(
                    cluster=params.get("cluster", "default"), service=service,
                    **{k: v for k, v in params.items() if k not in ("cluster",)})
            if operation in ("reboot", "start", "stop"):
                client = manager.client("ec2")
                method = {"reboot": "reboot_instances", "start": "start_instances",
                          "stop": "stop_instances"}[operation]
                return getattr(client, method)(InstanceIds=params.get("instance_ids", []))
            if operation == "update_function_configuration":
                client = manager.client("lambda")
                return client.update_function_configuration(
                    FunctionName=service, **params)
            raise ValueError(f"unsupported operation {operation!r}")

        try:
            result = await asyncio.to_thread(call)
            return {"status": "applied", "result": str(result)[:2000]}
        except Exception as exc:  # noqa: BLE001
            return {"status": "failed", "error": f"{type(exc).__name__}: {exc}"}

    async def cloudwatch_alarms(args):
        if not manager.available():
            return {"error": "boto3 is not installed"}

        def call():
            client = manager.client("cloudwatch")
            kwargs = {}
            if args.get("state"):
                kwargs["StateValue"] = str(args["state"]).upper()
            payload = client.describe_alarms(**kwargs)
            return {"alarms": [
                {"alarmName": a.get("AlarmName"), "state": a.get("StateValue"),
                 "metric": a.get("MetricName"), "threshold": a.get("Threshold"),
                 "reason": a.get("StateReason", "")[:300]}
                for a in payload.get("MetricAlarms", [])
            ]}

        return await asyncio.to_thread(call)

    async def cloudwatch_logs(args):
        if not manager.available():
            return {"error": "boto3 is not installed"}

        def call():
            import time as _time

            client = manager.client("logs")
            minutes = float(args.get("minutes_back", 30))
            kwargs: dict[str, Any] = {
                "logGroupName": str(args.get("log_group", "")),
                "startTime": int((_time.time() - minutes * 60) * 1000),
                "limit": int(args.get("limit", 100)),
            }
            if args.get("filter_pattern"):
                kwargs["filterPattern"] = str(args["filter_pattern"])
            payload = client.filter_log_events(**kwargs)
            return {"events": [
                {"ts": e.get("timestamp"), "message": e.get("message", "")[:500]}
                for e in payload.get("events", [])
            ]}

        return await asyncio.to_thread(call)

    async def aws_cli(args):
        return await run_aws_cli([str(a) for a in args.get("args", [])])

    reg.define(
        "aws_query",
        "Query AWS resources. service: one of the 49 catalog ids or 'all'; "
        f"category: one of {CATEGORIES}.",
        object_schema({"service": {"type": "string"},
                       "category": {"type": "string"},
                       "region": {"type": "string"}}),
        aws_query, category="aws",
    )
    reg.define(
        "aws_mutate",
        "Mutate AWS resources (ECS update/scale, EC2 reboot/start/stop, Lambda "
        "config). Approval-gated by risk.",
        object_schema({"operation": {"type": "string"},
                       "service": {"type": "string"},
                       "params": {"type": "object"},
                       "rollback": {"type": "string"}}, ["operation"]),
        aws_mutate, category="aws", risk=RiskLevel.HIGH,
    )
    reg.define(
        "cloudwatch_alarms",
        "List CloudWatch alarms, optionally by state.",
        object_schema({"state": {"type": "string"}}),
        cloudwatch_alarms, category="aws",
    )
    reg.define(
        "cloudwatch_logs",
        "Filter CloudWatch log events from a log group.",
        object_schema({"log_group": {"type": "string"},
                       "filter_pattern": {"type": "string"},
                       "minutes_back": {"type": "number"},
                       "limit": {"type": "number"}}, ["log_group"]),
        cloudwatch_logs, category="aws",
    )
    reg.define(
        "aws_cli",
        "Read-only AWS CLI escape hatch: args = ['<service>', '<operation>', "
        "...flags]. Shell operators rejected; mutations rejected.",
        object_schema({"args": {"type": "array"}}, ["args"]),
        aws_cli, category="aws",
    )

    # Deep drill-down helpers beyond the catalog rows (tools/aws_deep.py:
    # EKS cluster/nodegroup health, Amplify deploy-job failures).
    from runbookai_tpu.tools import aws_deep

    aws_deep.register(reg, manager)
