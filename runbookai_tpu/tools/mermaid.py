"""Mermaid-source parsing → ASCII rendering for the ``render_mermaid`` tool.

Parity target: reference ``src/tools/diagram/mermaid.ts`` — diagram-type
detection (:51), flowchart/sequence/state parsers (:70/:149/:200), and the
``mermaidToASCII`` dispatcher (:516) behind the ``render_mermaid`` registry
tool (registry.ts:3648). Rendering reuses the box/lifeline renderers in
``tools/diagram.py``; the parsers accept the mermaid subset the agent emits
(graph/flowchart TD|LR, sequenceDiagram, stateDiagram-v2).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

_DECOR = r"(?:\[[^\]]+\]|\{[^}]+\}|\(\([^)]+\)\)|\(\[[^\]]+\]\))?"
_EDGE_RE = re.compile(
    rf"^(\w+{_DECOR})\s*(-\.+-?[>ox]?|-{{1,2}}[>ox]?|={{2,}}[>ox]?|\.{{2,}}[>ox]?)"
    rf"\s*(?:\|([^|]+)\|)?\s*(\w+{_DECOR})$")
_NODE_RE = re.compile(
    r"^(\w+)(\[([^\]]+)\]|\{([^}]+)\}|\(\(([^)]+)\)\)|\(\[([^\]]+)\]\))?$")
_PARTICIPANT_RE = re.compile(r"^participant\s+(\w+)(?:\s+as\s+(.+))?$", re.I)
_MESSAGE_RE = re.compile(r"^(\w+)\s*(-{1,2}>>?|\.{2,}>>?)\s*(\w+)\s*:\s*(.+)$")
_TRANSITION_RE = re.compile(r"^(\[\*\]|\w+)\s*-->\s*(\[\*\]|\w+)(?:\s*:\s*(.+))?$")


@dataclass
class Flowchart:
    direction: str = "TD"
    nodes: dict[str, dict[str, str]] = field(default_factory=dict)
    edges: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class SequenceDiagram:
    participants: list[str] = field(default_factory=list)
    messages: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class StateDiagram:
    states: list[str] = field(default_factory=list)
    transitions: list[dict[str, Any]] = field(default_factory=list)


def detect_diagram_type(code: str) -> str:
    first = code.strip().split("\n", 1)[0].strip().lower()
    if first.startswith(("graph", "flowchart")):
        return "flowchart"
    if first.startswith("sequencediagram"):
        return "sequence"
    if first.startswith("statediagram"):
        return "state"
    return "unknown"


def is_mermaid_code(code: str) -> bool:
    return detect_diagram_type(code) != "unknown"


def _body_lines(code: str) -> list[str]:
    lines = code.strip().split("\n")[1:]
    return [ln.strip() for ln in lines
            if ln.strip() and not ln.strip().startswith("%%")]


def parse_flowchart(code: str) -> Flowchart:
    chart = Flowchart()
    first = code.strip().split("\n", 1)[0].lower()
    for d in ("lr", "bt", "rl"):
        if first.endswith(" " + d):
            chart.direction = d.upper()
    def define_node(text: str) -> str:
        """Parse ``A`` / ``A[Label]`` / ``A{X}`` / ``A((X))`` / ``A([X])``."""
        node = _NODE_RE.match(text)
        if not node:
            return text
        nid, decor, rect, diamond, circle, stadium = node.groups()
        label, shape = nid, "rect"
        if rect:
            label = rect
        elif diamond:
            label, shape = diamond, "diamond"
        elif circle:
            label, shape = circle, "circle"
        elif stadium:
            label, shape = stadium, "stadium"
        if decor or nid not in chart.nodes:
            chart.nodes[nid] = {"id": nid, "label": label, "shape": shape}
        return nid

    for line in _body_lines(code):
        edge = _EDGE_RE.match(line)
        if edge:
            src_text, connector, label, dst_text = edge.groups()
            style = ("dotted" if "." in connector
                     else "thick" if "=" in connector else "solid")
            arrow = ("x" if "x" in connector
                     else "normal" if ">" in connector else "none")
            chart.edges.append({"from": define_node(src_text),
                                "to": define_node(dst_text),
                                "label": label or "",
                                "style": style, "arrow": arrow})
            continue
        define_node(line)
    return chart


def parse_sequence(code: str) -> SequenceDiagram:
    diagram = SequenceDiagram()
    seen: set[str] = set()

    def add(pid: str) -> None:
        if pid not in seen:
            seen.add(pid)
            diagram.participants.append(pid)

    for line in _body_lines(code):
        participant = _PARTICIPANT_RE.match(line)
        if participant:
            add(participant.group(1))
            continue
        message = _MESSAGE_RE.match(line)
        if message:
            src, connector, dst, text = message.groups()
            add(src)
            add(dst)
            kind = ("dotted" if ".." in connector
                    else "async" if "--" in connector else "solid")
            diagram.messages.append({"from": src, "to": dst, "label": text,
                                     "type": kind})
    return diagram


def parse_state(code: str) -> StateDiagram:
    diagram = StateDiagram()
    seen: set[str] = set()
    for line in _body_lines(code):
        transition = _TRANSITION_RE.match(line)
        if not transition:
            continue
        src, dst, label = transition.groups()
        for state in (src, dst):
            if state != "[*]" and state not in seen:
                seen.add(state)
                diagram.states.append(state)
        diagram.transitions.append({"from": src, "to": dst,
                                    "label": label or ""})
    return diagram


def render_state_ascii(diagram: StateDiagram) -> str:
    lines = ["State diagram:", ""]
    for state in diagram.states:
        lines.append(f"  ( {state} )")
    lines.append("")
    for t in diagram.transitions:
        src = "●" if t["from"] == "[*]" else t["from"]
        dst = "◉" if t["to"] == "[*]" else t["to"]
        label = f" : {t['label']}" if t["label"] else ""
        lines.append(f"  {src} ──▶ {dst}{label}")
    return "\n".join(lines)


def mermaid_to_ascii(code: str) -> str:
    """Dispatch on diagram type (mermaid.ts:516-538)."""
    from runbookai_tpu.tools.diagram import render_flowchart, render_sequence

    kind = detect_diagram_type(code)
    if kind == "flowchart":
        chart = parse_flowchart(code)
        return render_flowchart(list(chart.nodes.values()), chart.edges)
    if kind == "sequence":
        diagram = parse_sequence(code)
        return render_sequence(diagram.participants, diagram.messages)
    if kind == "state":
        return render_state_ascii(parse_state(code))
    return f"(unsupported mermaid diagram; first line: {code.strip().splitlines()[0] if code.strip() else ''!r})"
