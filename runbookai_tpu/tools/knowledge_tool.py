"""search_knowledge tool (reference ``src/tools/registry.ts:790``)."""

from __future__ import annotations

from runbookai_tpu.tools.registry import ToolRegistry, object_schema


def register(reg: ToolRegistry, retriever) -> None:
    async def search_knowledge(args):
        hits = retriever.hybrid.search(
            str(args.get("query", "")),
            limit=int(args.get("limit", 6)),
            knowledge_type=args.get("type"),
            service=args.get("service"),
        )
        return {
            "results": [
                {
                    "doc_id": h.doc.doc_id,
                    "title": h.doc.title,
                    "type": h.doc.knowledge_type,
                    "section": h.chunk.section,
                    "content": h.chunk.content[:1200],
                    "score": round(h.score, 4),
                    "services": h.doc.services,
                }
                for h in hits
            ]
        }

    reg.define(
        "search_knowledge",
        "Search the knowledge base (runbooks, postmortems, known issues, "
        "architecture docs). Optional filters: type, service.",
        object_schema(
            {"query": {"type": "string"}, "type": {"type": "string"},
             "service": {"type": "string"}, "limit": {"type": "number"}},
            ["query"],
        ),
        search_knowledge, category="knowledge",
    )
