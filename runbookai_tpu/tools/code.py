"""Code provider tools: GitHub / GitLab queries including fix-candidate
retrieval for remediation.

Parity targets: reference ``src/tools/code/github.ts`` (:284) and
``gitlab.ts`` (:348) — recent PR/MR and commit queries plus the
``fix_candidates`` action used by the orchestrator's remediation phase
(investigation-orchestrator.ts:551-628).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from runbookai_tpu.tools.registry import ToolRegistry, object_schema


def _get(url: str, headers: dict[str, str], params: Optional[dict] = None,
         timeout: float = 20.0) -> Any:
    import requests

    resp = requests.get(url, headers=headers, params=params or {}, timeout=timeout)
    resp.raise_for_status()
    return resp.json()


class GitHubClient:
    def __init__(self, token: str, base_url: Optional[str] = None):
        self.base = (base_url or "https://api.github.com").rstrip("/")
        self.headers = {"Authorization": f"Bearer {token}",
                        "Accept": "application/vnd.github+json"}

    async def recent_prs(self, repo: str, state: str = "closed",
                         limit: int = 10) -> list[dict[str, Any]]:
        data = await asyncio.to_thread(
            _get, f"{self.base}/repos/{repo}/pulls", self.headers,
            {"state": state, "sort": "updated", "direction": "desc",
             "per_page": limit})
        return [{"number": p["number"], "title": p["title"],
                 "merged_at": p.get("merged_at"), "user": p["user"]["login"],
                 "url": p["html_url"]} for p in data]

    async def recent_commits(self, repo: str, limit: int = 10) -> list[dict[str, Any]]:
        data = await asyncio.to_thread(
            _get, f"{self.base}/repos/{repo}/commits", self.headers,
            {"per_page": limit})
        return [{"sha": c["sha"][:10],
                 "message": c["commit"]["message"].splitlines()[0][:120],
                 "author": c["commit"]["author"]["name"],
                 "date": c["commit"]["author"]["date"]} for c in data]

    async def fix_candidates(self, repo: str, keywords: list[str],
                             limit: int = 5) -> list[dict[str, Any]]:
        """Recently merged PRs whose titles match incident keywords — the
        rollback/fix candidates for remediation."""
        prs = await self.recent_prs(repo, state="closed", limit=30)
        scored = []
        for pr in prs:
            title = pr["title"].lower()
            hits = sum(1 for k in keywords if k.lower() in title)
            if pr.get("merged_at"):
                scored.append((hits, pr))
        scored.sort(key=lambda t: (t[0], t[1].get("merged_at") or ""), reverse=True)
        return [{"relevance": hits, **pr} for hits, pr in scored[:limit]]


class GitLabClient:
    def __init__(self, token: str, base_url: Optional[str] = None):
        self.base = (base_url or "https://gitlab.com").rstrip("/") + "/api/v4"
        self.headers = {"PRIVATE-TOKEN": token}

    @staticmethod
    def _project_id(repo: str) -> str:
        import urllib.parse

        return urllib.parse.quote(repo, safe="")

    async def recent_mrs(self, repo: str, state: str = "merged",
                         limit: int = 10) -> list[dict[str, Any]]:
        data = await asyncio.to_thread(
            _get, f"{self.base}/projects/{self._project_id(repo)}/merge_requests",
            self.headers, {"state": state, "order_by": "updated_at",
                           "per_page": limit})
        return [{"number": m["iid"], "title": m["title"],
                 "merged_at": m.get("merged_at"), "user": m["author"]["username"],
                 "url": m["web_url"]} for m in data]

    async def fix_candidates(self, repo: str, keywords: list[str],
                             limit: int = 5) -> list[dict[str, Any]]:
        mrs = await self.recent_mrs(repo, state="merged", limit=30)
        scored = []
        for mr in mrs:
            hits = sum(1 for k in keywords if k.lower() in mr["title"].lower())
            scored.append((hits, mr))
        scored.sort(key=lambda t: (t[0], t[1].get("merged_at") or ""), reverse=True)
        return [{"relevance": hits, **mr} for hits, mr in scored[:limit]]


def _make_query(client, repos: list[str], kind: str):
    async def query(args):
        action = str(args.get("action", "recent_prs"))
        repo = str(args.get("repo") or (repos[0] if repos else ""))
        if not repo:
            return {"error": f"no {kind} repo configured or provided"}
        try:
            if action in ("recent_prs", "recent_mrs"):
                fn = getattr(client, "recent_prs", None) or client.recent_mrs
                return {"items": await fn(repo, limit=int(args.get("limit", 10)))}
            if action == "recent_commits" and hasattr(client, "recent_commits"):
                return {"items": await client.recent_commits(
                    repo, limit=int(args.get("limit", 10)))}
            if action == "fix_candidates":
                keywords = [str(k) for k in args.get("keywords", [])]
                service = str(args.get("service", ""))
                if service:
                    keywords.append(service)
                return {"candidates": await client.fix_candidates(repo, keywords)}
            return {"error": f"unknown action {action!r}",
                    "available": ["recent_prs", "recent_commits", "fix_candidates"]}
        except Exception as exc:  # noqa: BLE001
            return {"error": f"{type(exc).__name__}: {exc}"}

    return query


def register(reg: ToolRegistry, config) -> None:
    gh_cfg = config.providers.github
    gl_cfg = config.providers.gitlab
    if gh_cfg.enabled:
        gh = GitHubClient(gh_cfg.token or "", gh_cfg.base_url)
        reg.define(
            "github_query",
            "GitHub queries. action: recent_prs|recent_commits|fix_candidates "
            "(fix_candidates finds merged PRs matching incident keywords).",
            object_schema({"action": {"type": "string"}, "repo": {"type": "string"},
                           "keywords": {"type": "array"},
                           "service": {"type": "string"},
                           "limit": {"type": "number"}}, ["action"]),
            _make_query(gh, gh_cfg.repos, "github"), category="code",
        )
    if gl_cfg.enabled:
        gl = GitLabClient(gl_cfg.token or "", gl_cfg.base_url)
        reg.define(
            "gitlab_query",
            "GitLab queries. action: recent_mrs|fix_candidates.",
            object_schema({"action": {"type": "string"}, "repo": {"type": "string"},
                           "keywords": {"type": "array"},
                           "service": {"type": "string"},
                           "limit": {"type": "number"}}, ["action"]),
            _make_query(gl, gl_cfg.repos, "gitlab"), category="code",
        )
