"""Kubernetes tools: kubectl subprocess wrapper, read-only surface exposed.

Parity target: reference ``src/providers/kubernetes/client.ts`` (756 LoC
kubectl wrapper: spawn with ``-o json``, multi-context; read-only actions
exposed via ``kubernetes_query`` registry.ts:1696 — status/contexts/
namespaces/pods/deployments/nodes/events/top_pods/top_nodes). The reference
left the client's mutating methods un-exposed; this build additionally
registers ``kubernetes_mutate`` (scale/rollout_restart/rollout_undo/
delete_pod) through the safety/approval gate — the ``aws_mutate`` analog —
so K8s remediation steps can actually execute.
"""

from __future__ import annotations

import asyncio
import json
import shutil
import subprocess
from typing import Any, Optional

from runbookai_tpu.agent.types import RiskLevel
from runbookai_tpu.tools.registry import ToolRegistry, object_schema


class KubernetesClient:
    def __init__(self, context: Optional[str] = None, timeout: float = 30.0,
                 kubectl: str = "kubectl"):
        self.context = context
        self.timeout = timeout
        self.kubectl = kubectl

    def available(self) -> bool:
        return shutil.which(self.kubectl) is not None

    async def _run(self, args: list[str], parse_json: bool = True) -> Any:
        cmd = [self.kubectl]
        if self.context:
            cmd += ["--context", self.context]
        cmd += args
        if parse_json:
            cmd += ["-o", "json"]

        def call():
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=self.timeout)
            if proc.returncode != 0:
                raise RuntimeError(proc.stderr.strip()[:1000])
            return json.loads(proc.stdout) if parse_json else proc.stdout

        return await asyncio.to_thread(call)

    # ------------------------------------------------------------ read-only

    async def contexts(self) -> list[str]:
        out = await self._run(["config", "get-contexts", "-o", "name"],
                              parse_json=False)
        return [l for l in out.splitlines() if l.strip()]

    async def namespaces(self) -> list[str]:
        data = await self._run(["get", "namespaces"])
        return [i["metadata"]["name"] for i in data.get("items", [])]

    async def pods(self, namespace: Optional[str] = None) -> list[dict[str, Any]]:
        args = ["get", "pods"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        data = await self._run(args)
        out = []
        for item in data.get("items", []):
            statuses = item.get("status", {}).get("containerStatuses", [])
            restarts = sum(c.get("restartCount", 0) for c in statuses)
            out.append({
                "name": item["metadata"]["name"],
                "namespace": item["metadata"].get("namespace"),
                "status": item.get("status", {}).get("phase"),
                "restarts": restarts,
                "containers": [
                    {"name": c.get("name"), "ready": c.get("ready", False),
                     "state": next(iter(c.get("state", {})), "unknown")}
                    for c in statuses
                ],
            })
        return out

    async def deployments(self, namespace: Optional[str] = None) -> list[dict[str, Any]]:
        args = ["get", "deployments"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        data = await self._run(args)
        return [{
            "name": i["metadata"]["name"],
            "namespace": i["metadata"].get("namespace"),
            "replicas": f"{i.get('status', {}).get('readyReplicas', 0)}/"
                        f"{i.get('spec', {}).get('replicas', 0)}",
            "images": [c.get("image") for c in
                       i.get("spec", {}).get("template", {}).get("spec", {})
                       .get("containers", [])],
        } for i in data.get("items", [])]

    async def nodes(self) -> list[dict[str, Any]]:
        data = await self._run(["get", "nodes"])
        out = []
        for item in data.get("items", []):
            conditions = {c["type"]: c["status"]
                         for c in item.get("status", {}).get("conditions", [])}
            out.append({
                "name": item["metadata"]["name"],
                "status": "Ready" if conditions.get("Ready") == "True" else "NotReady",
                "conditions": conditions,
            })
        return out

    async def events(self, namespace: Optional[str] = None) -> list[dict[str, Any]]:
        args = ["get", "events", "--sort-by=.lastTimestamp"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        data = await self._run(args)
        return [{
            "ts": i.get("lastTimestamp"), "type": i.get("type"),
            "reason": i.get("reason"),
            "object": f"{i.get('involvedObject', {}).get('kind', '?')}/"
                      f"{i.get('involvedObject', {}).get('name', '?')}",
            "message": i.get("message", "")[:300],
        } for i in data.get("items", [])[-50:]]

    async def logs(self, pod: str, namespace: str = "default",
                   container: Optional[str] = None, tail: int = 100) -> str:
        args = ["logs", pod, "-n", namespace, f"--tail={tail}"]
        if container:
            args += ["-c", container]
        return await self._run(args, parse_json=False)

    async def describe(self, kind: str, name: str, namespace: str = "default") -> str:
        return await self._run(["describe", kind, name, "-n", namespace],
                               parse_json=False)

    async def top_pods(self, namespace: Optional[str] = None) -> str:
        args = ["top", "pods"]
        args += ["-n", namespace] if namespace else ["--all-namespaces"]
        return await self._run(args, parse_json=False)

    async def top_nodes(self) -> str:
        return await self._run(["top", "nodes"], parse_json=False)

    async def cluster_info(self) -> str:
        return await self._run(["cluster-info"], parse_json=False)

    # ------------------------------------- mutations (exposed via kubernetes_mutate)

    async def scale(self, deployment: str, replicas: int,
                    namespace: str = "default") -> str:
        return await self._run(
            ["scale", "deployment", deployment, f"--replicas={replicas}",
             "-n", namespace], parse_json=False)

    async def rollout_restart(self, deployment: str, namespace: str = "default") -> str:
        return await self._run(
            ["rollout", "restart", f"deployment/{deployment}", "-n", namespace],
            parse_json=False)

    async def rollout_undo(self, deployment: str, namespace: str = "default") -> str:
        return await self._run(
            ["rollout", "undo", f"deployment/{deployment}", "-n", namespace],
            parse_json=False)

    async def rollout_status(self, deployment: str, namespace: str = "default") -> str:
        return await self._run(
            ["rollout", "status", f"deployment/{deployment}", "-n", namespace],
            parse_json=False)

    async def delete_pod(self, pod: str, namespace: str = "default") -> str:
        return await self._run(["delete", "pod", pod, "-n", namespace],
                               parse_json=False)


def register(reg: ToolRegistry, config, safety=None) -> None:
    contexts = config.providers.kubernetes.contexts
    client = KubernetesClient(context=contexts[0] if contexts else None)

    async def kubernetes_query(args):
        if not client.available():
            return {"error": "kubectl not installed; enable simulated mode "
                             "(providers.kubernetes.simulated: true)"}
        action = str(args.get("action", "pods"))
        ns = args.get("namespace")
        c = KubernetesClient(context=args.get("context") or client.context) \
            if args.get("context") else client
        try:
            if action == "status" or action == "cluster-info":
                return {"info": await c.cluster_info()}
            if action == "contexts":
                return {"contexts": await c.contexts()}
            if action == "namespaces":
                return {"namespaces": await c.namespaces()}
            if action == "pods":
                return {"pods": await c.pods(ns)}
            if action == "deployments":
                return {"deployments": await c.deployments(ns)}
            if action == "nodes":
                return {"nodes": await c.nodes()}
            if action == "events":
                return {"events": await c.events(ns)}
            if action == "logs":
                return {"logs": await c.logs(str(args.get("pod", "")),
                                             ns or "default",
                                             args.get("container"),
                                             int(args.get("tail", 100)))}
            if action == "describe":
                return {"description": await c.describe(
                    str(args.get("kind", "pod")), str(args.get("name", "")),
                    ns or "default")}
            if action == "top_pods":
                return {"top": await c.top_pods(ns)}
            if action == "top_nodes":
                return {"top": await c.top_nodes()}
            return {"error": f"unknown action {action!r}",
                    "available": ["status", "contexts", "namespaces", "pods",
                                  "deployments", "nodes", "events", "logs",
                                  "describe", "top_pods", "top_nodes"]}
        except Exception as exc:  # noqa: BLE001
            return {"error": f"{type(exc).__name__}: {exc}"}

    reg.define(
        "kubernetes_query",
        "Read-only Kubernetes queries via kubectl. action: status|contexts|"
        "namespaces|pods|deployments|nodes|events|logs|describe|top_pods|top_nodes.",
        object_schema({"action": {"type": "string"},
                       "namespace": {"type": "string"},
                       "context": {"type": "string"},
                       "pod": {"type": "string"}, "name": {"type": "string"},
                       "kind": {"type": "string"}, "container": {"type": "string"},
                       "tail": {"type": "number"}}, ["action"]),
        kubernetes_query, category="kubernetes",
    )

    async def kubernetes_mutate(args):
        """Risk-gated K8s mutations — the ``aws_mutate`` analog (VERDICT r2
        weak #10: without this, K8s remediation steps could not execute).
        kubectl's mutating verbs existed on the client but were never
        registry-exposed (reference kubernetes/client.ts mirrors that gap;
        this build closes it through the same safety gate)."""
        operation = str(args.get("operation", ""))
        ns = str(args.get("namespace") or "default")
        target = str(args.get("name", ""))
        # Validate BEFORE the approval gate: an unknown operation, missing
        # kubectl, or absent required argument must not consume the
        # session's mutation budget or an operator's attention.
        if operation not in ("scale", "rollout_restart", "rollout_undo",
                             "delete_pod"):
            return {"error": f"unknown operation {operation!r}",
                    "available": ["scale", "rollout_restart", "rollout_undo",
                                  "delete_pod"]}
        if operation == "scale" and args.get("replicas") is None:
            # A missing count must be an error, never an implicit scale-to-1.
            return {"error": "scale requires an explicit 'replicas' count"}
        if not client.available():
            return {"error": "kubectl not installed; enable simulated mode "
                             "(providers.kubernetes.simulated: true)"}
        desc = f"Kubernetes {operation} on {target} (ns {ns})"
        if operation == "scale":
            desc += f" to {int(args['replicas'])} replicas"
        if safety is not None:
            from runbookai_tpu.agent.safety import ApprovalRequest, classify_risk

            decision = await safety.gate(ApprovalRequest(
                operation=operation, risk=classify_risk(operation),
                description=desc,
                params={k: v for k, v in args.items() if k != "operation"},
                rollback_hint=args.get("rollback"),
            ))
            if not decision.approved:
                return {"status": "rejected", "reason": decision.reason}
        c = KubernetesClient(context=args.get("context") or client.context) \
            if args.get("context") else client
        try:
            if operation == "scale":
                return {"result": await c.scale(
                    target, int(args["replicas"]), ns)}
            if operation == "rollout_restart":
                return {"result": await c.rollout_restart(target, ns)}
            if operation == "rollout_undo":
                return {"result": await c.rollout_undo(target, ns)}
            return {"result": await c.delete_pod(target, ns)}
        except Exception as exc:  # noqa: BLE001
            return {"error": f"{type(exc).__name__}: {exc}"}

    reg.define(
        "kubernetes_mutate",
        "Kubernetes mutations via kubectl, gated through the safety/approval "
        "flow. operation: scale|rollout_restart|rollout_undo|delete_pod. "
        "Provide name (deployment or pod), namespace, replicas (scale), and "
        "a rollback hint.",
        object_schema({"operation": {"type": "string"},
                       "name": {"type": "string"},
                       "namespace": {"type": "string"},
                       "replicas": {"type": "number"},
                       "context": {"type": "string"},
                       "rollback": {"type": "string"}},
                      ["operation", "name"]),
        kubernetes_mutate, category="kubernetes", risk=RiskLevel.HIGH,
    )
