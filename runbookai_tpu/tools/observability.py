"""Observability tools: Datadog and Prometheus HTTP clients.

Parity targets: reference ``src/tools/observability/datadog.ts`` (:93-560 —
action-dispatch tool: metrics, logs, traces, monitors, events, services) and
``prometheus.ts`` (:116-315 — instant/range PromQL, firing alerts, target
health, quick health check, COMMON_QUERIES).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

from runbookai_tpu.tools.registry import ToolRegistry, object_schema


def _http_get(url: str, headers: dict[str, str], params: dict[str, Any],
              timeout: float = 20.0) -> Any:
    import requests

    resp = requests.get(url, headers=headers, params=params, timeout=timeout)
    resp.raise_for_status()
    return resp.json()


class DatadogClient:
    def __init__(self, api_key: str, app_key: str, site: str = "datadoghq.com"):
        self.base = f"https://api.{site}/api"
        self.headers = {"DD-API-KEY": api_key, "DD-APPLICATION-KEY": app_key}

    async def _get(self, path: str, params: dict[str, Any]) -> Any:
        return await asyncio.to_thread(
            _http_get, f"{self.base}{path}", self.headers, params)

    async def metrics(self, query: str, minutes_back: float = 60) -> Any:
        now = int(time.time())
        return await self._get("/v1/query", {
            "query": query, "from": now - int(minutes_back * 60), "to": now})

    async def logs(self, query: str, minutes_back: float = 60, limit: int = 50) -> Any:
        import requests

        def call():
            resp = requests.post(
                f"{self.base}/v2/logs/events/search",
                headers={**self.headers, "Content-Type": "application/json"},
                json={"filter": {"query": query,
                                 "from": f"now-{int(minutes_back)}m", "to": "now"},
                      "page": {"limit": limit}},
                timeout=20)
            resp.raise_for_status()
            return resp.json()

        return await asyncio.to_thread(call)

    async def monitors(self) -> Any:
        return await self._get("/v1/monitor", {})

    async def events(self, minutes_back: float = 120) -> Any:
        now = int(time.time())
        return await self._get("/v1/events", {
            "start": now - int(minutes_back * 60), "end": now})

    async def traces(self, query: str, minutes_back: float = 60) -> Any:
        return await self._get("/v2/spans/events", {
            "filter[query]": query, "filter[from]": f"now-{int(minutes_back)}m",
            "filter[to]": "now", "page[limit]": 25})

    async def services(self) -> Any:
        return await self._get("/v2/services/definitions", {})


# Useful canned PromQL (reference prometheus.ts COMMON_QUERIES).
PROM_COMMON_QUERIES = {
    "cpu": 'sum(rate(container_cpu_usage_seconds_total[5m])) by (pod)',
    "memory": 'sum(container_memory_working_set_bytes) by (pod)',
    "error_rate": 'sum(rate(http_requests_total{status=~"5.."}[5m])) by (service)',
    "p99_latency": 'histogram_quantile(0.99, sum(rate(http_request_duration_seconds_bucket[5m])) by (le, service))',
    "up": "up",
}


class PrometheusClient:
    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    async def _get(self, path: str, params: dict[str, Any]) -> Any:
        return await asyncio.to_thread(_http_get, f"{self.base}{path}", {}, params)

    async def query(self, promql: str) -> Any:
        return await self._get("/api/v1/query", {"query": promql})

    async def query_range(self, promql: str, minutes_back: float = 60,
                          step: str = "60s") -> Any:
        now = time.time()
        return await self._get("/api/v1/query_range", {
            "query": promql, "start": now - minutes_back * 60, "end": now,
            "step": step})

    async def alerts(self) -> Any:
        return await self._get("/api/v1/alerts", {})

    async def targets(self) -> Any:
        return await self._get("/api/v1/targets", {"state": "active"})

    async def health_check(self) -> dict[str, Any]:
        """Quick health: firing alerts + down targets (prometheus.ts)."""
        alerts = await self.alerts()
        targets = await self.targets()
        firing = [a for a in alerts.get("data", {}).get("alerts", [])
                  if a.get("state") == "firing"]
        down = [t for t in targets.get("data", {}).get("activeTargets", [])
                if t.get("health") != "up"]
        return {"firing_alerts": len(firing), "down_targets": len(down),
                "alerts": firing[:10], "targets_down": down[:10]}


def register(reg: ToolRegistry, config) -> None:
    obs = config.observability
    if obs.datadog.enabled and not obs.datadog.simulated:
        dd = DatadogClient(obs.datadog.api_key or "", obs.datadog.app_key or "",
                           obs.datadog.site)

        async def datadog(args):
            action = str(args.get("action", "metrics"))
            try:
                if action == "metrics":
                    return await dd.metrics(str(args.get("query", "")),
                                            float(args.get("minutes_back", 60)))
                if action == "logs":
                    return await dd.logs(str(args.get("query", "")),
                                         float(args.get("minutes_back", 60)))
                if action == "monitors":
                    return await dd.monitors()
                if action == "events":
                    return await dd.events(float(args.get("minutes_back", 120)))
                if action == "traces":
                    return await dd.traces(str(args.get("query", "")))
                if action == "services":
                    return await dd.services()
                return {"error": f"unknown action {action!r}",
                        "available": ["metrics", "logs", "monitors", "events",
                                      "traces", "services"]}
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        reg.define(
            "datadog",
            "Datadog queries. action: metrics|logs|monitors|events|traces|services.",
            object_schema({"action": {"type": "string"},
                           "query": {"type": "string"},
                           "minutes_back": {"type": "number"}}, ["action"]),
            datadog, category="observability",
        )

    if obs.prometheus.enabled and not obs.prometheus.simulated:
        prom = PrometheusClient(obs.prometheus.base_url or "http://localhost:9090")

        async def prometheus(args):
            action = str(args.get("action", "query"))
            q = str(args.get("query", ""))
            q = PROM_COMMON_QUERIES.get(q, q)
            try:
                if action == "query":
                    return await prom.query(q)
                if action == "query_range":
                    return await prom.query_range(
                        q, float(args.get("minutes_back", 60)))
                if action == "alerts":
                    return await prom.alerts()
                if action == "targets":
                    return await prom.targets()
                if action == "health":
                    return await prom.health_check()
                return {"error": f"unknown action {action!r}",
                        "available": ["query", "query_range", "alerts",
                                      "targets", "health"],
                        "common_queries": sorted(PROM_COMMON_QUERIES)}
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        reg.define(
            "prometheus",
            "Prometheus queries. action: query|query_range|alerts|targets|health; "
            f"query accepts PromQL or a common-query name {sorted(PROM_COMMON_QUERIES)}.",
            object_schema({"action": {"type": "string"},
                           "query": {"type": "string"},
                           "minutes_back": {"type": "number"}}, ["action"]),
            prometheus, category="observability",
        )
