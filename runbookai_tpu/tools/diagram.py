"""Terminal visualization tools: ASCII charts and diagram renderers.

Parity target: reference ``src/tools/diagram/charts.ts`` (asciichart
line/bar/gauge/sparkline/histogram :31-119) and ``mermaid.ts`` (mermaid →
ASCII flowchart/sequence renderers :238-516). The system prompt mandates
visualizing numeric series (reference prompts.ts:128-207), so these tools are
always registered.
"""

from __future__ import annotations

from typing import Any

from runbookai_tpu.tools.registry import ToolRegistry, object_schema

SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(SPARK_CHARS[int((v - lo) / span * (len(SPARK_CHARS) - 1))] for v in values)


def line_chart(values: list[float], height: int = 10, label: str = "") -> str:
    """asciichart-style plot with a y-axis."""
    if not values:
        return "(no data)"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    rows = []
    for level in range(height, -1, -1):
        threshold = lo + span * level / height
        axis = f"{threshold:>10.2f} ┤"
        line = []
        for i, v in enumerate(values):
            cur = round((v - lo) / span * height)
            prev = round((values[i - 1] - lo) / span * height) if i else cur
            if cur == level:
                line.append("╰" if prev > cur else ("╭" if prev < cur else "─"))
            elif min(prev, cur) < level < max(prev, cur):
                line.append("│")
            else:
                line.append(" ")
        rows.append(axis + "".join(line))
    out = "\n".join(rows)
    return f"{label}\n{out}" if label else out


def bar_chart(items: list[tuple[str, float]], width: int = 40) -> str:
    if not items:
        return "(no data)"
    hi = max(abs(v) for _, v in items) or 1.0
    label_w = min(24, max(len(str(k)) for k, _ in items))
    lines = []
    for k, v in items:
        bar = "█" * max(1, int(abs(v) / hi * width)) if v else ""
        lines.append(f"{str(k)[:label_w]:<{label_w}} │{bar} {v:g}")
    return "\n".join(lines)


def gauge(value: float, lo: float = 0.0, hi: float = 100.0, width: int = 30,
          label: str = "") -> str:
    frac = 0.0 if hi == lo else max(0.0, min(1.0, (value - lo) / (hi - lo)))
    filled = int(frac * width)
    return f"{label} [{'█' * filled}{'░' * (width - filled)}] {value:g}/{hi:g}"


def histogram(values: list[float], bins: int = 10, width: int = 30) -> str:
    if not values:
        return "(no data)"
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    counts = [0] * bins
    for v in values:
        counts[min(bins - 1, int((v - lo) / span * bins))] += 1
    peak = max(counts) or 1
    lines = []
    for i, c in enumerate(counts):
        start = lo + span * i / bins
        lines.append(f"{start:>10.2f} │{'█' * int(c / peak * width)} {c}")
    return "\n".join(lines)


# --------------------------------------------------------------------------- #
# mermaid-ish diagram rendering                                               #
# --------------------------------------------------------------------------- #


def render_flowchart(nodes: list[dict[str, Any]], edges: list[dict[str, Any]]) -> str:
    """Vertical boxes-and-arrows flowchart."""
    by_id = {str(n["id"]): str(n.get("label", n["id"])) for n in nodes}
    out_edges: dict[str, list[tuple[str, str]]] = {}
    indegree = {nid: 0 for nid in by_id}
    for e in edges:
        src, dst = str(e["from"]), str(e["to"])
        out_edges.setdefault(src, []).append((dst, str(e.get("label", ""))))
        if dst in indegree:
            indegree[dst] += 1
    order: list[str] = []
    frontier = [n for n, d in indegree.items() if d == 0] or list(by_id)
    seen = set()
    while frontier:
        cur = frontier.pop(0)
        if cur in seen:
            continue
        seen.add(cur)
        order.append(cur)
        for dst, _ in out_edges.get(cur, []):
            if dst not in seen:
                frontier.append(dst)
    for nid in by_id:
        if nid not in seen:
            order.append(nid)

    lines = []
    for i, nid in enumerate(order):
        label = by_id.get(nid, nid)
        box_w = len(label) + 4
        lines.append("┌" + "─" * (box_w - 2) + "┐")
        lines.append(f"│ {label} │")
        lines.append("└" + "─" * (box_w - 2) + "┘")
        for dst, elabel in out_edges.get(nid, []):
            arrow = f"  │ {elabel}" if elabel else "  │"
            lines.append(arrow)
            lines.append(f"  ▼ → {by_id.get(dst, dst)}")
    return "\n".join(lines)


def render_sequence(actors: list[str], messages: list[dict[str, Any]]) -> str:
    if not actors:
        return "(no actors)"
    col_w = max(14, max(len(a) for a in actors) + 4)
    header = "".join(f"{a:^{col_w}}" for a in actors)
    lines = [header, "".join(f"{'│':^{col_w}}" for _ in actors)]
    idx = {a: i for i, a in enumerate(actors)}
    for msg in messages:
        src, dst = idx.get(str(msg.get("from"))), idx.get(str(msg.get("to")))
        text = str(msg.get("label", ""))[: col_w * 2]
        if src is None or dst is None:
            continue
        lo, hi = sorted((src, dst))
        span = (hi - lo) * col_w - 1
        arrow = ("─" * (span - 1) + (">" if dst > src else "")) if dst != src else "─┐"
        if dst < src:
            arrow = "<" + "─" * (span - 1)
        pad = lo * col_w + col_w // 2 + 1
        lines.append(" " * pad + arrow)
        lines.append(" " * pad + text)
    return "\n".join(lines)


def register(reg: ToolRegistry) -> None:
    async def visualize_metrics(args):
        kind = args.get("chart", "line")
        title = args.get("title", "")
        if kind == "line":
            values = [float(v) for v in args.get("values", [])]
            return {"chart": line_chart(values, label=title),
                    "sparkline": sparkline(values)}
        if kind == "sparkline":
            return {"chart": sparkline([float(v) for v in args.get("values", [])])}
        if kind == "bar":
            items = [(str(i.get("label", "?")), float(i.get("value", 0)))
                     for i in args.get("items", [])]
            return {"chart": bar_chart(items)}
        if kind == "gauge":
            return {"chart": gauge(float(args.get("value", 0)),
                                   float(args.get("min", 0)),
                                   float(args.get("max", 100)), label=title)}
        if kind == "histogram":
            return {"chart": histogram([float(v) for v in args.get("values", [])])}
        return {"error": f"unknown chart kind {kind!r}",
                "available": ["line", "sparkline", "bar", "gauge", "histogram"]}

    async def generate_flowchart(args):
        return {"diagram": render_flowchart(args.get("nodes", []), args.get("edges", []))}

    async def generate_sequence_diagram(args):
        return {"diagram": render_sequence(args.get("actors", []),
                                           args.get("messages", []))}

    async def generate_architecture_diagram(args):
        # Architecture view = flowchart of services with dependency edges.
        nodes = [{"id": s, "label": s} for s in args.get("services", [])]
        edges = [{"from": d.get("from"), "to": d.get("to"),
                  "label": d.get("label", "depends on")}
                 for d in args.get("dependencies", [])]
        return {"diagram": render_flowchart(nodes, edges)}

    reg.define(
        "visualize_metrics",
        "Render numeric data as a terminal chart. chart: line|sparkline|bar|"
        "gauge|histogram; values: number[] (line/sparkline/histogram); "
        "items: {label,value}[] (bar); value/min/max (gauge).",
        object_schema({"chart": {"type": "string"}, "title": {"type": "string"},
                       "values": {"type": "array"}, "items": {"type": "array"},
                       "value": {"type": "number"}, "min": {"type": "number"},
                       "max": {"type": "number"}}, ["chart"]),
        visualize_metrics, category="diagram",
    )
    reg.define(
        "generate_flowchart",
        "Render an ASCII flowchart. nodes: {id,label}[]; edges: {from,to,label}[].",
        object_schema({"nodes": {"type": "array"}, "edges": {"type": "array"}},
                      ["nodes"]),
        generate_flowchart, category="diagram",
    )
    reg.define(
        "generate_sequence_diagram",
        "Render an ASCII sequence diagram. actors: string[]; messages: {from,to,label}[].",
        object_schema({"actors": {"type": "array"}, "messages": {"type": "array"}},
                      ["actors"]),
        generate_sequence_diagram, category="diagram",
    )
    async def render_mermaid(args):
        from runbookai_tpu.tools.mermaid import detect_diagram_type, mermaid_to_ascii

        code = str(args.get("code", ""))
        return {"type": detect_diagram_type(code),
                "diagram": mermaid_to_ascii(code)}

    reg.define(
        "generate_architecture_diagram",
        "Render a service architecture diagram. services: string[]; "
        "dependencies: {from,to,label}[].",
        object_schema({"services": {"type": "array"},
                       "dependencies": {"type": "array"}}, ["services"]),
        generate_architecture_diagram, category="diagram",
    )
    reg.define(
        "render_mermaid",
        "Render mermaid source (graph/flowchart, sequenceDiagram, "
        "stateDiagram) as an ASCII terminal diagram.",
        object_schema({"code": {"type": "string"}}, ["code"]),
        render_mermaid, category="diagram",
    )
