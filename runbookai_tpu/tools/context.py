"""Context drill-down tools: recover cleared/compacted tool results.

Parity target: reference ``src/tools/registry.ts`` ``get_full_result`` (:3081)
/ ``list_results`` (:3143) with ``setActiveScratchpad`` (:3072). These close
the loop on tiered storage: the agent can always retrieve the full payload of
a result whose in-context tier was degraded.
"""

from __future__ import annotations

from typing import Optional

from runbookai_tpu.agent.scratchpad import Scratchpad
from runbookai_tpu.tools.registry import ToolRegistry, object_schema

_active: Optional[Scratchpad] = None


def set_active_scratchpad(pad: Optional[Scratchpad]) -> None:
    global _active
    _active = pad


def get_active_scratchpad() -> Optional[Scratchpad]:
    return _active


def register(reg: ToolRegistry) -> None:
    async def get_full_result(args):
        if _active is None:
            return {"error": "no active session"}
        entry = _active.get_result_by_id(str(args.get("result_id", "")))
        if entry is None:
            return {"error": f"unknown result_id {args.get('result_id')!r}",
                    "available": [r["result_id"] for r in _active.list_results()]}
        return {"result_id": entry.result_id, "tool": entry.tool,
                "args": entry.args, "result": entry.full, "error": entry.error}

    async def list_results(args):
        if _active is None:
            return {"error": "no active session"}
        return {"results": _active.list_results()}

    reg.define(
        "get_full_result",
        "Retrieve the full stored payload of a previous tool result by its "
        "result_id (results may be compacted or cleared from context).",
        object_schema({"result_id": {"type": "string"}}, ["result_id"]),
        get_full_result, category="context",
    )
    reg.define(
        "list_results",
        "List all tool results from this session with their storage tier and summaries.",
        object_schema({}),
        list_results, category="context",
    )
