"""Incident tools: PagerDuty, Opsgenie, Slack (REST).

Parity targets: reference ``src/tools/incident/pagerduty.ts`` (:145-313),
``opsgenie.ts`` (:88-263 — get/list alert, get/list incident, add note, ack,
close), ``slack.ts`` (:72+ Block Kit posts: updates, root-cause summaries,
thread reads).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from runbookai_tpu.agent.types import RiskLevel
from runbookai_tpu.tools.registry import ToolRegistry, object_schema


def _request(method: str, url: str, headers: dict[str, str],
             json_body: Optional[dict] = None, params: Optional[dict] = None,
             timeout: float = 20.0) -> Any:
    import requests

    resp = requests.request(method, url, headers=headers, json=json_body,
                            params=params, timeout=timeout)
    resp.raise_for_status()
    return resp.json() if resp.content else {}


class PagerDutyClient:
    def __init__(self, api_key: str):
        self.headers = {"Authorization": f"Token token={api_key}",
                        "Content-Type": "application/json"}
        self.base = "https://api.pagerduty.com"

    async def get_incident(self, incident_id: str) -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base}/incidents/{incident_id}", self.headers)

    async def list_incidents(self, status: Optional[str] = None) -> Any:
        params = {"statuses[]": status} if status else {}
        return await asyncio.to_thread(
            _request, "GET", f"{self.base}/incidents", self.headers, None, params)

    async def add_note(self, incident_id: str, content: str, email: str) -> Any:
        return await asyncio.to_thread(
            _request, "POST", f"{self.base}/incidents/{incident_id}/notes",
            {**self.headers, "From": email},
            {"note": {"content": content}})


class OpsgenieClient:
    def __init__(self, api_key: str):
        self.headers = {"Authorization": f"GenieKey {api_key}",
                        "Content-Type": "application/json"}
        self.base = "https://api.opsgenie.com/v2"
        self.base_v1 = "https://api.opsgenie.com/v1"

    async def get_alert(self, alert_id: str) -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base}/alerts/{alert_id}", self.headers)

    async def list_alerts(self, query: str = "") -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base}/alerts", self.headers, None,
            {"query": query} if query else {})

    async def get_incident(self, incident_id: str) -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base_v1}/incidents/{incident_id}", self.headers)

    async def list_incidents(self, query: str = "") -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base_v1}/incidents", self.headers, None,
            {"query": query} if query else {})

    async def add_note(self, alert_id: str, note: str) -> Any:
        return await asyncio.to_thread(
            _request, "POST", f"{self.base}/alerts/{alert_id}/notes",
            self.headers, {"note": note})

    async def acknowledge(self, alert_id: str) -> Any:
        return await asyncio.to_thread(
            _request, "POST", f"{self.base}/alerts/{alert_id}/acknowledge",
            self.headers, {})

    async def close(self, alert_id: str) -> Any:
        return await asyncio.to_thread(
            _request, "POST", f"{self.base}/alerts/{alert_id}/close",
            self.headers, {})


class SlackClient:
    def __init__(self, bot_token: str):
        self.headers = {"Authorization": f"Bearer {bot_token}",
                        "Content-Type": "application/json"}
        self.base = "https://slack.com/api"

    async def post_message(self, channel: str, text: str,
                           blocks: Optional[list] = None,
                           thread_ts: Optional[str] = None) -> Any:
        body: dict[str, Any] = {"channel": channel, "text": text[:39_000]}
        if blocks:
            body["blocks"] = blocks
        if thread_ts:
            body["thread_ts"] = thread_ts
        return await asyncio.to_thread(
            _request, "POST", f"{self.base}/chat.postMessage", self.headers, body)

    async def read_thread(self, channel: str, thread_ts: str) -> Any:
        return await asyncio.to_thread(
            _request, "GET", f"{self.base}/conversations.replies", self.headers,
            None, {"channel": channel, "ts": thread_ts})


def incident_update_blocks(title: str, status: str, details: str) -> list[dict]:
    """Block Kit incident update (reference slack.ts:126+)."""
    return [
        {"type": "header", "text": {"type": "plain_text", "text": title[:150]}},
        {"type": "section", "fields": [
            {"type": "mrkdwn", "text": f"*Status:*\n{status}"},
        ]},
        {"type": "section", "text": {"type": "mrkdwn", "text": details[:2900]}},
    ]


def root_cause_blocks(root_cause: str, confidence: str, services: list[str],
                      remediation: list[str]) -> list[dict]:
    blocks = [
        {"type": "header", "text": {"type": "plain_text", "text": "Root cause identified"}},
        {"type": "section", "text": {"type": "mrkdwn",
                                     "text": f"*Root cause:* {root_cause[:2800]}"}},
        {"type": "section", "fields": [
            {"type": "mrkdwn", "text": f"*Confidence:*\n{confidence}"},
            {"type": "mrkdwn", "text": f"*Services:*\n{', '.join(services)[:500]}"},
        ]},
    ]
    if remediation:
        steps = "\n".join(f"{i+1}. {s}" for i, s in enumerate(remediation[:8]))
        blocks.append({"type": "section",
                       "text": {"type": "mrkdwn",
                                "text": f"*Remediation:*\n{steps[:2900]}"}})
    return blocks


def register(reg: ToolRegistry, config) -> None:
    inc = config.incident

    if inc.pagerduty.enabled and not inc.pagerduty.simulated:
        pd = PagerDutyClient(inc.pagerduty.api_key or "")

        async def pd_get(args):
            try:
                return await pd.get_incident(str(args.get("incident_id", "")))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        async def pd_list(args):
            try:
                return await pd.list_incidents(args.get("status"))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        async def pd_note(args):
            try:
                return await pd.add_note(str(args.get("incident_id", "")),
                                         str(args.get("content", "")),
                                         str(args.get("from_email", "runbook@local")))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        reg.define("pagerduty_get_incident", "Fetch a PagerDuty incident by id.",
                   object_schema({"incident_id": {"type": "string"}}, ["incident_id"]),
                   pd_get, category="incident")
        reg.define("pagerduty_list_incidents",
                   "List PagerDuty incidents (status: triggered|acknowledged|resolved).",
                   object_schema({"status": {"type": "string"}}),
                   pd_list, category="incident")
        reg.define("pagerduty_add_note", "Add a note to a PagerDuty incident.",
                   object_schema({"incident_id": {"type": "string"},
                                  "content": {"type": "string"}},
                                 ["incident_id", "content"]),
                   pd_note, category="incident", risk=RiskLevel.LOW)

    if inc.opsgenie.enabled and not inc.opsgenie.simulated:
        og = OpsgenieClient(inc.opsgenie.api_key or "")

        def wrap(coro_fn):
            async def inner(args):
                try:
                    return await coro_fn(args)
                except Exception as exc:  # noqa: BLE001
                    return {"error": f"{type(exc).__name__}: {exc}"}

            return inner

        reg.define("opsgenie_get_alert", "Fetch an Opsgenie alert by id.",
                   object_schema({"alert_id": {"type": "string"}}, ["alert_id"]),
                   wrap(lambda a: og.get_alert(str(a.get("alert_id", "")))),
                   category="incident")
        reg.define("opsgenie_list_alerts", "List Opsgenie alerts (optional query).",
                   object_schema({"query": {"type": "string"}}),
                   wrap(lambda a: og.list_alerts(str(a.get("query", "")))),
                   category="incident")
        reg.define("opsgenie_get_incident", "Fetch an Opsgenie incident by id.",
                   object_schema({"incident_id": {"type": "string"}}, ["incident_id"]),
                   wrap(lambda a: og.get_incident(str(a.get("incident_id", "")))),
                   category="incident")
        reg.define("opsgenie_list_incidents", "List Opsgenie incidents.",
                   object_schema({"query": {"type": "string"}}),
                   wrap(lambda a: og.list_incidents(str(a.get("query", "")))),
                   category="incident")
        reg.define("opsgenie_add_note", "Add a note to an Opsgenie alert.",
                   object_schema({"alert_id": {"type": "string"},
                                  "note": {"type": "string"}}, ["alert_id", "note"]),
                   wrap(lambda a: og.add_note(str(a.get("alert_id", "")),
                                              str(a.get("note", "")))),
                   category="incident", risk=RiskLevel.LOW)
        reg.define("opsgenie_acknowledge_alert", "Acknowledge an Opsgenie alert.",
                   object_schema({"alert_id": {"type": "string"}}, ["alert_id"]),
                   wrap(lambda a: og.acknowledge(str(a.get("alert_id", "")))),
                   category="incident", risk=RiskLevel.LOW)
        reg.define("opsgenie_close_alert", "Close an Opsgenie alert.",
                   object_schema({"alert_id": {"type": "string"}}, ["alert_id"]),
                   wrap(lambda a: og.close(str(a.get("alert_id", "")))),
                   category="incident", risk=RiskLevel.HIGH)

    if inc.slack.enabled and inc.slack.bot_token:
        slack = SlackClient(inc.slack.bot_token)
        default_channel = inc.slack.default_channel or ""

        async def slack_post_update(args):
            try:
                return await slack.post_message(
                    str(args.get("channel") or default_channel),
                    str(args.get("text", "")),
                    blocks=incident_update_blocks(
                        str(args.get("title", "Incident update")),
                        str(args.get("status", "investigating")),
                        str(args.get("text", ""))),
                    thread_ts=args.get("thread_ts"))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        async def slack_post_root_cause(args):
            try:
                return await slack.post_message(
                    str(args.get("channel") or default_channel),
                    f"Root cause: {args.get('root_cause', '')}",
                    blocks=root_cause_blocks(
                        str(args.get("root_cause", "")),
                        str(args.get("confidence", "medium")),
                        [str(s) for s in args.get("services", [])],
                        [str(s) for s in args.get("remediation", [])]),
                    thread_ts=args.get("thread_ts"))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        async def slack_read_thread(args):
            try:
                return await slack.read_thread(str(args.get("channel", "")),
                                               str(args.get("thread_ts", "")))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        async def slack_message(args):
            try:
                return await slack.post_message(
                    str(args.get("channel") or default_channel),
                    str(args.get("text", "")), thread_ts=args.get("thread_ts"))
            except Exception as exc:  # noqa: BLE001
                return {"error": f"{type(exc).__name__}: {exc}"}

        reg.define("slack_post_update", "Post a formatted incident update to Slack.",
                   object_schema({"channel": {"type": "string"},
                                  "title": {"type": "string"},
                                  "status": {"type": "string"},
                                  "text": {"type": "string"},
                                  "thread_ts": {"type": "string"}}, ["text"]),
                   slack_post_update, category="incident", risk=RiskLevel.LOW)
        reg.define("slack_post_root_cause",
                   "Post a root-cause summary with remediation to Slack.",
                   object_schema({"channel": {"type": "string"},
                                  "root_cause": {"type": "string"},
                                  "confidence": {"type": "string"},
                                  "services": {"type": "array"},
                                  "remediation": {"type": "array"},
                                  "thread_ts": {"type": "string"}}, ["root_cause"]),
                   slack_post_root_cause, category="incident", risk=RiskLevel.LOW)
        reg.define("slack_read_thread", "Read a Slack thread's messages.",
                   object_schema({"channel": {"type": "string"},
                                  "thread_ts": {"type": "string"}},
                                 ["channel", "thread_ts"]),
                   slack_read_thread, category="incident")
        reg.define("slack_message", "Post a plain message to Slack.",
                   object_schema({"channel": {"type": "string"},
                                  "text": {"type": "string"},
                                  "thread_ts": {"type": "string"}}, ["text"]),
                   slack_message, category="incident", risk=RiskLevel.LOW)
