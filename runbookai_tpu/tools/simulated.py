"""Fixture-backed simulated providers — run the full agent without cloud creds.

SURVEY.md §7 step 4 calls for a simulated provider set so ``runbook ask`` and
the eval suite run end-to-end on TPU with no AWS/K8s/SaaS credentials. The
default scenario is a payment-api latency incident (bad deployment shrank the
DB connection pool) exercising the same signal chain the reference demo data
models (``src/demo/demo-data.ts``): PagerDuty incident → CloudWatch alarms →
logs with pool-exhaustion errors → deployment event → pod restarts.

Custom scenarios load from ``providers.aws.fixtures_path`` (JSON with the same
top-level keys as ``DEFAULT_FIXTURES``).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Optional

from runbookai_tpu.agent.types import RiskLevel
from runbookai_tpu.tools.registry import ToolRegistry, object_schema


def _ts(minutes_ago: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() - minutes_ago * 60))


def default_fixtures() -> dict[str, Any]:
    return {
        "aws": {
            "ecs": [
                {"service": "payment-api", "status": "ACTIVE", "runningCount": 2,
                 "desiredCount": 4, "pendingCount": 2,
                 "deployments": [{"id": "ecs-svc/9371", "status": "PRIMARY",
                                  "createdAt": _ts(42), "taskDefinition": "payment-api:57"},
                                 {"id": "ecs-svc/9368", "status": "DRAINING",
                                  "taskDefinition": "payment-api:56"}]},
                {"service": "checkout-web", "status": "ACTIVE", "runningCount": 3,
                 "desiredCount": 3, "pendingCount": 0},
                {"service": "inventory-service", "status": "ACTIVE", "runningCount": 2,
                 "desiredCount": 2, "pendingCount": 0},
            ],
            "rds": [
                {"dbInstance": "payments-db", "engine": "postgres", "status": "available",
                 "maxConnections": 100, "currentConnections": 98,
                 "cpuUtilization": 41.0, "freeStorageGb": 212.5},
            ],
            "lambda": [
                {"functionName": "payment-webhook-processor", "state": "Active",
                 "lastModified": _ts(42), "timeout": 30, "memorySize": 256,
                 "errors24h": 310},
            ],
            "ec2": [
                {"instanceId": "i-0a1b2c3d", "state": "running", "type": "m5.large",
                 "name": "bastion"},
            ],
        },
        "cloudwatch_alarms": [
            {"alarmName": "payment-api-p99-latency", "state": "ALARM",
             "metric": "TargetResponseTime", "threshold": 1.5,
             "currentValue": 4.82, "stateChangedAt": _ts(38),
             "service": "payment-api"},
            {"alarmName": "payments-db-connections", "state": "ALARM",
             "metric": "DatabaseConnections", "threshold": 90,
             "currentValue": 98, "stateChangedAt": _ts(35), "service": "payments-db"},
            {"alarmName": "checkout-web-5xx", "state": "OK",
             "metric": "HTTPCode_Target_5XX_Count", "threshold": 25,
             "currentValue": 3, "service": "checkout-web"},
        ],
        "cloudwatch_logs": {
            "/ecs/payment-api": [
                {"ts": _ts(36), "level": "ERROR",
                 "message": "HikariPool-1 - Connection is not available, request timed out after 30000ms (total=20, active=20, idle=0, waiting=142)"},
                {"ts": _ts(35), "level": "ERROR",
                 "message": "org.postgresql.util.PSQLException: FATAL: remaining connection slots are reserved"},
                {"ts": _ts(34), "level": "WARN",
                 "message": "payment request latency 4831ms exceeds SLO 1500ms for /v2/charge"},
                {"ts": _ts(30), "level": "ERROR",
                 "message": "timeout acquiring connection from pool: pool size 20 (was 50 before deploy payment-api:57)"},
            ],
            "/aws/lambda/payment-webhook-processor": [
                {"ts": _ts(33), "level": "ERROR",
                 "message": "Task timed out after 30.03 seconds while calling payment-api /v2/charge"},
            ],
        },
        "kubernetes": {
            "pods": [
                {"name": "payment-api-6d9f7c-x2lq4", "namespace": "prod",
                 "status": "Running", "restarts": 6, "age": "41m",
                 "containers": [{"name": "app", "ready": True}]},
                {"name": "payment-api-6d9f7c-9kzzn", "namespace": "prod",
                 "status": "CrashLoopBackOff", "restarts": 11, "age": "41m",
                 "containers": [{"name": "app", "ready": False}]},
                {"name": "checkout-web-7b4d9-aaaa1", "namespace": "prod",
                 "status": "Running", "restarts": 0, "age": "6d"},
            ],
            "deployments": [
                {"name": "payment-api", "namespace": "prod", "replicas": "2/4",
                 "updatedAt": _ts(42), "image": "payment-api:2.31.0"},
                {"name": "checkout-web", "namespace": "prod", "replicas": "3/3",
                 "image": "checkout-web:1.9.2"},
            ],
            "events": [
                {"ts": _ts(41), "type": "Normal", "reason": "ScalingReplicaSet",
                 "object": "deployment/payment-api",
                 "message": "Scaled up replica set payment-api-6d9f7c to 4"},
                {"ts": _ts(36), "type": "Warning", "reason": "BackOff",
                 "object": "pod/payment-api-6d9f7c-9kzzn",
                 "message": "Back-off restarting failed container"},
            ],
            "nodes": [
                {"name": "node-1", "status": "Ready", "cpu": "61%", "memory": "72%"},
                {"name": "node-2", "status": "Ready", "cpu": "55%", "memory": "64%"},
            ],
        },
        "datadog": {
            "metrics": {
                "payment-api.request.latency.p99": {
                    "unit": "ms",
                    "points": [[_ts(60), 310], [_ts(50), 340], [_ts(45), 330],
                               [_ts(40), 2900], [_ts(30), 4400], [_ts(20), 4820],
                               [_ts(10), 4710]],
                },
                "payments-db.connections.active": {
                    "unit": "connections",
                    "points": [[_ts(60), 44], [_ts(50), 46], [_ts(40), 93],
                               [_ts(30), 98], [_ts(20), 98], [_ts(10), 97]],
                },
            },
            "events": [
                {"ts": _ts(42), "title": "Deployed payment-api v2.31.0",
                 "tags": ["service:payment-api", "env:prod", "deploy"],
                 "text": "config change: db pool max_size 50 -> 20 (PR #4312)"},
            ],
            "monitors": [
                {"name": "payment-api p99 latency", "status": "Alert",
                 "query": "avg(last_5m):p99:payment-api.request.latency > 1500"},
            ],
        },
        "prometheus": {
            "alerts": [
                {"name": "HighLatencyP99", "state": "firing",
                 "labels": {"service": "payment-api", "severity": "page"},
                 "activeAt": _ts(38)},
            ],
            "queries": {
                "up": [{"metric": {"job": "payment-api"}, "value": 1},
                       {"metric": {"job": "checkout-web"}, "value": 1}],
            },
        },
        "pagerduty": [
            {"id": "PD-12345", "title": "High p99 latency on payment-api",
             "status": "triggered", "urgency": "high", "createdAt": _ts(38),
             "service": "payment-api",
             "description": "p99 latency above 1.5s SLO for 10 minutes; "
                            "customer checkout failures reported",
             "notes": []},
        ],
        "github": {
            "payment-api": [
                {"number": 4312, "title": "Tune DB pool settings",
                 "mergedAt": _ts(55), "author": "dev-a",
                 "files": ["config/database.yaml"],
                 "diff_hint": "max_pool_size: 50 -> 20"},
            ],
        },
    }


class SimulatedCloud:
    """Holds the fixture state + mutation journal for simulated tools."""

    def __init__(self, fixtures: Optional[dict[str, Any]] = None):
        self.fixtures = fixtures or default_fixtures()
        self.mutations: list[dict[str, Any]] = []

    @classmethod
    def from_config(cls, config) -> "SimulatedCloud":
        path = getattr(config.providers.aws, "fixtures_path", None)
        if path and Path(path).is_file():
            return cls(json.loads(Path(path).read_text()))
        return cls()


# --------------------------------------------------------------------------- #
# registration helpers                                                        #
# --------------------------------------------------------------------------- #


def register_aws(reg: ToolRegistry, sim: SimulatedCloud) -> None:
    async def aws_query(args):
        service = args.get("service")
        aws = sim.fixtures["aws"]
        if service and service != "all":
            return {service: aws.get(service, []),
                    "note": None if service in aws else
                    f"no {service!r} resources; available: {sorted(aws)}"}
        return aws

    async def aws_mutate(args):
        record = {"operation": args.get("operation"), "service": args.get("service"),
                  "params": args.get("params", {}), "ts": time.time()}
        sim.mutations.append(record)
        return {"status": "applied", "simulated": True, **record}

    async def cloudwatch_alarms(args):
        state = args.get("state")
        alarms = sim.fixtures["cloudwatch_alarms"]
        if state:
            alarms = [a for a in alarms if a["state"] == state.upper()]
        return {"alarms": alarms}

    async def cloudwatch_logs(args):
        group = args.get("log_group", "")
        logs = sim.fixtures["cloudwatch_logs"]
        if group not in logs:
            return {"error": f"log group {group!r} not found",
                    "available": sorted(logs)}
        events = logs[group]
        pattern = (args.get("filter_pattern") or "").lower()
        if pattern:
            events = [e for e in events if pattern in e["message"].lower()]
        return {"log_group": group, "events": events}

    reg.define(
        "aws_query",
        "Query AWS resource inventory and state. service: one of "
        "ec2|ecs|rds|lambda|... or 'all'.",
        object_schema({"service": {"type": "string"},
                       "region": {"type": "string"}}),
        aws_query, category="aws",
    )
    reg.define(
        "aws_mutate",
        "Mutate AWS resources (scale service, restart task, update config). "
        "Requires approval; high risk.",
        object_schema({"operation": {"type": "string"},
                       "service": {"type": "string"},
                       "params": {"type": "object"}}, ["operation"]),
        aws_mutate, category="aws", risk=RiskLevel.HIGH,
    )
    reg.define(
        "cloudwatch_alarms",
        "List CloudWatch alarms, optionally filtered by state (ALARM|OK|INSUFFICIENT_DATA).",
        object_schema({"state": {"type": "string"}}),
        cloudwatch_alarms, category="aws",
    )
    reg.define(
        "cloudwatch_logs",
        "Fetch recent CloudWatch log events from a log group, with optional "
        "filter_pattern and minutes_back.",
        object_schema({"log_group": {"type": "string"},
                       "filter_pattern": {"type": "string"},
                       "minutes_back": {"type": "number"}}, ["log_group"]),
        cloudwatch_logs, category="aws",
    )


def register_kubernetes(reg: ToolRegistry, sim: SimulatedCloud) -> None:
    async def kubernetes_query(args):
        action = args.get("action", "pods")
        k8s = sim.fixtures["kubernetes"]
        if action in ("status", "cluster-info"):
            return {"nodes": k8s["nodes"], "healthy": all(
                n["status"] == "Ready" for n in k8s["nodes"])}
        if action in k8s:
            items = k8s[action]
            ns = args.get("namespace")
            if ns and isinstance(items, list):
                items = [i for i in items if i.get("namespace", ns) == ns]
            return {action: items}
        return {"error": f"unknown action {action!r}",
                "available": ["status", *sorted(k8s)]}

    reg.define(
        "kubernetes_query",
        "Read-only Kubernetes queries. action: status|pods|deployments|nodes|events.",
        object_schema({"action": {"type": "string"},
                       "namespace": {"type": "string"},
                       "context": {"type": "string"}}, ["action"]),
        kubernetes_query, category="kubernetes",
    )


def register_observability(reg: ToolRegistry, sim: SimulatedCloud, obs_cfg) -> None:
    async def datadog(args):
        action = args.get("action", "metrics")
        dd = sim.fixtures["datadog"]
        if action == "metrics":
            query = args.get("query", "")
            series = {k: v for k, v in dd["metrics"].items() if not query or query in k}
            return {"series": series or {"note": f"no series match {query!r}",
                                         "available": sorted(dd['metrics'])}}
        if action in dd:
            return {action: dd[action]}
        return {"error": f"unknown action {action!r}",
                "available": ["metrics", *sorted(dd)]}

    async def prometheus(args):
        action = args.get("action", "alerts")
        prom = sim.fixtures["prometheus"]
        if action == "alerts":
            return {"alerts": prom["alerts"]}
        if action in ("query", "query_range"):
            q = args.get("query", "up")
            return {"result": prom["queries"].get(q, []),
                    "query": q}
        return {"error": f"unknown action {action!r}"}

    if obs_cfg.datadog.enabled:
        reg.define(
            "datadog",
            "Datadog queries. action: metrics|events|monitors; query filters series.",
            object_schema({"action": {"type": "string"}, "query": {"type": "string"},
                           "minutes_back": {"type": "number"}}, ["action"]),
            datadog, category="observability",
        )
    if obs_cfg.prometheus.enabled:
        reg.define(
            "prometheus",
            "Prometheus queries. action: alerts|query|query_range with PromQL query.",
            object_schema({"action": {"type": "string"}, "query": {"type": "string"}},
                          ["action"]),
            prometheus, category="observability",
        )


def register_incident(reg: ToolRegistry, sim: SimulatedCloud, inc_cfg) -> None:
    def _find(incident_id: str) -> Optional[dict[str, Any]]:
        for inc in sim.fixtures["pagerduty"]:
            if inc["id"] == incident_id:
                return inc
        return None

    async def get_incident(args):
        inc = _find(args.get("incident_id", ""))
        return inc or {"error": f"incident {args.get('incident_id')!r} not found",
                       "known": [i["id"] for i in sim.fixtures["pagerduty"]]}

    async def list_incidents(args):
        status = args.get("status")
        items = sim.fixtures["pagerduty"]
        if status:
            items = [i for i in items if i["status"] == status]
        return {"incidents": items}

    async def add_note(args):
        inc = _find(args.get("incident_id", ""))
        if not inc:
            return {"error": "incident not found"}
        inc.setdefault("notes", []).append(
            {"ts": time.time(), "content": args.get("content", "")})
        return {"status": "ok", "notes": len(inc["notes"])}

    reg.define(
        "pagerduty_get_incident",
        "Fetch a PagerDuty incident by id (e.g. PD-12345).",
        object_schema({"incident_id": {"type": "string"}}, ["incident_id"]),
        get_incident, category="incident",
    )
    reg.define(
        "pagerduty_list_incidents",
        "List PagerDuty incidents, optionally by status (triggered|acknowledged|resolved).",
        object_schema({"status": {"type": "string"}}),
        list_incidents, category="incident",
    )
    reg.define(
        "pagerduty_add_note",
        "Add a note to a PagerDuty incident.",
        object_schema({"incident_id": {"type": "string"},
                       "content": {"type": "string"}}, ["incident_id", "content"]),
        add_note, category="incident", risk=RiskLevel.LOW,
    )


def register_code(reg: ToolRegistry, sim: SimulatedCloud) -> None:
    """Fixture-backed github_query (recent_prs / fix_candidates) — serves
    the ``github`` fixtures block (deploy-culprit PRs in generated
    incident scenarios; see simulate/generator.py)."""

    async def github_query(args):
        repos = sim.fixtures.get("github", {})
        action = args.get("action", "recent_prs")
        service = args.get("service") or args.get("repo") or ""
        keywords = [str(k).lower() for k in (args.get("keywords") or [])]
        out = []
        for repo, prs in repos.items():
            if service and service not in repo:
                continue
            for pr in prs:
                if action == "fix_candidates" and keywords:
                    hay = (pr.get("title", "") + " "
                           + pr.get("diff_hint", "")).lower()
                    if not any(k in hay for k in keywords):
                        continue
                out.append({"repo": repo, **pr})
        limit = int(args.get("limit") or 10)
        return {"action": action, "results": out[:limit]}

    reg.define(
        "github_query",
        "GitHub queries. action: recent_prs|recent_commits|fix_candidates "
        "(fix_candidates finds merged PRs matching incident keywords).",
        object_schema({"action": {"type": "string"}, "repo": {"type": "string"},
                       "keywords": {"type": "array"},
                       "service": {"type": "string"},
                       "limit": {"type": "number"}}, ["action"]),
        github_query, category="code",
    )


def register_triage(reg: ToolRegistry, sim: SimulatedCloud) -> None:
    """Cross-modality signal triage over the fixture providers.

    The pure logic lives in :mod:`runbookai_tpu.agent.signal_triage`;
    this adapter feeds it everything the fixture cloud knows. Real
    providers can reuse the same module by collecting the equivalent
    alarm/log/event lists from live queries."""

    async def signal_triage(args):
        from runbookai_tpu.agent.signal_triage import triage_signals

        fx = sim.fixtures
        incidents = fx.get("pagerduty") or []
        iid = args.get("incident_id")
        inc = next((i for i in incidents if i.get("id") == iid), None) \
            if iid else None
        inc = inc or (incidents[0] if incidents else {})
        rep = triage_signals(
            alarms=fx.get("cloudwatch_alarms", []),
            logs=fx.get("cloudwatch_logs", {}),
            dd_events=fx.get("datadog", {}).get("events", []),
            pods=fx.get("kubernetes", {}).get("pods", []),
            prom_alerts=fx.get("prometheus", {}).get("alerts", []),
            incident=inc,
            known_services=[e.get("service")
                            for e in fx.get("aws", {}).get("ecs", [])],
        )
        return {"report": rep.render(), "candidates": rep.candidates[:5],
                "modality_notes": rep.modality_notes}

    reg.define(
        "signal_triage",
        "Cross-modality signal triage: dates every alarm/log/event against "
        "the incident start (live vs stale vs recovered), builds the "
        "symptom graph, flags missing telemetry, and ranks root-cause "
        "candidate services. Run this FIRST when investigating.",
        object_schema({"incident_id": {"type": "string"}}),
        signal_triage, category="analysis",
    )
