"""Deep EKS / Amplify helpers: drill-down beyond the catalog rows.

Reference parity: ``src/tools/aws/eks.ts:71-360`` (clusters, node
groups, fargate profiles, cluster health) and ``amplify.ts:55-300``
(apps, branches, jobs, app health). The repo's generic catalog lists
top-level resources (``tools/aws.py``); these helpers add the
per-resource drill-down and the health roll-up an investigation
actually needs: WHICH node group is degraded, WHICH deploy job failed.

Built on the same :class:`~runbookai_tpu.tools.aws.AWSClientManager`
(profile / role-assumption / region); every call is read-only boto3.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

from runbookai_tpu.tools.aws import AWSClientManager


def _thread(fn):
    return asyncio.to_thread(fn)


# ------------------------------------------------------------------ EKS


async def eks_overview(manager: AWSClientManager,
                       region: Optional[str] = None,
                       cluster: Optional[str] = None) -> dict[str, Any]:
    """Clusters → node groups → fargate profiles with a health roll-up.

    Mirrors the reference's ``getAllClustersWithStatus`` +
    ``checkClusterHealth``: a cluster is unhealthy when its status is
    not ACTIVE, any node group is not ACTIVE, or a node group reports
    issues."""

    def call() -> dict[str, Any]:
        eks = manager.client("eks", region)
        names = ([cluster] if cluster
                 else eks.list_clusters().get("clusters", []))
        out = []
        for name in names[:20]:
            c = eks.describe_cluster(name=name).get("cluster", {})
            entry: dict[str, Any] = {
                "name": name,
                "status": c.get("status"),
                "version": c.get("version"),
                "endpoint_access": (c.get("resourcesVpcConfig") or {}).get(
                    "endpointPublicAccess"),
                "nodegroups": [],
                "fargate_profiles": [],
                "issues": [],
            }
            if c.get("status") != "ACTIVE":
                entry["issues"].append(
                    f"cluster status {c.get('status')}")
            for ng in eks.list_nodegroups(clusterName=name).get(
                    "nodegroups", [])[:20]:
                g = eks.describe_nodegroup(
                    clusterName=name, nodegroupName=ng).get("nodegroup", {})
                scaling = g.get("scalingConfig") or {}
                issues = [f"{i.get('code')}: {i.get('message', '')[:140]}"
                          for i in (g.get("health") or {}).get("issues", [])]
                entry["nodegroups"].append({
                    "name": ng, "status": g.get("status"),
                    "desired": scaling.get("desiredSize"),
                    "min": scaling.get("minSize"),
                    "max": scaling.get("maxSize"),
                    "instance_types": g.get("instanceTypes"),
                    "issues": issues,
                })
                if g.get("status") != "ACTIVE":
                    entry["issues"].append(
                        f"nodegroup {ng} status {g.get('status')}")
                entry["issues"].extend(
                    f"nodegroup {ng} {i}" for i in issues)
            for fp in eks.list_fargate_profiles(clusterName=name).get(
                    "fargateProfileNames", [])[:10]:
                p = eks.describe_fargate_profile(
                    clusterName=name, fargateProfileName=fp).get(
                        "fargateProfile", {})
                entry["fargate_profiles"].append(
                    {"name": fp, "status": p.get("status")})
            entry["healthy"] = not entry["issues"]
            out.append(entry)
        return {"clusters": out,
                "unhealthy": [c["name"] for c in out if not c["healthy"]]}

    return await _thread(call)


# -------------------------------------------------------------- Amplify


async def amplify_overview(manager: AWSClientManager,
                           region: Optional[str] = None,
                           app: Optional[str] = None,
                           jobs_per_branch: int = 5) -> dict[str, Any]:
    """Apps → branches → recent jobs with deploy-failure detection.

    Mirrors ``getAllAppsWithStatus`` + ``checkAppHealth``: an app is
    unhealthy when any branch's most recent job FAILED (the bad-deploy
    signature the investigation is usually chasing)."""

    def call() -> dict[str, Any]:
        amp = manager.client("amplify", region)
        apps = amp.list_apps().get("apps", [])
        if app:
            apps = [a for a in apps
                    if a.get("appId") == app or a.get("name") == app]
        out = []
        for a in apps[:20]:
            app_id = a.get("appId")
            entry: dict[str, Any] = {
                "app_id": app_id, "name": a.get("name"),
                "platform": a.get("platform"),
                "default_domain": a.get("defaultDomain"),
                "branches": [], "issues": [],
            }
            for br in amp.list_branches(appId=app_id).get(
                    "branches", [])[:10]:
                bname = br.get("branchName")
                jobs = amp.list_jobs(
                    appId=app_id, branchName=bname,
                    maxResults=jobs_per_branch).get("jobSummaries", [])
                recent = [{
                    "job_id": j.get("jobId"), "status": j.get("status"),
                    "type": j.get("jobType"),
                    "commit": (j.get("commitId") or "")[:10],
                    "started": str(j.get("startTime", ""))[:19],
                } for j in jobs]
                entry["branches"].append({
                    "name": bname, "stage": br.get("stage"),
                    "auto_build": br.get("enableAutoBuild"),
                    "recent_jobs": recent,
                })
                if recent and recent[0]["status"] == "FAILED":
                    entry["issues"].append(
                        f"branch {bname}: latest deploy job "
                        f"{recent[0]['job_id']} FAILED "
                        f"(commit {recent[0]['commit']})")
            entry["healthy"] = not entry["issues"]
            out.append(entry)
        return {"apps": out,
                "unhealthy": [x["name"] for x in out if not x["healthy"]]}

    return await _thread(call)


def register(reg, manager: AWSClientManager) -> None:
    """Register eks_query / amplify_query next to the generic aws tools."""
    from runbookai_tpu.tools.registry import object_schema

    async def eks_query(args):
        if not manager.available():
            return {"error": "boto3 is not installed; EKS drill-down "
                             "needs real AWS access"}
        return await eks_overview(manager, region=args.get("region"),
                                  cluster=args.get("cluster"))

    async def amplify_query(args):
        if not manager.available():
            return {"error": "boto3 is not installed; Amplify drill-down "
                             "needs real AWS access"}
        return await amplify_overview(manager, region=args.get("region"),
                                      app=args.get("app"))

    reg.define(
        "eks_query",
        "EKS drill-down: clusters -> node groups (scaling, health "
        "issues) -> fargate profiles, with an unhealthy-cluster roll-up.",
        object_schema({"cluster": {"type": "string"},
                       "region": {"type": "string"}}),
        eks_query, category="aws",
    )
    reg.define(
        "amplify_query",
        "Amplify drill-down: apps -> branches -> recent deploy jobs, "
        "flagging branches whose latest job FAILED.",
        object_schema({"app": {"type": "string"},
                       "region": {"type": "string"}}),
        amplify_query, category="aws",
    )
