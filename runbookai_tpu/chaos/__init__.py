"""Chaos hardening for the serving fleet (docs/robustness.md).

Two halves, composable but independent:

- :mod:`~runbookai_tpu.chaos.inject` — deterministic, seeded fault
  injection: :class:`FaultSchedule` (same seed ⇒ byte-identical plan)
  applied to a live fleet by :class:`ChaosInjector` through documented
  seams (``EngineCore.chaos_hook``, ``AsyncFleet.chaos_pull_hook``).
- :mod:`~runbookai_tpu.chaos.supervisor` — :class:`FleetSupervisor`:
  heartbeat-driven detection of dead/wedged replicas, in-flight
  failover through the router's retry path, online replica rebuild
  (``AsyncFleet.rebuild_replica``) and hysteresis-guarded rejoin.

The ``bench.py --soak-scenarios`` arm drives both against the full
composed stack and gates on production invariants (zero lost requests
outside fault windows, TTFT bounds, fairness, RSS/fd bounds, seeded
digest determinism) — the serving twin of tier-1.
"""

from runbookai_tpu.chaos.inject import (
    FAULT_KINDS,
    ChaosInjector,
    ChaosReplicaCrash,
    FaultEvent,
    FaultSchedule,
)
from runbookai_tpu.chaos.supervisor import SUPERVISOR_STATES, FleetSupervisor

__all__ = [
    "FAULT_KINDS",
    "SUPERVISOR_STATES",
    "ChaosInjector",
    "ChaosReplicaCrash",
    "FaultEvent",
    "FaultSchedule",
    "FleetSupervisor",
]
