"""Deterministic, seeded fault injection against a live engine fleet.

The serving platform now composes multi-model fleets, disaggregated
tiers, fleet-wide KV sharing, SLO scheduling and live workload
observation — but nothing ever *broke* it on purpose. This module is the
breaking half of the chaos story (AIBrix makes fault-tolerant replica
management a first-class serving-infrastructure concern; FlashInfer-
Bench's repeatable-harness discipline is why the schedule is seeded):

- :class:`FaultSchedule` is a pure function of ``(seed, duration, dp,
  kinds)``: the same seed produces a byte-identical schedule JSON
  (pinned by ``tests/test_chaos.py``), so a chaos soak is re-runnable
  evidence, not a flake generator.
- :class:`ChaosInjector` walks a schedule against a live
  :class:`~runbookai_tpu.engine.fleet.AsyncFleet`, applying each fault
  through documented seams — the ``EngineCore.chaos_hook`` step seam
  (crash / wedge / spill pressure run under the engine lock, before any
  pool mutation), the ``AsyncFleet.chaos_pull_hook`` page-transfer seam
  (d2d delay / corruption on the in-transit payload), and a caller-
  supplied flood handler — and records every applied window with
  provenance (``/healthz`` ``chaos`` block, ``runbook chaos status``).

Fault model (docs/robustness.md):

``replica_crash``
    The replica's next step raises: the AsyncEngine loop fails its live
    requests and dies — the supervisor's crash signal. One-shot.
``replica_wedge``
    The replica's step thread stalls inside step() (under the engine
    lock) for the window: heartbeats stop advancing while work queues —
    the supervisor's wedge signal.
``kv_pull_delay`` / ``kv_pull_corrupt``
    Cross-replica page pulls slow down in transit / arrive with a
    flipped byte. Corruption MUST be rejected by the import digest check
    and degrade to recompute (``runbook_router_xreplica_stale_total``
    ``{reason="digest_mismatch"}``) — the payload never installs.
``spill_pressure``
    The host-RAM spill tier collapses (entries evicted, capacity zero)
    for the window, then recovers: readmit paths must degrade to
    recompute, never corrupt.
``tenant_flood``
    A burst of synthetic tenant traffic, submitted by the driver's
    registered flood handler (the injector itself never owns an event
    loop).
"""

from __future__ import annotations

import json
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from runbookai_tpu.utils import metrics as metrics_mod

# The closed fault vocabulary. Metric children are pre-created over this
# tuple (bounded label contract, RBK010) and the schedule generator
# validates requested kinds against it.
FAULT_KINDS = ("replica_crash", "replica_wedge", "kv_pull_delay",
               "kv_pull_corrupt", "spill_pressure", "tenant_flood")

# Fault kinds that target one replica (the others act fleet-wide).
_REPLICA_KINDS = ("replica_crash", "replica_wedge", "spill_pressure")


class ChaosReplicaCrash(RuntimeError):
    """The injected step failure — distinguishable in logs from a real
    device error, identical in effect (the engine loop's crash path)."""


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: what, when (offset seconds from injector
    start), for how long, and against which replica (fleet-local
    position; ``None`` for fleet-wide kinds)."""

    kind: str
    at_s: float
    duration_s: float
    replica: Optional[int] = None
    params: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "at_s": self.at_s,
                "duration_s": self.duration_s, "replica": self.replica,
                "params": dict(sorted(self.params.items()))}


@dataclass
class FaultSchedule:
    """A deterministic fault plan: same ``(seed, duration_s, dp, kinds,
    events_per_minute)`` ⇒ byte-identical :meth:`to_json` output."""

    seed: int
    duration_s: float
    dp: int
    events: list[FaultEvent]

    def to_json(self) -> str:
        doc = {"seed": self.seed, "duration_s": self.duration_s,
               "dp": self.dp,
               "events": [e.to_dict() for e in self.events]}
        return json.dumps(doc, indent=2, sort_keys=True)

    @classmethod
    def generate(cls, seed: int, duration_s: float, dp: int,
                 kinds: tuple = FAULT_KINDS,
                 events_per_minute: float = 12.0,
                 ensure_crash: bool = False) -> "FaultSchedule":
        """Sample a schedule from ``random.Random(seed)``.

        Event times land in the middle 80% of the run (a fault in the
        first instant would race fleet warmup; one in the final instant
        would outlive the measurement). Durations are bounded so every
        window closes inside the run. ``ensure_crash`` rewrites the
        first event into a ``replica_crash`` when none was sampled —
        the soak gate's acceptance scenario requires one."""
        unknown = set(kinds) - set(FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds {sorted(unknown)}; "
                             f"valid: {FAULT_KINDS}")
        if not kinds:
            raise ValueError("at least one fault kind is required")
        rng = random.Random(seed)
        n = max(1, int(duration_s * events_per_minute / 60.0))
        events: list[FaultEvent] = []
        for _ in range(n):
            kind = kinds[rng.randrange(len(kinds))]
            at = round(duration_s * (0.1 + 0.8 * rng.random()), 3)
            max_dur = max(0.05, min(duration_s * 0.25,
                                    duration_s - at, 10.0))
            duration = (0.0 if kind == "replica_crash"
                        else round(max_dur * (0.3 + 0.7 * rng.random()),
                                   3))
            replica = (rng.randrange(max(1, dp))
                       if kind in _REPLICA_KINDS else None)
            params: dict = {}
            if kind == "kv_pull_delay":
                params["delay_ms"] = rng.choice((10, 25, 50, 100))
            elif kind == "tenant_flood":
                params["requests"] = rng.choice((4, 8, 16))
                params["tenant"] = "spiky"
            events.append(FaultEvent(kind=kind, at_s=at,
                                     duration_s=duration,
                                     replica=replica, params=params))
        if ensure_crash and not any(e.kind == "replica_crash"
                                    for e in events):
            # The acceptance scenario's crash lands MID-run (35% in):
            # traffic is still flowing when the step thread dies, and
            # the tail of the run exercises detect→rebuild→rejoin.
            events.append(FaultEvent(
                kind="replica_crash", at_s=round(0.35 * duration_s, 3),
                duration_s=0.0, replica=rng.randrange(max(1, dp))))
        events.sort(key=lambda e: (e.at_s, e.kind))
        return cls(seed=seed, duration_s=duration_s, dp=dp, events=events)


class ChaosInjector:
    """Apply a :class:`FaultSchedule` to a live fleet, one daemon thread
    walking the events in time order. Every application is recorded as a
    window with provenance (planned vs applied offset, wall timestamp,
    status) and counted on ``runbook_chaos_faults_total{kind}``. The
    injector attaches itself as ``fleet.chaos`` so ``/healthz`` carries
    its snapshot."""

    def __init__(self, fleet, schedule: FaultSchedule, *,
                 flood_fn: Optional[Callable[[FaultEvent], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.fleet = fleet
        self.schedule = schedule
        self.flood_fn = flood_fn
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0: Optional[float] = None
        self._lock = threading.Lock()
        self.windows: list[dict[str, Any]] = []
        # Step hooks THIS injector armed, by fleet-local replica index
        # -> the window record: stop() disarms any that never fired (an
        # idle replica's crash hook must not detonate minutes after the
        # chaos run ended) and rewrites their provenance.
        self._armed: dict[int, dict[str, Any]] = {}
        reg = registry or metrics_mod.get_registry()
        counter = reg.counter(
            "runbook_chaos_faults_total",
            "Fault events applied by the chaos injector, by kind",
            labels=("kind",))
        self._m_faults = {kind: counter.labels(kind=kind)
                          for kind in FAULT_KINDS}
        fleet.chaos = self

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "ChaosInjector":
        self._t0 = self._clock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="chaos-injector")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        # Deactivate the transfer seam, and disarm any of OUR step
        # hooks that never fired (fired hooks clear themselves; a
        # rebuilt core carries none): an armed crash hook on a replica
        # that stayed idle through the run must not detonate on the
        # first real request minutes later. The window's provenance is
        # rewritten so nobody reads an unfired fault as applied.
        self.fleet.chaos_pull_hook = None
        for idx, window in self._armed.items():
            if idx >= len(self.fleet.cores):
                continue
            core = self.fleet.cores[idx]
            hook = core.chaos_hook
            if hook is not None and getattr(hook, "_chaos_injector",
                                            None) is self:
                core.chaos_hook = None
                with self._lock:
                    window["status"] = "disarmed (never fired)"

    def _elapsed(self) -> float:
        return self._clock() - (self._t0 or 0.0)

    def _run(self) -> None:
        for event in self.schedule.events:
            while not self._stop.is_set() \
                    and self._elapsed() < event.at_s:
                self._stop.wait(min(0.02,
                                    event.at_s - self._elapsed()))
            if self._stop.is_set():
                return
            self._apply(event)

    # ----------------------------------------------------------- appliers

    def _apply(self, event: FaultEvent) -> None:
        window = {
            "kind": event.kind,
            "replica": (self.fleet.replica_ids[event.replica]
                        if event.replica is not None else None),
            "planned_at_s": event.at_s,
            "applied_at_s": round(self._elapsed(), 4),
            "duration_s": event.duration_s,
            "ends_at_s": round(self._elapsed() + event.duration_s, 4),
            "wall_ts": time.time(),
            "params": dict(event.params),
            "status": "applied",
        }
        try:
            getattr(self, f"_apply_{event.kind}")(event, window)
        except Exception as exc:  # noqa: BLE001 — one bad fault must not
            # stop the schedule; the window records the failure.
            window["status"] = f"error: {exc}"
        with self._lock:
            self.windows.append(window)
        if window["status"] == "applied":
            # Errored faults never count as applied — the counter and
            # snapshot()["events_applied"] mean what they say.
            self._m_faults[event.kind].inc()

    def _arm(self, event: FaultEvent, window: dict, hook) -> None:
        """Install a step hook tagged as ours and remember its window,
        so stop() can disarm it (and fix the provenance) if it never
        fires."""
        hook._chaos_injector = self
        self._armed[event.replica] = window
        self.fleet.cores[event.replica].chaos_hook = hook

    def _apply_replica_crash(self, event: FaultEvent,
                             window: dict) -> None:
        def crash_hook(c) -> None:
            # One-shot: the rebuilt (or restarted) engine must serve.
            c.chaos_hook = None
            raise ChaosReplicaCrash(
                f"chaos: injected crash on replica {c.replica_idx}")

        self._arm(event, window, crash_hook)

    def _apply_replica_wedge(self, event: FaultEvent,
                             window: dict) -> None:
        end = self._clock() + event.duration_s
        stop = self._stop
        clock = self._clock

        def wedge_hook(c) -> None:
            # Stall the step thread (engine lock held — exactly what a
            # wedged dispatch looks like) until the window closes.
            while clock() < end and not stop.is_set():
                time.sleep(0.01)
            c.chaos_hook = None

        self._arm(event, window, wedge_hook)

    def _apply_kv_pull_delay(self, event: FaultEvent,
                             window: dict) -> None:
        end = self._clock() + event.duration_s
        delay_s = event.params.get("delay_ms", 25) / 1e3
        clock = self._clock

        def delay_hook(exported):
            # Runs in the pull's worker thread (no locks held): only the
            # pulling request pays the latency.
            if clock() < end:
                time.sleep(delay_s)
            return exported

        self.fleet.chaos_pull_hook = delay_hook

    def _apply_kv_pull_corrupt(self, event: FaultEvent,
                               window: dict) -> None:
        end = self._clock() + event.duration_s
        clock = self._clock

        def corrupt_hook(exported):
            if clock() < end and exported.leaves_k:
                # Flip one byte of the first exported page: the import's
                # per-block digest check must reject it (the pull
                # degrades to recompute; byte-identity survives).
                page = np.array(exported.leaves_k[0], copy=True)
                flat = page.view(np.uint8).reshape(-1)
                flat[0] ^= 0xFF
                exported.leaves_k[0] = page
            return exported

        self.fleet.chaos_pull_hook = corrupt_hook

    def _apply_spill_pressure(self, event: FaultEvent,
                              window: dict) -> None:
        end = self._clock() + event.duration_s
        clock = self._clock
        state: dict = {}

        def spill_hook(c) -> None:
            # Runs at step top under the engine lock — the only safe
            # place to mutate the spill tier from outside the step
            # thread's own paths.
            spill = c.kv.spill
            if spill is None:
                c.chaos_hook = None
                return
            if "saved" not in state:
                state["saved"] = spill.max_pages
                spill.evict_all()
                spill.max_pages = 0
            if clock() >= end:
                spill.max_pages = state["saved"]
                c.chaos_hook = None

        self._arm(event, window, spill_hook)

    def _apply_tenant_flood(self, event: FaultEvent,
                            window: dict) -> None:
        if self.flood_fn is None:
            raise RuntimeError("no flood handler registered")
        self.flood_fn(event)

    # -------------------------------------------------------- observability

    def active_windows(self) -> list[dict]:
        """Fault windows active RIGHT NOW, with provenance — the chaos
        block an opened incident's context carries (obs/incident.py): an
        incident during an injected fault says which fault."""
        now = self._elapsed() if self._t0 is not None else 0.0
        with self._lock:
            return [dict(w) for w in self.windows
                    if w["status"] == "applied"
                    and w["applied_at_s"] <= now < w["ends_at_s"]]

    def snapshot(self) -> dict:
        """The ``/healthz`` ``chaos`` block: schedule identity, applied
        windows with provenance, and which are active right now."""
        now = self._elapsed() if self._t0 is not None else 0.0
        with self._lock:
            windows = [dict(w) for w in self.windows]
        return {
            "seed": self.schedule.seed,
            "events_planned": len(self.schedule.events),
            "events_applied": sum(1 for w in windows
                                  if w["status"] == "applied"),
            "elapsed_s": round(now, 3),
            "active": [w["kind"] for w in windows
                       if w["status"] == "applied"
                       and w["applied_at_s"] <= now < w["ends_at_s"]],
            "windows": windows,
        }
