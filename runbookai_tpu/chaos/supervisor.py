"""Fleet supervision: detect dead/wedged replicas, fail over their
in-flight requests, rebuild the engine online, rejoin with hysteresis.

A replica that dies or wedges mid-stream used to take its in-flight
chains down silently: callers hung on done events, the router kept
placing new requests onto the corpse, and nothing rebuilt it. The
:class:`FleetSupervisor` closes that loop (AIBrix-style self-healing
replica management) with a per-replica state machine:

::

    healthy ──(step stalls with work queued)──▶ suspect
    healthy/suspect ──(loop crashed | stall > 2×timeout)──▶ failed
    failed ──(quarantine + fail over in-flight)──▶ rebuilding
    rebuilding ──(AsyncFleet.rebuild_replica)──▶ rejoining
    rejoining ──(hysteresis elapsed, no relapse)──▶ healthy
    suspect ──(step advances)──▶ healthy

Detection reads the flight recorder's step cursor as a heartbeat
(``total_steps`` advancing = alive), ``AsyncEngine.loop_crashed`` as the
crash signal, and a non-blocking engine-lock probe as the wedge
corroborator — the same signal ``health_snapshot`` reports as
``"unresponsive"`` when its lock budget runs out.

Failover: every live request on the failed core is force-finished as
ABORTED under a bounded lock attempt — the fleet's ``generate`` retry
loop (bounded exponential backoff, seeded jitter) re-places each one on
a sibling, and ``generate_stream`` fails over any stream that had not
yet yielded. Tokens already streamed cannot be unsaid; those streams end
in the ABORTED state the HTTP layer turns into a clean SSE error event.

Rebuild: ``AsyncFleet.rebuild_replica`` — engine teardown and
reconstruction on the replica's device slice as a first-class runtime
operation (the architectural unlock ROADMAP item 2's autoscaler also
needs). Rejoin hysteresis doubles per consecutive failure (capped), and
a replica that keeps dying past ``max_consecutive_rebuilds`` stays
quarantined (state ``failed``) rather than flapping the fleet.

Metric labels stay statically bounded (zero ``noqa`` sites — pinned by
``tests/test_lint.py``): per-state series are pre-created over the
:data:`SUPERVISOR_STATES` literal; per-replica detail lives in the
``/healthz`` ``supervisor`` block, not in label values.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from runbookai_tpu.engine.request import FinishReason, RequestState
from runbookai_tpu.utils import metrics as metrics_mod
from runbookai_tpu.utils.trace import get_tracer

SUPERVISOR_STATES = ("healthy", "suspect", "failed", "rebuilding",
                     "rejoining")

# Bounded transition history surfaced by snapshot() (the timeline a
# `runbook chaos status` renders; old entries age out).
_TRANSITIONS_MAX = 256

# Every live supervisor in the process: the runbook_supervisor_replicas
# gauge sums states across ALL of them (a multi-model deployment runs
# one supervisor per group; a callback bound to just the last-built one
# would silently stop reporting its siblings' failed replicas). Weak so
# a torn-down fleet's supervisor drops out of the scrape.
_SUPERVISORS: "weakref.WeakSet[FleetSupervisor]" = weakref.WeakSet()


@dataclass
class _ReplicaState:
    state: str = "healthy"
    since: float = 0.0
    reason: str = ""
    last_steps: int = 0
    last_advance: float = 0.0
    last_crash_count: int = 0
    rebuilds: int = 0
    consecutive_failures: int = 0
    rejoin_at: float = 0.0


class FleetSupervisor:
    """Poll-loop supervisor over one :class:`AsyncFleet`'s replicas."""

    def __init__(self, fleet, *, poll_interval_s: float = 0.05,
                 wedge_timeout_s: float = 60.0,
                 rejoin_hysteresis_s: float = 0.25,
                 rejoin_hysteresis_max_s: float = 30.0,
                 max_consecutive_rebuilds: int = 3,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.fleet = fleet
        self.poll_interval_s = poll_interval_s
        self.wedge_timeout_s = wedge_timeout_s
        self.rejoin_hysteresis_s = rejoin_hysteresis_s
        self.rejoin_hysteresis_max_s = rejoin_hysteresis_max_s
        self.max_consecutive_rebuilds = max_consecutive_rebuilds
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards _states + transitions against snapshot() readers (HTTP
        # threads). Never held across fleet calls or blocking work —
        # transitions mutate state briefly, detection/rebuild run
        # outside it.
        self._lock = threading.Lock()
        now = self._clock()
        self._states = [
            _ReplicaState(since=now, last_advance=now,
                          last_steps=core.flight.total_steps)
            for core in fleet.cores]
        self.transitions: deque = deque(maxlen=_TRANSITIONS_MAX)
        # Per-supervisor totals (snapshot()): the runbook_supervisor_*
        # counters are process-wide across every fleet's supervisor.
        self._rebuilds = 0
        self._failovers = 0
        reg = registry or metrics_mod.get_registry()
        transitions = reg.counter(
            "runbook_supervisor_transitions_total",
            "Replica state-machine transitions, by state entered",
            labels=("state",))
        self._m_transitions = {state: transitions.labels(state=state)
                               for state in SUPERVISOR_STATES}
        replicas = reg.gauge(
            "runbook_supervisor_replicas",
            "Replicas currently in each supervision state",
            labels=("state",))
        _SUPERVISORS.add(self)
        for state in SUPERVISOR_STATES:
            # Sums over EVERY live supervisor (racy state reads — the
            # scrape-gauge staleness contract), so per-group
            # supervisors don't overwrite each other's callback.
            replicas.labels(state=state).set_function(
                lambda s=state: float(sum(
                    1 for sup in list(_SUPERVISORS)
                    for st in sup._states if st.state == s)))
        self._m_rebuilds = reg.counter(
            "runbook_supervisor_rebuilds_total",
            "Online replica rebuilds (engine teardown + reconstruction "
            "on the replica's device slice)")
        self._m_failovers = reg.counter(
            "runbook_supervisor_failovers_total",
            "In-flight requests force-finished off a failed replica for "
            "router-level retry")
        fleet.supervisor = self

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "FleetSupervisor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="fleet-supervisor")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — supervision must survive
                import logging  # a poll hiccup; the next tick retries

                logging.getLogger(__name__).exception(
                    "supervisor poll failed")
            self._stop.wait(self.poll_interval_s)

    # ----------------------------------------------------------- detection

    def poll_once(self) -> None:
        """One detection sweep over every replica (public so tests and
        deterministic drivers can step the machine without the thread)."""
        now = self._clock()
        for i in range(self.fleet.dp):
            self._check(i, now)

    def _lock_busy(self, i: int) -> bool:
        """Non-blocking engine-lock probe: True when the step thread is
        holding the lock right now — the corroborating wedge signal
        health_snapshot reports as "unresponsive"."""
        lock = self.fleet.replicas[i]._lock
        acquired = lock.acquire(blocking=False)
        if acquired:
            lock.release()
        return not acquired

    def _crashed(self, i: int) -> bool:
        """Sticky crash detection: the loop's monotonic crash count
        catches a crash even when a caller's start() already restarted
        the loop before this poll; ``loop_crashed`` covers a dead loop
        nobody restarted."""
        st = self._states[i]
        replica = self.fleet.replicas[i]
        count = replica.crash_count
        if count > st.last_crash_count:
            st.last_crash_count = count
            return True
        return replica.loop_crashed

    def _check(self, i: int, now: float) -> None:
        st = self._states[i]
        core = self.fleet.cores[i]
        steps = core.flight.total_steps
        if steps != st.last_steps:
            st.last_steps = steps
            st.last_advance = now
            if st.state == "suspect":
                self._transition(i, "healthy", "step cursor advanced")
        if st.state == "healthy" and st.consecutive_failures \
                and now - st.since > 10 * self.wedge_timeout_s:
            # Sustained health clears the flap counter — the next
            # failure starts hysteresis from the base again.
            st.consecutive_failures = 0
        if st.state in ("healthy", "suspect"):
            if self._crashed(i):
                self._fail(i, now, "engine loop crashed")
                return
            stalled_for = now - st.last_advance
            if core.has_work and stalled_for > self.wedge_timeout_s:
                if st.state == "healthy":
                    self._transition(
                        i, "suspect",
                        f"no step in {stalled_for:.2f}s with work "
                        f"queued (lock "
                        f"{'held' if self._lock_busy(i) else 'free'})")
                elif stalled_for > 2 * self.wedge_timeout_s:
                    self._fail(i, now,
                               f"wedged: no step in {stalled_for:.2f}s")
        elif st.state == "rejoining":
            if self._crashed(i):
                self._fail(i, now, "crashed during rejoin hysteresis")
            elif now >= st.rejoin_at:
                self.fleet.unquarantine(i)
                self._transition(i, "healthy", "rejoined routing")

    # ------------------------------------------------- failover + rebuild

    def _fail(self, i: int, now: float, reason: str) -> None:
        self._transition(i, "failed", reason)
        self.fleet.quarantine(i)
        failed_over = self._failover(i)
        if failed_over:
            self._failovers += failed_over
            self._m_failovers.inc(failed_over)
        st = self._states[i]
        if st.consecutive_failures >= self.max_consecutive_rebuilds:
            # Flapping: stop burning rebuilds on a replica that dies
            # every time it comes back — it stays quarantined until an
            # operator intervenes (state sticky at "failed").
            self._transition(
                i, "failed",
                f"left quarantined after "
                f"{st.consecutive_failures} consecutive failures",
                force=True)
            return
        self._transition(i, "rebuilding",
                         f"failed over {failed_over} in-flight requests")
        try:
            new_core = self.fleet.rebuild_replica(i)
        except Exception as exc:  # noqa: BLE001 — a rebuild that raises
            # leaves the replica quarantined, never half-swapped.
            self._transition(i, "failed", f"rebuild error: {exc}",
                             force=True)
            return
        st.rebuilds += 1
        st.consecutive_failures += 1
        self._rebuilds += 1
        self._m_rebuilds.inc()
        hysteresis = min(
            self.rejoin_hysteresis_max_s,
            self.rejoin_hysteresis_s
            * (2 ** (st.consecutive_failures - 1)))
        st.rejoin_at = self._clock() + hysteresis
        st.last_steps = new_core.flight.total_steps
        st.last_advance = self._clock()
        st.last_crash_count = 0  # the fresh AsyncEngine counts from 0
        self._transition(i, "rejoining",
                         f"hysteresis {hysteresis:.2f}s")

    def _failover(self, i: int) -> int:
        """Unblock every live request on the failed core NOW so the
        router's retry loop re-places them. With the engine lock (a
        crashed core's lock is free) the full ``force_finish`` cleanup
        runs. When the lock cannot be had — a wedged step thread holds
        it — pools are NEVER touched (mutating them under a live step
        corrupts the core): only the request's finish state and done
        event are set, which is all the awaiters need; the pools belong
        to an abandoned core a fresh engine is about to replace."""
        core = self.fleet.cores[i]
        replica = self.fleet.replicas[i]
        locked = replica._lock.acquire(timeout=0.2)
        try:
            live = (list(core.waiting) + list(core.prefilling)
                    + list(core.decoding))
            for req in live:
                try:
                    if locked:
                        core.force_finish(req)
                    else:
                        req.finish_reason = (req.finish_reason
                                             or FinishReason.ABORTED)
                        req.state = RequestState.FINISHED
                        if req.done_event is not None:
                            req.done_event.set()
                except Exception:  # noqa: BLE001 — a poisoned core must
                    pass           # not strand the remaining awaiters
        finally:
            if locked:
                replica._lock.release()
        return len(live)

    def _transition(self, i: int, to: str, reason: str,
                    force: bool = False) -> None:
        st = self._states[i]
        if st.state == to and not force:
            return
        frm = st.state
        now = self._clock()
        with self._lock:
            st.state = to
            st.since = now
            st.reason = reason
            self.transitions.append({
                "ts": round(time.time(), 6),
                "replica": self.fleet.replica_ids[i],
                "from": frm,
                "to": to,
                "reason": reason,
            })
        self._m_transitions[to].inc()
        tracer = get_tracer()
        if tracer.enabled:
            tracer.event("supervisor.transition",
                         replica=self.fleet.replica_ids[i],
                         frm=frm, to=to, reason=reason)

    # -------------------------------------------------------- observability

    def state_of(self, i: int) -> str:
        """Current state of fleet-local replica position ``i``."""
        return self._states[i].state

    def snapshot(self) -> dict:
        """The ``/healthz`` ``supervisor`` block."""
        with self._lock:
            replicas = [{
                "replica": self.fleet.replica_ids[i],
                "state": st.state,
                "reason": st.reason,
                "rebuilds": st.rebuilds,
                "consecutive_failures": st.consecutive_failures,
            } for i, st in enumerate(self._states)]
            transitions = list(self.transitions)
        return {
            "wedge_timeout_s": self.wedge_timeout_s,
            "rejoin_hysteresis_s": self.rejoin_hysteresis_s,
            "replicas": replicas,
            "rebuilds_total": self._rebuilds,
            "failovers_total": self._failovers,
            "transitions": transitions,
        }
