"""Operability-context ingestion: spool hook events, replay to the backend.

Parity target: reference ``src/integrations/operability-context-ingestion.ts``
(client :344 with local spool + replay; claim building from hook payloads
:293). Events spool locally when the backend is unreachable and replay later
— ``runbook operability ingest/replay/status`` surface.
"""

from __future__ import annotations

import json
import time
import uuid
from pathlib import Path
from typing import Any

from runbookai_tpu.providers.operability import ContextClaim, Provenance


def build_claims_from_hook_event(event: dict[str, Any]) -> list[ContextClaim]:
    """Derive environment claims from a hook payload (ingestion :293)."""
    from runbookai_tpu.agent.memory import extract_services

    claims: list[ContextClaim] = []
    tool = str(event.get("tool_name", ""))
    command = str((event.get("tool_input") or {}).get("command", ""))
    text = f"{tool} {command} {json.dumps(event.get('tool_response', ''))[:500]}"
    services = extract_services(text)
    predicate = None
    low = command.lower()
    if any(w in low for w in ("deploy", "rollout", "apply")):
        predicate = "deployed"
    elif any(w in low for w in ("scale", "replicas")):
        predicate = "scaled"
    elif any(w in low for w in ("config", "env", "secret")):
        predicate = "config_changed"
    elif tool:
        predicate = "inspected"
    if predicate:
        for svc in services[:3]:
            claims.append(ContextClaim(
                subject=svc, predicate=predicate,
                value={"tool": tool, "command": command[:200]},
                confidence=0.6 if predicate != "inspected" else 0.3,
                provenance=Provenance(source="claude-hooks"),
            ))
    return claims


class IngestionClient:
    def __init__(self, adapter=None, spool_dir: str | Path = ".runbook/operability-spool"):
        self.adapter = adapter  # OperabilityAdapter with session_ingest
        self.spool = Path(spool_dir)

    # ------------------------------------------------------------------ send

    async def ingest(self, events: list[dict[str, Any]]) -> dict[str, Any]:
        """Try the backend; on failure spool to disk for later replay."""
        if self.adapter is not None and self.adapter.supports("session_ingest"):
            try:
                result = await self.adapter.ingest_session(events)
                return {"status": "sent", "count": len(events), "result": result}
            except Exception as exc:  # noqa: BLE001 — spool on any failure
                self._spool(events)
                return {"status": "spooled", "count": len(events),
                        "reason": f"{type(exc).__name__}: {exc}"}
        self._spool(events)
        return {"status": "spooled", "count": len(events),
                "reason": "no backend with session_ingest"}

    def _spool(self, events: list[dict[str, Any]]) -> Path:
        self.spool.mkdir(parents=True, exist_ok=True)
        path = self.spool / f"batch-{int(time.time())}-{uuid.uuid4().hex[:6]}.json"
        path.write_text(json.dumps({"spooled_at": time.time(), "events": events},
                                   default=str))
        return path

    # ---------------------------------------------------------------- replay

    async def replay(self) -> dict[str, Any]:
        replayed, failed = 0, 0
        if not self.spool.is_dir():
            return {"replayed": 0, "failed": 0}
        for batch in sorted(self.spool.glob("batch-*.json")):
            try:
                events = json.loads(batch.read_text()).get("events", [])
            except json.JSONDecodeError:
                batch.unlink()
                continue
            if self.adapter is None or not self.adapter.supports("session_ingest"):
                failed += 1
                continue
            try:
                await self.adapter.ingest_session(events)
                batch.unlink()
                replayed += 1
            except Exception:  # noqa: BLE001
                failed += 1
        return {"replayed": replayed, "failed": failed}

    def status(self) -> dict[str, Any]:
        batches = sorted(self.spool.glob("batch-*.json")) if self.spool.is_dir() else []
        pending_events = 0
        for b in batches:
            try:
                pending_events += len(json.loads(b.read_text()).get("events", []))
            except json.JSONDecodeError:
                continue
        return {"spooled_batches": len(batches), "pending_events": pending_events,
                "backend": getattr(self.adapter, "name", None)}
