"""Claude session store: persists hook event streams for learning ingestion.

Parity target: reference ``src/integrations/claude-session-store.ts`` — local
or S3 backends with optional mirroring; factory (:345). Events stream into
per-session JSONL; the learning loop ingests them later
(``learning/claude-session-ingestion.ts`` equivalent: :func:`ingest_sessions`).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Optional


class LocalSessionStore:
    def __init__(self, root: str | Path = ".runbook/claude-sessions"):
        self.root = Path(root)

    def _path(self, session_id: str) -> Path:
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in session_id)
        return self.root / f"{safe}.jsonl"

    def append(self, session_id: str, event: dict[str, Any]) -> None:
        self.root.mkdir(parents=True, exist_ok=True)
        with self._path(session_id).open("a") as f:
            f.write(json.dumps({"ts": time.time(), **event}, default=str) + "\n")

    def read(self, session_id: str) -> list[dict[str, Any]]:
        path = self._path(session_id)
        if not path.is_file():
            return []
        out = []
        for line in path.read_text().splitlines():
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out

    def list_sessions(self) -> list[str]:
        if not self.root.is_dir():
            return []
        return sorted(p.stem for p in self.root.glob("*.jsonl"))


class S3SessionStore:
    """S3 backend; requires boto3. Mirrors to a local store when given."""

    def __init__(self, bucket: str, prefix: str = "claude-sessions/",
                 mirror: Optional[LocalSessionStore] = None):
        self.bucket = bucket
        self.prefix = prefix
        self.mirror = mirror

    def append(self, session_id: str, event: dict[str, Any]) -> None:
        if self.mirror is not None:
            self.mirror.append(session_id, event)
        try:
            import boto3

            s3 = boto3.client("s3")
            key = f"{self.prefix}{session_id}/{int(time.time() * 1000)}.json"
            s3.put_object(Bucket=self.bucket, Key=key,
                          Body=json.dumps(event, default=str).encode())
        except Exception:  # noqa: BLE001 — mirroring keeps the local copy
            pass

    def read(self, session_id: str) -> list[dict[str, Any]]:
        if self.mirror is not None:
            return self.mirror.read(session_id)
        return []

    def list_sessions(self) -> list[str]:
        if self.mirror is not None:
            return self.mirror.list_sessions()
        return []


def create_session_store(config):
    """Factory (claude-session-store.ts:345)."""
    claude = config.integrations.claude
    local = LocalSessionStore(claude.session_store_path)
    if claude.session_store == "s3" and claude.s3_bucket:
        return S3SessionStore(claude.s3_bucket, mirror=local)
    return local


def ingest_sessions(store, retriever=None) -> dict[str, Any]:
    """Summarize stored sessions into learning signals: tool usage counts,
    services touched, blocked commands (claude-session-ingestion.ts)."""
    from runbookai_tpu.agent.memory import extract_services

    summary: dict[str, Any] = {"sessions": 0, "events": 0,
                               "tool_counts": {}, "services": {},
                               "blocked_commands": []}
    for session_id in store.list_sessions():
        events = store.read(session_id)
        if not events:
            continue
        summary["sessions"] += 1
        summary["events"] += len(events)
        for ev in events:
            tool = (ev.get("tool_name") or
                    (ev.get("tool_input") or {}).get("tool"))
            if tool:
                summary["tool_counts"][tool] = summary["tool_counts"].get(tool, 0) + 1
            text = json.dumps(ev, default=str)
            for svc in extract_services(text[:2000]):
                summary["services"][svc] = summary["services"].get(svc, 0) + 1
            if ev.get("decision") == "block":
                summary["blocked_commands"].append(
                    str((ev.get("tool_input") or {}).get("command", ""))[:200])
    return summary
