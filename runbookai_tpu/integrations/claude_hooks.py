"""Claude Code hooks integration: install/uninstall + stdin event handlers.

Parity targets: reference ``src/integrations/claude-hooks.ts`` (8 hook events
:13-21; settings.json install/uninstall/status :306-343) and
``hook-handlers.ts`` (``handleSessionStart`` :244, ``handleUserPromptSubmit``
:288 — detect services/symptoms in prompts and inject matching runbooks/known
issues; ``handlePreToolUse`` :380 — block dangerous commands;
``handlePostToolUse`` :423; dispatcher :455; stdin JSON protocol :481).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path
from typing import Any

HOOK_EVENTS = (
    "SessionStart", "UserPromptSubmit", "PreToolUse", "PostToolUse",
    "Notification", "Stop", "SubagentStop", "PreCompact",
)

# Dangerous command patterns blocked by PreToolUse (hook-handlers.ts:380).
DANGEROUS_PATTERNS = [
    re.compile(r"\brm\s+(-\w*[rf]\w*\s+)+"),
    re.compile(r"\bkubectl\s+delete\b"),
    re.compile(r"\bterraform\s+(destroy|apply)\b"),
    re.compile(r"\baws\s+\S*\s*(terminate|delete)-"),
    re.compile(r"\bdrop\s+(table|database)\b", re.IGNORECASE),
    re.compile(r"\bmkfs\b|\bdd\s+if="),
    re.compile(r":\s*\(\)\s*\{.*\};\s*:"),  # fork bomb
]


def install_hooks(settings_path: str | Path, command: str = "runbook hook") -> dict[str, Any]:
    """Add our hook entries to a Claude settings.json (merge-preserving)."""
    path = Path(settings_path)
    settings: dict[str, Any] = {}
    if path.is_file():
        try:
            settings = json.loads(path.read_text())
        except json.JSONDecodeError:
            settings = {}
    hooks = settings.setdefault("hooks", {})
    for event in HOOK_EVENTS:
        entries = hooks.setdefault(event, [])
        already = any(
            h.get("command", "").startswith(command)
            for entry in entries for h in entry.get("hooks", [])
        )
        if not already:
            entries.append({"hooks": [{"type": "command",
                                       "command": f"{command} {event}"}]})
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(settings, indent=2))
    return settings


def uninstall_hooks(settings_path: str | Path, command: str = "runbook hook") -> bool:
    path = Path(settings_path)
    if not path.is_file():
        return False
    try:
        settings = json.loads(path.read_text())
    except json.JSONDecodeError:
        return False
    hooks = settings.get("hooks", {})
    changed = False
    for event in list(hooks):
        kept = []
        for entry in hooks[event]:
            inner = [h for h in entry.get("hooks", [])
                     if not h.get("command", "").startswith(command)]
            if inner:
                entry["hooks"] = inner
                kept.append(entry)
            else:
                changed = True
        hooks[event] = kept
        if not kept:
            del hooks[event]
    if changed:
        path.write_text(json.dumps(settings, indent=2))
    return changed


def hooks_status(settings_path: str | Path, command: str = "runbook hook") -> dict[str, bool]:
    path = Path(settings_path)
    status = {event: False for event in HOOK_EVENTS}
    if not path.is_file():
        return status
    try:
        settings = json.loads(path.read_text())
    except json.JSONDecodeError:
        return status
    for event, entries in settings.get("hooks", {}).items():
        if event in status:
            status[event] = any(
                h.get("command", "").startswith(command)
                for entry in entries for h in entry.get("hooks", []))
    return status


class HookHandlers:
    def __init__(self, retriever=None, session_store=None):
        self.retriever = retriever
        self.session_store = session_store

    # ------------------------------------------------------------- handlers

    def handle_session_start(self, payload: dict[str, Any]) -> dict[str, Any]:
        self._record("SessionStart", payload)
        return {"continue": True,
                "systemMessage": "RunbookAI knowledge hooks active."}

    def handle_user_prompt_submit(self, payload: dict[str, Any]) -> dict[str, Any]:
        """Inject matching runbooks/known issues for services/symptoms
        detected in the prompt (hook-handlers.ts:288)."""
        self._record("UserPromptSubmit", payload)
        prompt = str(payload.get("prompt", ""))
        if self.retriever is None or not prompt:
            return {"continue": True}
        from runbookai_tpu.agent.memory import extract_services, extract_symptoms

        terms = extract_services(prompt) + extract_symptoms(prompt)
        if not terms:
            return {"continue": True}
        hits = self.retriever.hybrid.search(" ".join(terms[:6]), limit=3)
        if not hits:
            return {"continue": True}
        context = "\n".join(
            f"- [{h.doc.doc_id}] {h.doc.title} ({h.doc.knowledge_type}): "
            f"{h.chunk.content[:200]}"
            for h in hits)
        return {"continue": True,
                "hookSpecificOutput": {
                    "hookEventName": "UserPromptSubmit",
                    "additionalContext":
                        f"Relevant operational knowledge:\n{context}"}}

    def handle_pre_tool_use(self, payload: dict[str, Any]) -> dict[str, Any]:
        """Block dangerous commands (hook-handlers.ts:380)."""
        self._record("PreToolUse", payload)
        tool_input = payload.get("tool_input") or {}
        command = str(tool_input.get("command", ""))
        for pattern in DANGEROUS_PATTERNS:
            if pattern.search(command):
                return {"decision": "block",
                        "reason": f"runbookai safety: command matches dangerous "
                                  f"pattern {pattern.pattern!r}"}
        return {"continue": True}

    def handle_post_tool_use(self, payload: dict[str, Any]) -> dict[str, Any]:
        self._record("PostToolUse", payload)
        return {"continue": True}

    def handle_default(self, event: str, payload: dict[str, Any]) -> dict[str, Any]:
        self._record(event, payload)
        return {"continue": True}

    def _record(self, event: str, payload: dict[str, Any]) -> None:
        if self.session_store is not None:
            self.session_store.append(payload.get("session_id", "unknown"),
                                      {"event": event, **payload})

    # ----------------------------------------------------------- dispatcher

    def handle_hook_event(self, event: str, payload: dict[str, Any]) -> dict[str, Any]:
        handlers = {
            "SessionStart": self.handle_session_start,
            "UserPromptSubmit": self.handle_user_prompt_submit,
            "PreToolUse": self.handle_pre_tool_use,
            "PostToolUse": self.handle_post_tool_use,
        }
        handler = handlers.get(event)
        if handler is None:
            return self.handle_default(event, payload)
        return handler(payload)


def run_hook_stdin(event: str, handlers: HookHandlers,
                   stdin=None, stdout=None) -> int:
    """stdin JSON protocol entrypoint (hook-handlers.ts:481)."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    try:
        payload = json.loads(stdin.read() or "{}")
    except json.JSONDecodeError:
        payload = {}
    result = handlers.handle_hook_event(event, payload)
    stdout.write(json.dumps(result))
    stdout.flush()
    # Exit code 2 signals a block to Claude Code.
    return 2 if result.get("decision") == "block" else 0
