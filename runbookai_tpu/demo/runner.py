"""Scripted demo playback (reference ``src/demo/demo-runner.ts:223``).

Replays the canned investigation with timing; ``--fast`` is 3×. Renders
through the same event vocabulary as real runs so the terminal output is
identical in shape to a live investigation.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from runbookai_tpu.demo.data import DEMO_CHART, DEMO_INCIDENT, DEMO_SCRIPT
from runbookai_tpu.agent.types import AgentEvent


def run_demo(
    emit: Optional[Callable[[AgentEvent], None]] = None,
    fast: bool = False,
    sleep=time.sleep,
) -> list[AgentEvent]:
    """Play the demo; returns the event list (also streamed via ``emit``)."""
    speed = 3.0 if fast else 1.0
    events: list[AgentEvent] = []

    def push(kind: str, data: dict) -> None:
        ev = AgentEvent(kind, data)
        events.append(ev)
        if emit:
            emit(ev)

    push("start", {"incident": DEMO_INCIDENT, "demo": True})
    for delay, kind, payload in DEMO_SCRIPT:
        sleep(delay / speed)
        if kind == "conclusion":
            # Attach the latency chart the visualization policy mandates.
            from runbookai_tpu.tools.diagram import line_chart, sparkline

            payload = dict(payload)
            payload["chart"] = line_chart(
                [float(v) for v in DEMO_CHART],
                label="payment-api p99 latency (ms), last 60m")
            payload["sparkline"] = sparkline([float(v) for v in DEMO_CHART])
        push(kind, payload)
    return events


def render_event(ev: AgentEvent) -> str:
    """Terminal line renderer shared by demo and live CLI output."""
    d = ev.data
    k = ev.kind
    if k == "answer":
        from runbookai_tpu.cli.markdown import render_markdown

        import sys

        return "\n" + render_markdown(d.get("text", ""),
                                      color=sys.stdout.isatty())
    if k == "start":
        inc = d.get("incident", {})
        title = inc.get("title") or d.get("query", "")
        return f"▶ {title}" if title else "▶ session started"
    if k == "phase":
        return f"\n== {d.get('name', '').upper()} == {d.get('text', '')}"
    if k == "phase_change":
        return f"\n== {d.get('phase', '').upper()} =="
    if k == "triage":
        return (f"  severity={d.get('severity')} services={', '.join(d.get('services', []))}"
                f"\n  {d.get('summary', '')}")
    if k == "tool_call":
        return f"  → {d.get('name')}({d.get('args', {})})"
    if k == "tool_result":
        return f"    ✓ {d.get('summary') or d.get('result_id') or 'ok'}"
    if k == "hypothesis_created":
        parent = f" (under {d['parent']})" if d.get("parent") else ""
        return f"  + {d.get('id')}: {d.get('statement')}{parent} [p={d.get('priority', '?')}]"
    if k == "hypothesis_updated":
        return (f"  * {d.get('id')} -> {d.get('action')} "
                f"({d.get('reason', d.get('confidence', ''))})")
    if k == "evidence":
        return f"    · evidence via {d.get('tool')} for {d.get('hypothesis')}"
    if k == "conclusion":
        lines = [
            "\n╔═ ROOT CAUSE " + "═" * 50,
            f"║ {d.get('root_cause', '')}",
            f"║ confidence: {d.get('confidence')}  "
            f"services: {', '.join(d.get('services', d.get('affected_services', [])))}",
            "╚" + "═" * 63,
        ]
        if d.get("chart"):
            lines.append(d["chart"])
        return "\n".join(lines)
    if k == "remediation_step":
        return f"  [{d.get('risk', '?').upper():8}] {d.get('description')}"
    if k == "warning":
        return f"  ! {d.get('text')}"
    if k == "thinking":
        return f"  … {d.get('text', '')[:120]}"
    if k == "knowledge_retrieved":
        return f"  ⚲ knowledge retrieved {d.get('counts', d.get('trigger', ''))}"
    if k == "iteration":
        return f"\n-- iteration {d.get('n')} --"
    if k == "done":
        return "\n✔ done"
    if k == "error":
        return f"  ✗ {d}"
    if k == "token":
        # Inline-streaming consumers (cli._print_event) never reach here;
        # line-based consumers get the raw delta without debug wrapping.
        return d.get("delta", "")
    return f"  [{k}] {d}"
