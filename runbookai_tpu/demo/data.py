"""Canned demo investigation data (reference ``src/demo/demo-data.ts``).

A fully scripted payment-api latency investigation: phases, tool outputs,
hypothesis tree updates, and the final conclusion — zero model, zero network.
This is the CPU baseline config in BASELINE.md (config 1).
"""

from __future__ import annotations

DEMO_INCIDENT = {
    "id": "PD-12345",
    "title": "High p99 latency on payment-api",
    "severity": "high",
    "service": "payment-api",
}

# Each step: (delay_s, kind, payload) — delays scaled by speed factor.
DEMO_SCRIPT: list[tuple[float, str, dict]] = [
    (0.2, "phase", {"name": "triage", "text": "Triaging incident PD-12345…"}),
    (0.7, "tool_call", {"name": "pagerduty_get_incident",
                        "args": {"incident_id": "PD-12345"}}),
    (0.5, "tool_result", {"name": "pagerduty_get_incident",
                          "summary": "triggered 38m ago: p99 latency above 1.5s SLO, "
                                     "customer checkout failures reported"}),
    (0.6, "tool_call", {"name": "cloudwatch_alarms", "args": {"state": "ALARM"}}),
    (0.5, "tool_result", {"name": "cloudwatch_alarms",
                          "summary": "2 alarms firing: payment-api-p99-latency "
                                     "(4.82s vs 1.5s), payments-db-connections (98/100)"}),
    (0.4, "triage", {"severity": "high",
                     "summary": "payment-api p99 latency 3x above SLO; "
                                "db connections near limit",
                     "services": ["payment-api", "payments-db"]}),
    (0.3, "phase", {"name": "hypothesize", "text": "Generating hypotheses…"}),
    (0.8, "hypothesis_created", {"id": "H1", "statement":
                                 "DB connection pool exhaustion is throttling requests",
                                 "priority": 0.9}),
    (0.3, "hypothesis_created", {"id": "H2", "statement":
                                 "Recent deployment introduced a performance regression",
                                 "priority": 0.8}),
    (0.3, "hypothesis_created", {"id": "H3", "statement":
                                 "Node CPU saturation is slowing all pods",
                                 "priority": 0.4}),
    (0.3, "phase", {"name": "investigate", "text": "Investigating H1 (priority 0.9)…"}),
    (0.7, "tool_call", {"name": "cloudwatch_logs",
                        "args": {"log_group": "/ecs/payment-api",
                                 "filter_pattern": "connection"}}),
    (0.8, "tool_result", {"name": "cloudwatch_logs",
                          "summary": "HikariPool-1 exhausted: total=20 active=20 "
                                     "waiting=142; 'pool size 20 (was 50 before deploy "
                                     "payment-api:57)'"}),
    (0.5, "tool_call", {"name": "aws_query", "args": {"service": "rds"}}),
    (0.5, "tool_result", {"name": "aws_query",
                          "summary": "payments-db: 98/100 connections, cpu 41% — "
                                     "connection-bound, not cpu-bound"}),
    (0.5, "hypothesis_updated", {"id": "H1", "action": "branch", "confidence": 0.6,
                                 "reason": "pool exhausted — but why now?"}),
    (0.3, "hypothesis_created", {"id": "H4", "parent": "H1", "statement":
                                 "Deploy payment-api:57 shrank the pool from 50 to 20",
                                 "priority": 0.95}),
    (0.3, "phase", {"name": "investigate", "text": "Investigating H4 (priority 0.95)…"}),
    (0.6, "tool_call", {"name": "datadog", "args": {"action": "events"}}),
    (0.6, "tool_result", {"name": "datadog",
                          "summary": "42m ago: 'Deployed payment-api v2.31.0 — config "
                                     "change: db pool max_size 50 -> 20 (PR #4312)'"}),
    (0.5, "hypothesis_updated", {"id": "H4", "action": "confirm", "confidence": 0.92,
                                 "reason": "deploy event matches alarm onset; config "
                                           "change directly explains pool exhaustion"}),
    (0.4, "hypothesis_updated", {"id": "H2", "action": "merged",
                                 "reason": "subsumed by H4"}),
    (0.4, "hypothesis_updated", {"id": "H3", "action": "prune",
                                 "reason": "node cpu 55-61%, not saturated"}),
    (0.3, "phase", {"name": "conclude", "text": "Forming conclusion…"}),
    (0.9, "conclusion", {
        "root_cause": "Deploy payment-api v2.31.0 (PR #4312) reduced the database "
                      "connection pool max_size from 50 to 20. Under normal load the "
                      "pool saturates, requests queue for connections, and p99 latency "
                      "breaches the SLO.",
        "confidence": "high",
        "services": ["payment-api", "payments-db"],
    }),
    (0.3, "phase", {"name": "remediate", "text": "Planning remediation…"}),
    (0.6, "remediation_step", {"description": "Rollback payment-api to v2.30.x "
                                              "(task definition :56)", "risk": "high"}),
    (0.3, "remediation_step", {"description": "Revert PR #4312 pool configuration",
                               "risk": "low"}),
    (0.3, "remediation_step", {"description": "Add alert on connection-pool saturation "
                                              ">80%", "risk": "low"}),
    (0.2, "done", {"elapsed": "investigation complete"}),
]

DEMO_CHART = [310, 340, 330, 2900, 4400, 4820, 4710]
