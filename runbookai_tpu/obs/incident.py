"""Live incident monitoring + black-box capture: the fleet writes its
own postmortems.

:class:`IncidentMonitor` is the live half of the detection story
(:mod:`runbookai_tpu.obs.detect` is the pure half): a poll loop folds
the signals the platform already exports — SLO burn, workload drift,
replica health, supervisor states, router sheds / stale pull
rejections, queue-wait percentiles — into :class:`IncidentDetector`
readings, and on every **open** preserves the evidence while the
incident is still happening: a bounded, schema-versioned,
content-hashed **incident bundle** written to a rotated on-disk
directory (``llm.obs.incident_dir``, oldest pruned past
``incident_max_bundles``). A bundle carries per-replica flight-recorder
tails, the ``/healthz`` body, the live workload fingerprint + drift
breakdown, the supervisor/chaos blocks (fault provenance — WAS a fault
injected when this opened), a trace JSONL tail and a full metrics
scrape — everything the reference system's incident investigator would
ask a human to paste, captured at detection time instead.

Surfaces (everywhere the platform already looks):

- ``GET /debug/incidents`` and the ``/healthz`` ``incidents`` block
  (server/openai_api.py);
- ``runbook incident list|show [--bundle]`` (cli/main.py) — works
  against a live server or straight off the bundle directory;
- ``runbook_incident_open{signal}`` (**absent** when no incident of
  that signal is open — the ``runbook_slo_*`` absence contract),
  ``runbook_incident_total{signal}`` (materialized at 0 so ``rate()``
  works from the first incident) and
  ``runbook_incident_duration_seconds{signal}`` (resolved open→resolve
  durations). Labels are pre-created over the
  :data:`~runbookai_tpu.obs.detect.INCIDENT_SIGNALS` literal tuple —
  zero noqa sites, pinned by ``tests/test_lint.py``;
- ``incident.open`` / ``incident.resolve`` tracer events, stitched into
  ``runbook timeline`` as a span band (utils/timeline.py) so a dp retry
  during an incident is visible in one view;
- the ``bench.py --soak-scenarios`` detection-coverage invariant:
  every injected fault window must overlap a detected incident of a
  matching signal class, and the chaos-free baseline pass must open
  zero incidents (the false-positive gate).

Threading: one daemon poll thread (``poll_once`` public for
deterministic drivers — bench, fixtures). Detector state mutates only
under ``self._lock``; bundle writes, tracer events and metric bumps run
OUTSIDE it (blocking I/O under a lock is exactly what ``runbook lint``
RBK003 exists to catch).
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Optional, Sequence

from runbookai_tpu.obs.detect import (
    INCIDENT_SIGNALS,
    IncidentDetector,
    default_policies,
)
from runbookai_tpu.obs.query import bucket_quantile, counter_increase
from runbookai_tpu.utils import metrics as metrics_mod
from runbookai_tpu.utils.trace import get_tracer

BUNDLE_SCHEMA_VERSION = 1

# The bundle `history` section's own version: lookback payload shape
# may evolve independently of the bundle envelope.
HISTORY_SCHEMA_VERSION = 1

# The store series the monitor writes each poll: the detector's input
# readings, one labelset per INCIDENT_SIGNALS entry. Store-only (never
# registered in the registry) — registering it as a gauge would make
# absent signals linger at their last stored value, breaking the
# absence contract the readings carry.
SIGNAL_SERIES = "runbook_incident_signal"

# Resolved-incident durations: seconds from open to resolve.
INCIDENT_DURATION_BUCKETS = (1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                             300.0, 600.0, 1800.0, 3600.0)

# Resolved incidents kept in the in-memory feed (bundles persist more).
_RECENT_MAX = 32


# ------------------------------------------------------------- bundles


def bundle_hash(doc: dict[str, Any]) -> str:
    """Content hash over the canonical JSON of everything BUT the hash
    field itself — ``verify_bundle`` recomputes exactly this."""
    body = {k: v for k, v in doc.items() if k != "content_hash"}
    canonical = json.dumps(body, sort_keys=True,
                           separators=(",", ":"), default=str)
    return "sha256:" + hashlib.sha256(canonical.encode()).hexdigest()


def write_bundle(directory: str | Path, doc: dict[str, Any],
                 max_bundles: int = 16) -> Path:
    """Write one incident bundle (stamping schema version + content
    hash) and prune the oldest past ``max_bundles`` — the black box is
    bounded like the flight ring and the trace JSONL.

    Filenames lead with the capture timestamp (ms) so they sort
    chronologically ACROSS process restarts: detector ids restart at
    inc-0001 per process, and a restarted server pointed at the same
    persistent ``incident_dir`` must neither overwrite the previous
    run's postmortems nor prune the wrong "oldest"."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    doc = dict(doc)
    doc["schema_version"] = BUNDLE_SCHEMA_VERSION
    doc["content_hash"] = bundle_hash(doc)
    inc = doc.get("incident") or {}
    stamp = max(0, int(float(doc.get("captured_ts") or 0.0) * 1000))
    name = (f"{stamp:013d}-{inc.get('id', 'inc-0000')}"
            f"-{inc.get('signal', 'unknown')}.json")
    path = directory / name
    # The same serialization laxity as the hash (default=str): an
    # evidence value that is stringifiable but not JSON-native must not
    # desync the written bytes from the hash input — or kill the write.
    path.write_text(json.dumps(doc, indent=2, sort_keys=True,
                               default=str) + "\n")
    for stale in sorted(directory.glob("*.json"))[:-max(1, max_bundles)]:
        stale.unlink(missing_ok=True)
    return path


def load_bundle(path: str | Path) -> dict[str, Any]:
    return json.loads(Path(path).read_text())


def verify_bundle(path: str | Path) -> tuple[bool, str, str]:
    """Recompute the content hash: ``(ok, expected, actual)``. A bundle
    that fails is corrupt or hand-edited — either way not evidence."""
    doc = load_bundle(path)
    stored = str(doc.get("content_hash", ""))
    actual = bundle_hash(doc)
    return stored == actual, stored, actual


def list_bundles(directory: str | Path) -> list[Path]:
    """Bundles oldest→newest (the timestamp-prefixed names sort
    chronologically even across process restarts)."""
    directory = Path(directory)
    if not directory.is_dir():
        return []
    return sorted(directory.glob("*.json"))


# ------------------------------------------------------------- monitor


class IncidentMonitor:
    """Poll-loop incident detection over live fleets + monitors."""

    def __init__(self, fleets: Sequence[Any] = (), *,
                 cores: Optional[Sequence[Any]] = None,
                 slo_monitor: Any = None, workload_monitor: Any = None,
                 detector: Optional[IncidentDetector] = None,
                 bundle_dir: Optional[str | Path] = None,
                 max_bundles: int = 16,
                 poll_interval_s: float = 1.0,
                 flight_tail: int = 32, trace_tail: int = 64,
                 tsdb: Any = None, history_lookback_s: float = 60.0,
                 clock: Callable[[], float] = time.time,
                 registry: Optional[metrics_mod.MetricsRegistry] = None):
        self.fleets = list(fleets)
        if cores is not None:
            self.cores = list(cores)
        else:
            self.cores = [c for fleet in self.fleets
                          for c in getattr(fleet, "cores", ())]
        self.slo_monitor = slo_monitor
        self.workload_monitor = workload_monitor
        # Embedded time-series store (obs/tsdb.py). When attached, the
        # derivative-shaped readings (router sheds / stale pulls /
        # queue-wait p95) come from the STORE's samples instead of
        # hand-rolled snapshot diffs, every poll's readings are
        # ingested as the SIGNAL_SERIES history, and bundles embed a
        # pre-open lookback window. None = the PR-15 snapshot-diff
        # paths, unchanged.
        self.tsdb = tsdb
        self.history_lookback_s = float(history_lookback_s)
        self.bundle_dir = Path(bundle_dir) if bundle_dir else None
        self.max_bundles = max(1, int(max_bundles))
        self.poll_interval_s = float(poll_interval_s)
        self.flight_tail = int(flight_tail)
        self.trace_tail = int(trace_tail)
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards the detector + recent feed + counter baselines against
        # snapshot() readers (HTTP threads). Never held across bundle
        # writes, tracer events or metric bumps.
        self._lock = threading.Lock()
        self._detector = detector if detector is not None \
            else IncidentDetector()
        self._recent: list[dict[str, Any]] = []
        # Counter baselines for delta-shaped signals (sheds, stale
        # pulls) and the queue-wait bucket-snapshot window (the shared
        # utils/metrics.HistogramWindow) — the tsdb-off fallback paths.
        self._prev_counts: dict[str, float] = {}
        self._queue_window: Optional[metrics_mod.HistogramWindow] = None
        # End of the previous poll's store window (tsdb path): each
        # poll's derivative readings diff the store samples over
        # [previous poll, this poll].
        self._last_poll_now: Optional[float] = None
        reg = registry or metrics_mod.get_registry()
        g_open = reg.gauge(
            "runbook_incident_open",
            "Open incidents per signal class; a signal with no open "
            "incident scrapes as ABSENCE, never 0 (the runbook_slo_* "
            "contract)", labels=("signal",))
        # A rebuilt monitor takes over the scrape; stale callbacks from
        # a torn-down fleet's monitor must not keep reporting.
        g_open.clear_functions()
        c_total = reg.counter(
            "runbook_incident_total",
            "Incidents opened, by signal class (materialized at 0 so "
            "rate() works from the first incident)", labels=("signal",))
        h_duration = reg.histogram(
            "runbook_incident_duration_seconds",
            "Open-to-resolve duration of resolved incidents, by signal",
            labels=("signal",), buckets=INCIDENT_DURATION_BUCKETS)
        self._m_total = {}
        self._m_duration = {}
        for signal in INCIDENT_SIGNALS:
            g_open.labels(signal=signal).set_function(
                lambda s=signal: self._open_count_or_raise(s))
            child = c_total.labels(signal=signal)
            child.inc(0.0)
            self._m_total[signal] = child
            self._m_duration[signal] = h_duration.labels(signal=signal)

    def _open_count_or_raise(self, signal: str) -> float:
        with self._lock:
            n = sum(1 for i in self._detector.open_incidents()
                    if i["signal"] == signal)
        if n == 0:
            raise LookupError(f"{signal}: no open incident")
        return float(n)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "IncidentMonitor":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="incident-monitor")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — detection must survive a
                import logging  # poll hiccup; the next tick retries

                logging.getLogger(__name__).exception(
                    "incident poll failed")
            self._stop.wait(self.poll_interval_s)

    # ------------------------------------------------------------ readings

    def _max_burn(self) -> Optional[float]:
        """Worst objective's lifetime burn, WITHOUT the violation-counter
        side effect ``SLOMonitor.evaluate`` has."""
        slo = self.slo_monitor
        if slo is None or not getattr(slo, "objectives", None):
            return None
        burns = []
        for key, obj in slo.objectives.items():
            current = slo.current_ms(key)
            if current is not None:
                burns.append(current / obj["target_ms"])
        return max(burns) if burns else None

    def _max_drift(self) -> Optional[float]:
        monitor = self.workload_monitor
        if monitor is None:
            return None
        drifts = [monitor.drift(m) for m in monitor.fingerprinters]
        drifts = [d for d in drifts if d is not None]
        return max(drifts) if drifts else None

    def _min_health(self) -> Optional[float]:
        monitor = self.workload_monitor
        if monitor is None:
            return None
        healths = [monitor.replica_health(core, model)
                   for model, fp in monitor.fingerprinters.items()
                   for core in fp.cores]
        return min(healths) if healths else None

    def _unhealthy_replicas(self) -> list[Any]:
        """Global replica ids the supervisors hold in failed/rebuilding/
        rejoining — both the replica_failure reading and the context an
        opened incident carries."""
        out = []
        for fleet in self.fleets:
            sup = getattr(fleet, "supervisor", None)
            if sup is None:
                continue
            for i in range(fleet.dp):
                if sup.state_of(i) in ("failed", "rebuilding", "rejoining"):
                    out.append(fleet.replica_ids[i])
        return out

    def _counter_delta(self, key: str, total: float) -> float:
        prev = self._prev_counts.get(key)
        self._prev_counts[key] = total
        return 0.0 if prev is None else max(0.0, total - prev)

    def _queue_wait_p95(self) -> Optional[float]:
        """p95 of the queue-wait observations since the LAST poll
        (bucket-snapshot diff via the shared
        utils/metrics.HistogramWindow — the sched/feedback windowing
        idiom) — None when no request was admitted this window
        (absence)."""
        hist = metrics_mod.get_registry().get("runbook_queue_wait_seconds")
        if not isinstance(hist, metrics_mod.Histogram):
            return None
        if self._queue_window is None or self._queue_window.hist is not hist:
            self._queue_window = metrics_mod.HistogramWindow(hist)
        return self._queue_window.percentile(95)

    def _trend_readings_from_store(self, readings: dict[str, Any],
                                   now: float) -> None:
        """The derivative-shaped signals from the STORE's samples over
        [previous poll, now] — sheds / stale pulls as reset-aware
        counter increases, queue-wait p95 as a bucket-snapshot quantile
        (obs/query math, so detection and ``/debug/query`` cannot
        disagree). First poll (no window yet) and windows with no
        samples stay absent."""
        start = self._last_poll_now
        self._last_poll_now = now
        if start is None or start >= now:
            return
        for signal, metric in (
                ("router_shed", "runbook_router_shed_total"),
                ("router_stale", "runbook_router_xreplica_stale_total")):
            increases = [inc for _, pts in self.tsdb.select(
                             metric, start, now)
                         if (inc := counter_increase(pts)) is not None]
            if increases:
                readings[signal] = float(sum(increases))
        rows = bucket_quantile(
            self.tsdb.select("runbook_queue_wait_seconds_bucket",
                             start, now), 0.95)
        if rows:
            readings["queue_wait"] = max(v for _, v in rows)

    def collect(self, now: Optional[float] = None) -> dict[str, Any]:
        """One reading for the detector: every signal with live evidence
        (missing keys are the absence contract). Runs WITHOUT the
        monitor lock — every source has its own synchronization story
        (scrape-gauge torn-read tolerance)."""
        readings: dict[str, Any] = {}
        burn = self._max_burn()
        if burn is not None:
            readings["slo_burn"] = burn
        drift = self._max_drift()
        if drift is not None:
            readings["workload_drift"] = drift
        health = self._min_health()
        if health is not None:
            readings["replica_health"] = health
        if any(getattr(f, "supervisor", None) is not None
               for f in self.fleets):
            readings["replica_failure"] = float(
                len(self._unhealthy_replicas()))
        if self.tsdb is not None:
            self._trend_readings_from_store(
                readings, float(self._clock() if now is None else now))
            return readings
        sheds = [f.shed_total() for f in self.fleets
                 if hasattr(f, "shed_total")]
        if sheds:
            readings["router_shed"] = self._counter_delta(
                "router_shed", float(sum(sheds)))
        stale = [f.stale_rejections() for f in self.fleets
                 if hasattr(f, "stale_rejections")]
        if stale:
            readings["router_stale"] = self._counter_delta(
                "router_stale", float(sum(stale)))
        queue_p95 = self._queue_wait_p95()
        if queue_p95 is not None:
            readings["queue_wait"] = queue_p95
        return readings

    # ---------------------------------------------------------- detection

    def poll_once(self, now: Optional[float] = None) -> list[tuple[str, dict]]:
        """One detection fold (public so bench and tests can drive the
        machine deterministically without the thread). Side effects —
        bundle capture, tracer events, metric bumps — run outside the
        state lock."""
        now = self._clock() if now is None else float(now)
        if self.tsdb is not None:
            # Aligned sweep: the derivative readings diff the store's
            # samples at consecutive polls, so every poll contributes
            # exactly one window endpoint (the sampler thread's own
            # cadence only adds resolution in between).
            self.tsdb.sample_once(now)
        readings = self.collect(now)
        if self.tsdb is not None:
            # The detector's input readings become first-class history:
            # what the bundle lookback and `runbook incident show`
            # render. Absent signals ingest nothing.
            for signal, value in sorted(readings.items()):
                self.tsdb.ingest(now, SIGNAL_SERIES,
                                 (("signal", signal),), float(value))
        with self._lock:
            events = self._detector.observe(now, readings)
            for kind, inc in events:
                if kind == "open":
                    inc["context"] = self._context(readings)
                elif kind == "resolve":
                    self._recent.append(dict(inc))
                    del self._recent[:-_RECENT_MAX]
            # Copies for the unlocked side-effect phase: the docs keep
            # mutating under later folds.
            emitted = [(kind, dict(inc)) for kind, inc in events]
        for kind, inc in emitted:
            self._emit(kind, inc)
        return emitted

    def _context(self, readings: dict[str, Any]) -> dict[str, Any]:
        """What was true the instant the incident opened: the replicas
        involved, the chaos windows active RIGHT NOW (fault provenance —
        an incident during an injected fault says so), and the full
        reading that tripped the detector."""
        chaos_active = []
        for fleet in self.fleets:
            chaos = getattr(fleet, "chaos", None)
            if chaos is not None:
                chaos_active.extend(chaos.active_windows())
        return {
            "replicas": self._unhealthy_replicas(),
            "chaos_active": chaos_active,
            "reading": {k: round(float(v), 6)
                        for k, v in sorted(readings.items())},
        }

    def _emit(self, kind: str, inc: dict[str, Any]) -> None:
        tracer = get_tracer()
        if kind == "open":
            self._m_total[inc["signal"]].inc()
            if tracer.enabled:
                tracer.event("incident.open", incident=inc["id"],
                             signal=inc["signal"],
                             severity=inc["severity"],
                             value=inc["value_at_open"],
                             replicas=inc["context"].get("replicas", []))
            if self.bundle_dir is not None:
                self.capture_bundle(inc)
        elif kind == "resolve":
            self._m_duration[inc["signal"]].observe(inc["duration_s"])
            if tracer.enabled:
                tracer.event("incident.resolve", incident=inc["id"],
                             signal=inc["signal"],
                             duration_s=inc["duration_s"])

    # ------------------------------------------------------------ capture

    def _trace_tail(self) -> list[dict[str, Any]]:
        tracer = get_tracer()
        if not tracer.enabled or tracer.path is None:
            return []
        try:
            lines = tracer.path.read_text().splitlines()[-self.trace_tail:]
        except OSError:
            return []
        out = []
        for line in lines:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # the writer's in-flight partial last line
        return out

    def evidence(self) -> dict[str, Any]:
        """The black-box payload: bounded snapshots of every live
        surface, taken while the incident is still happening."""
        body: dict[str, Any] = {}
        healthz = {}
        flight = {}
        for fi, fleet in enumerate(self.fleets):
            snap_fn = getattr(fleet, "health_snapshot", None)
            scope = getattr(fleet, "model", None) or f"fleet{fi}"
            if snap_fn is not None:
                healthz[str(scope)] = snap_fn()
        for core in self.cores:
            recorder = getattr(core, "flight", None)
            if recorder is None or not recorder.enabled:
                continue
            rid = core.replica_idx if core.replica_idx is not None else 0
            flight[str(rid)] = recorder.snapshot(self.flight_tail)
        body["healthz"] = healthz
        body["flight"] = flight
        if self.workload_monitor is not None:
            body["workload"] = self.workload_monitor.snapshot()
        slo = self.slo_monitor
        if slo is not None and getattr(slo, "objectives", None):
            body["slo"] = slo.evaluate()
        body["trace_tail"] = self._trace_tail()
        body["metrics"] = metrics_mod.get_registry().render()
        return body

    def history_section(self,
                        now: Optional[float] = None,
                        ) -> Optional[dict[str, Any]]:
        """The bundle's pre-open lookback: every INCIDENT_SIGNALS entry
        with stored samples inside ``history_lookback_s`` of ``now``,
        as ``[ts, value]`` pairs from the SIGNAL_SERIES history the
        poll loop ingests. None when no store is attached (the bundle
        then carries no ``history`` key at all); a signal that was
        absent over the whole window is absent here too."""
        if self.tsdb is None:
            return None
        now = float(self._clock() if now is None else now)
        signals: dict[str, list[list[float]]] = {}
        for labels, pts in self.tsdb.select(
                SIGNAL_SERIES, now - self.history_lookback_s, now):
            name = labels.get("signal")
            if name in INCIDENT_SIGNALS:
                signals[name] = [[round(ts, 3), round(v, 6)]
                                 for ts, v in pts]
        return {"schema_version": HISTORY_SCHEMA_VERSION,
                "lookback_s": round(self.history_lookback_s, 3),
                "signals": dict(sorted(signals.items()))}

    def capture_bundle(self, inc: dict[str, Any]) -> Optional[Path]:
        """Write one incident's bundle (schema-versioned, content-hashed,
        rotation-pruned). Failures never propagate into the poll loop —
        a full disk must not stop detection."""
        doc: dict[str, Any] = {
            "captured_ts": round(self._clock(), 3),
            "incident": dict(inc),
            "evidence": self.evidence(),
        }
        history = self.history_section()
        if history is not None:
            # Inside the content-hash envelope: verify_bundle covers
            # the lookback exactly like every other evidence section.
            doc["history"] = history
        try:
            path = write_bundle(self.bundle_dir, doc,
                                max_bundles=self.max_bundles)
        except (OSError, TypeError, ValueError):
            # Full disk, or an evidence source emitting something even
            # default=str cannot serialize — detection keeps running.
            return None
        with self._lock:
            live = self._detector._open.get(inc["signal"])
            if live is not None and live["id"] == inc["id"]:
                live["bundle"] = path.name
        return path

    # ------------------------------------------------------------ surface

    def snapshot(self, full: bool = False) -> dict[str, Any]:
        """The ``/healthz`` ``incidents`` block (light) and the
        ``GET /debug/incidents`` body (``full=True`` adds the resolved
        feed and the on-disk bundle listing). ``totals`` carries only
        signals that HAVE opened incidents — absence, not a zero row per
        signal (the metric's materialized-zero lives on /metrics where
        rate() needs it)."""
        with self._lock:
            open_incidents = [dict(i)
                              for i in self._detector.open_incidents()]
            recent = [dict(i) for i in self._recent]
        totals: dict[str, int] = {}
        for inc in [*recent, *open_incidents]:
            totals[inc["signal"]] = totals.get(inc["signal"], 0) + 1
        body: dict[str, Any] = {
            "enabled": True,
            "open": open_incidents,
            "open_count": len(open_incidents),
            "totals": dict(sorted(totals.items())),
            "bundle_dir": (str(self.bundle_dir)
                           if self.bundle_dir is not None else None),
        }
        if full:
            body["recent"] = recent
            body["bundles"] = [p.name for p in list_bundles(self.bundle_dir)] \
                if self.bundle_dir is not None else []
        return body

    def incidents(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(i) for i in self._detector.incidents()]

    # ------------------------------------------------------------ factory

    @classmethod
    def from_config(cls, llm_cfg: Any, *, fleets: Sequence[Any] = (),
                    cores: Optional[Sequence[Any]] = None,
                    slo_monitor: Any = None, workload_monitor: Any = None,
                    tsdb: Any = None,
                    ) -> Optional["IncidentMonitor"]:
        """Build from ``llm.obs`` (None when the obs layer or incident
        detection is disabled). The drift policy's open threshold tracks
        ``llm.obs.drift_threshold`` — the incident and
        ``runbook_plan_stale`` must agree on what "drifted" means."""
        obs_cfg = getattr(llm_cfg, "obs", None)
        if obs_cfg is None or not getattr(obs_cfg, "enabled", False) \
                or not getattr(obs_cfg, "incidents_enabled", True):
            return None
        detector = IncidentDetector(default_policies(
            drift_threshold=float(getattr(obs_cfg, "drift_threshold",
                                          0.35)),
            open_after_s=getattr(obs_cfg, "incident_open_s", 5.0),
            resolve_after_s=getattr(obs_cfg, "incident_resolve_s", 10.0)))
        tsdb_cfg = getattr(obs_cfg, "tsdb", None)
        return cls(
            fleets, cores=cores, slo_monitor=slo_monitor,
            workload_monitor=workload_monitor, detector=detector,
            bundle_dir=getattr(obs_cfg, "incident_dir", None),
            max_bundles=getattr(obs_cfg, "incident_max_bundles", 16),
            poll_interval_s=getattr(obs_cfg, "incident_poll_interval_s",
                                    1.0),
            tsdb=tsdb,
            history_lookback_s=getattr(tsdb_cfg, "lookback_s", 60.0)
            if tsdb_cfg is not None else 60.0)


__all__ = [
    "BUNDLE_SCHEMA_VERSION", "HISTORY_SCHEMA_VERSION",
    "INCIDENT_DURATION_BUCKETS", "SIGNAL_SERIES",
    "IncidentMonitor", "bundle_hash", "list_bundles", "load_bundle",
    "verify_bundle", "write_bundle",
]
