"""Workload monitor: drift detection, plan-staleness, replica health.

:class:`WorkloadMonitor` owns one
:class:`~runbookai_tpu.obs.fingerprint.WorkloadFingerprinter` per served
model group and compares each live fingerprint against that group's
**reference descriptor** — the serving plan's provenance ``workload``
block when a plan is pinned (``llm.plan`` / ``llm.models[].plan``), the
``llm.obs.workload`` block otherwise, and the tuner's default
:class:`~runbookai_tpu.autotune.cost_model.Workload` as the last resort.
The comparison is the observation half of ROADMAP item 3's closed loop:
``runbook_workload_drift_score`` crossing ``llm.obs.drift_threshold``
(scraped as ``runbook_plan_stale``) is the retune trigger a future
governor subscribes to; this layer itself changes NOTHING — no plan is
swapped, no traffic moved, so byte-identity with an unmonitored engine
is structural.

Exported series (absent-not-zero, the ``runbook_slo_*`` contract: an
empty/warmup window drops the series rather than scraping drift=0):

- ``runbook_workload_{prompt_len_p50,output_len_p50,concurrency,
  guided_share,spec_hit_rate,prefix_cache_share,window_requests}{model}``
- ``runbook_workload_drift_score{model}`` / ``runbook_plan_stale{model}``
- ``runbook_replica_health{replica,model}`` — composite SLO-burn x queue
  x KV-pressure x drift score in [0, 1]; the admission signal ROADMAP
  item 2's autoscaler will consume (present whenever the monitor is on —
  health is computable before the first fingerprint).

Surfaces: ``GET /debug/workload`` and the ``/healthz`` ``workload``
block (per-group + merged fleet-wide, like ``debug_steps``), the
``runbook workload`` CLI, ``bench.py`` details, and a rotated on-disk
fingerprint history with window provenance (``llm.obs.history_dir``).
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Callable, Optional, Sequence

from runbookai_tpu.obs.fingerprint import (
    DEFAULT_DRIFT_THRESHOLD,
    DESCRIPTOR_KEYS,
    WorkloadFingerprinter,
    build_fingerprint,
    drift_score,
)
from runbookai_tpu.utils import metrics as metrics_mod

# How long a computed fingerprint is reused across scrape callbacks: one
# /metrics scrape samples ~8 workload gauges per model, and each would
# otherwise re-fold the window.
_FINGERPRINT_MEMO_S = 1.0


class FingerprintHistory:
    """Rotated on-disk fingerprint trail with window provenance.

    One JSON file per recording (``fingerprint-<seq>.json``), oldest
    pruned past ``max_files`` — a soak's history is bounded like the
    flight ring and the trace JSONL. Each file carries the window span
    and sample counts the fingerprint was folded from, so a retune
    decision is auditable against the exact traffic that motivated it.
    """

    def __init__(self, directory: str | Path, max_files: int = 64):
        self.dir = Path(directory)
        self.max_files = max(1, int(max_files))

    def _existing(self) -> list[Path]:
        if not self.dir.is_dir():
            return []
        return sorted(self.dir.glob("fingerprint-*.json"))

    def record(self, entry: dict[str, Any]) -> Path:
        self.dir.mkdir(parents=True, exist_ok=True)
        existing = self._existing()
        seq = 0
        if existing:
            try:
                seq = int(existing[-1].stem.split("-")[-1]) + 1
            except ValueError:
                seq = len(existing)
        path = self.dir / f"fingerprint-{seq:08d}.json"
        path.write_text(json.dumps(entry, indent=2, sort_keys=True) + "\n")
        for stale in self._existing()[:-self.max_files]:
            stale.unlink(missing_ok=True)
        return path

    def entries(self) -> list[dict[str, Any]]:
        out = []
        for path in self._existing():
            try:
                out.append(json.loads(path.read_text()))
            except (OSError, json.JSONDecodeError):
                continue
        return out


def reference_descriptor(llm_cfg: Any,
                         plan_path: Optional[str] = None,
                         ) -> tuple[dict[str, Any], str]:
    """Resolve the descriptor a group's live fingerprint is judged
    against: plan provenance workload > ``llm.obs.workload`` > tuner
    defaults. Returns ``(descriptor, source)`` — the source string rides
    into ``/debug/workload`` so an operator can see WHICH yardstick the
    drift score measures."""
    from runbookai_tpu.autotune.cost_model import Workload

    if plan_path:
        try:
            from runbookai_tpu.autotune.plan import load_plan

            plan = load_plan(plan_path)
            wl = {k: plan.workload[k] for k in DESCRIPTOR_KEYS
                  if k in plan.workload}
            if wl:
                base = Workload().to_dict()
                base.update(wl)
                return base, f"plan:{plan.plan_id}"
        except ValueError:
            pass  # invalid plan already refused loudly at engine build
    obs_cfg = getattr(llm_cfg, "obs", None)
    configured = getattr(obs_cfg, "workload", None)
    if configured is not None:
        return dict(configured.to_descriptor()), "config:llm.obs.workload"
    return Workload().to_dict(), "default"


def replica_health(core: Any, *, burn: Optional[float] = None,
                   drift: Optional[float] = None) -> float:
    """Composite per-replica health in [0, 1]: the product of four
    normalized factors — SLO burn (1 while the worst objective is inside
    target, 1/burn past it), queue depth (vs one batch of slots), KV
    pressure (free-page headroom), and workload drift (1 - score). A
    replica at 1.0 is serving its tuned workload with headroom; the
    autoscaler-facing admission signal (ROADMAP item 2) degrades
    multiplicatively because any single exhausted axis makes the replica
    a bad placement regardless of the others."""
    slots = max(1, core.ecfg.max_batch_slots)
    queue = len(core.waiting) + len(core.prefilling)
    queue_factor = 1.0 / (1.0 + queue / slots)
    kv_factor = max(0.0, 1.0 - float(core.kv.utilization()))
    burn_factor = (1.0 if burn is None or burn <= 1.0
                   else 1.0 / max(burn, 1.0))
    drift_factor = 1.0 - min(1.0, drift or 0.0)
    return round(queue_factor * kv_factor * burn_factor * drift_factor, 4)


class WorkloadMonitor:
    """Per-model fingerprinters + drift scoring + the metric surface."""

    def __init__(self, fingerprinters: dict[str, WorkloadFingerprinter],
                 references: dict[str, tuple[dict[str, Any], str]], *,
                 drift_threshold: float = DEFAULT_DRIFT_THRESHOLD,
                 slo_monitor: Any = None, tenants: Any = None,
                 history: Optional[FingerprintHistory] = None,
                 history_interval_s: float = 60.0,
                 clock: Optional[Callable[[], float]] = None,
                 registry: Optional[metrics_mod.MetricsRegistry] = None):
        if not fingerprinters:
            raise ValueError("a workload monitor needs >= 1 fingerprinter")
        self.fingerprinters = dict(fingerprinters)
        # Injected clock seam (the supervisor's flap-damping pattern):
        # history-rotation intervals and the scrape memo are pure
        # functions of it, so interval tests drive a fake clock instead
        # of wall-clock sleeps. Defaults to the first fingerprinter's
        # clock so window math and rotation timing cannot disagree.
        self._clock = clock if clock is not None else \
            next(iter(self.fingerprinters.values()))._clock
        self.references = {name: references.get(name, ({}, "default"))
                           for name in fingerprinters}
        self.drift_threshold = float(drift_threshold)
        self.slo_monitor = slo_monitor
        self.tenants = tenants
        self.history = history
        self.history_interval_s = float(history_interval_s)
        self._history_last = 0.0
        self._memo: dict[str, tuple[float, Optional[dict]]] = {}
        self._memo_lock = threading.Lock()
        for fp in self.fingerprinters.values():
            fp.install_taps()
        self._install_metrics(registry or metrics_mod.get_registry())

    # ----------------------------------------------------------- folding

    def _fp(self, model: str) -> Optional[dict[str, Any]]:
        """Memoized fingerprint (one fold serves a whole scrape pass)."""
        now = self._clock()
        with self._memo_lock:
            cached = self._memo.get(model)
            if cached is not None and now - cached[0] < _FINGERPRINT_MEMO_S:
                return cached[1]
        fp = self.fingerprinters[model].fingerprint(now)
        with self._memo_lock:
            self._memo[model] = (now, fp)
        return fp

    @staticmethod
    def _drift_of(fp: dict[str, Any], reference: dict[str, Any]) -> float:
        # No step evidence in the window (recorder off / ring aged out):
        # the concurrency dimension is excluded rather than scored off a
        # floor value that would fabricate drift.
        skip = ("concurrency",) if fp.get("concurrency") is None else ()
        return drift_score(fp["workload"], reference, skip=skip)

    def drift(self, model: str) -> Optional[float]:
        fp = self._fp(model)
        if fp is None:
            return None
        return self._drift_of(fp, self.references[model][0])

    def plan_stale(self, model: str) -> Optional[bool]:
        d = self.drift(model)
        return None if d is None else d > self.drift_threshold

    # Memo key for the merged fold — cannot collide with a served model
    # name (config names never carry parentheses).
    _MERGED_KEY = "(fleet)"

    def merged_fingerprint(self, now: Optional[float] = None
                           ) -> Optional[dict[str, Any]]:
        """Fleet-wide fingerprint: every group's window folded together
        (the ``debug_steps`` merge contract — one traffic picture for
        the whole endpoint). Memoized like the per-model folds (snapshot
        is wired into /healthz, and a health probe must not re-sort 4k
        samples per call); a single-group monitor reuses that group's
        already-memoized fingerprint instead of folding the identical
        window twice."""
        if len(self.fingerprinters) == 1:
            fp = self._fp(next(iter(self.fingerprinters)))
            return None if fp is None else {**fp, "model": "fleet"}
        now = self._clock() if now is None else float(now)
        with self._memo_lock:
            cached = self._memo.get(self._MERGED_KEY)
            if cached is not None and now - cached[0] < _FINGERPRINT_MEMO_S:
                return cached[1]
        fps = list(self.fingerprinters.values())
        window_s = max(fp.window_s for fp in fps)
        t0 = now - window_s
        samples = [s for fp in fps for s in fp.samples()]
        steps = [r for fp in fps for r in fp._step_records(t0)]
        metrics: dict[str, float] = {}
        for fp in fps:
            for key, value in fp._metrics().items():
                metrics[key] = metrics.get(key, 0) + value
        merged = build_fingerprint(samples, steps, metrics, model="fleet",
                                   window=(t0, now))
        with self._memo_lock:
            self._memo[self._MERGED_KEY] = (now, merged)
        return merged

    # ----------------------------------------------------------- surface

    def snapshot(self) -> dict[str, Any]:
        """``GET /debug/workload`` / ``/healthz`` body: per-group
        fingerprint + drift + staleness, a merged fleet-wide view, and
        the cumulative per-tenant admission mix when tenancy is on."""
        models: dict[str, Any] = {}
        for name in self.fingerprinters:
            fp = self._fp(name)
            reference, source = self.references[name]
            d = self._drift_of(fp, reference) if fp is not None else None
            models[name] = {
                "fingerprint": fp,
                "drift_score": d,
                "plan_stale": (None if d is None
                               else d > self.drift_threshold),
                "reference": reference,
                "reference_source": source,
            }
        drifts = [m["drift_score"] for m in models.values()
                  if m["drift_score"] is not None]
        body: dict[str, Any] = {
            "enabled": True,
            "drift_threshold": self.drift_threshold,
            "models": models,
            "merged": self.merged_fingerprint(),
            # Fleet-wide staleness is the WORST group: one stale model on
            # a shared endpoint is a retune trigger even while siblings
            # still match their plans.
            "drift_score": max(drifts) if drifts else None,
            "plan_stale": (max(drifts) > self.drift_threshold
                           if drifts else None),
        }
        if self.tenants is not None:
            body["tenant_mix"] = self._tenant_mix()
        self._maybe_record(body)
        return body

    def _tenant_mix(self) -> dict[str, Any]:
        """Cumulative per-tenant admitted-request shares from the
        governor's counters (the workload's WHO axis; the fingerprint
        covers the WHAT)."""
        try:
            snap = self.tenants.snapshot()
        except Exception:  # noqa: BLE001 — observability never fails a scrape
            return {}
        counts = {name: int(row.get("admitted", 0))
                  for name, row in snap.get("tenants", {}).items()}
        total = sum(counts.values())
        return {name: {"admitted": n,
                       "share": round(n / total, 4) if total else 0.0}
                for name, n in sorted(counts.items())}

    def _maybe_record(self, body: dict[str, Any]) -> None:
        if self.history is None:
            return
        now = self._clock()
        if now - self._history_last < self.history_interval_s:
            return
        self._history_last = now
        entry = {
            "recorded_ts": round(now, 3),
            "drift_threshold": self.drift_threshold,
            "models": {
                name: {
                    "fingerprint": m["fingerprint"],
                    "drift_score": m["drift_score"],
                    "plan_stale": m["plan_stale"],
                    "reference_source": m["reference_source"],
                }
                for name, m in body["models"].items()
            },
        }
        try:
            self.history.record(entry)
        except OSError:
            pass  # a full disk must not fail the scrape that noticed it

    # ----------------------------------------------------------- health

    def _max_burn(self) -> Optional[float]:
        """Worst configured objective's lifetime burn ratio, WITHOUT the
        violation-counter side effect a gauge scrape has."""
        slo = self.slo_monitor
        if slo is None or not getattr(slo, "objectives", None):
            return None
        burns = []
        for key, obj in slo.objectives.items():
            current = slo.current_ms(key)
            if current is not None:
                burns.append(current / obj["target_ms"])
        return max(burns) if burns else None

    def replica_health(self, core: Any, model: str) -> float:
        return replica_health(core, burn=self._max_burn(),
                              drift=self.drift(model))

    # ----------------------------------------------------------- metrics

    def _install_metrics(self, reg: metrics_mod.MetricsRegistry) -> None:
        def fp_value(model: str, fn) -> float:
            fp = self._fp(model)
            if fp is None:
                raise LookupError(f"{model}: empty fingerprint window")
            return float(fn(fp))

        gauges = (
            ("runbook_workload_prompt_len_p50",
             "Live p50 prompt tokens over the fingerprint window",
             lambda fp: fp["prompt_tokens"]["p50"]),
            ("runbook_workload_output_len_p50",
             "Live p50 generated tokens over the fingerprint window",
             lambda fp: fp["output_tokens"]["p50"]),
            ("runbook_workload_concurrency",
             "Live offered concurrency (decode batch + queued backlog, "
             "mean over non-idle steps in the window)",
             lambda fp: fp["workload"]["concurrency"]),
            ("runbook_workload_guided_share",
             "Fraction of window requests that were grammar-guided",
             lambda fp: fp["guided_share"]),
            ("runbook_workload_spec_hit_rate",
             "Extra accepted speculative tokens per decode dispatch",
             lambda fp: fp["spec_hit_rate"]),
            ("runbook_workload_prefix_cache_share",
             "Prompt tokens served from the prefix cache over the window",
             lambda fp: fp["prefix_cache_share"]),
            ("runbook_workload_window_requests",
             "Completed requests inside the fingerprint window",
             lambda fp: fp["window"]["samples"]),
        )
        models = list(self.fingerprinters)
        for name, help_text, fn in gauges:
            metric = reg.gauge(name, help_text, labels=("model",))
            metric.clear_functions()
            for model in models:
                # runbook: noqa[RBK010] — model label: served-group
                # catalog names, fixed at monitor attach.
                metric.labels(model=model).set_function(
                    lambda m=model, f=fn: fp_value(m, f))

        def drift_or_raise(model: str) -> float:
            d = self.drift(model)
            if d is None:
                raise LookupError(f"{model}: empty fingerprint window")
            return d

        g_drift = reg.gauge(
            "runbook_workload_drift_score",
            "Bounded [0,1] distance between the live workload fingerprint "
            "and the serving plan's provenance workload (or the "
            "configured descriptor); absent until the window has samples",
            labels=("model",))
        g_stale = reg.gauge(
            "runbook_plan_stale",
            "1 when the live workload drift exceeds llm.obs."
            "drift_threshold — the serving plan no longer matches the "
            "traffic; absent until the window has samples",
            labels=("model",))
        g_drift.clear_functions()
        g_stale.clear_functions()
        for model in models:
            # runbook: noqa[RBK010] — model label: served-group
            # catalog names, fixed at monitor attach.
            g_drift.labels(model=model).set_function(
                lambda m=model: drift_or_raise(m))
            # runbook: noqa[RBK010] — model label: served-group
            # catalog names, fixed at monitor attach.
            g_stale.labels(model=model).set_function(
                lambda m=model: float(
                    drift_or_raise(m) > self.drift_threshold))

        g_health = reg.gauge(
            "runbook_replica_health",
            "Composite replica health in [0,1]: SLO burn x queue depth x "
            "KV pressure x workload drift (1.0 = serving its tuned "
            "workload with headroom)", labels=("replica", "model"))
        g_health.clear_functions()
        for model, fp in self.fingerprinters.items():
            for core in fp.cores:
                rid = core.replica_idx if core.replica_idx is not None else 0
                # runbook: noqa[RBK010] — replica/model labels: pinned
                # replica ids x served-group names, fixed at attach.
                g_health.labels(replica=str(rid), model=model).set_function(
                    lambda c=core, m=model: self.replica_health(c, m))

    # ------------------------------------------------------------ factory

    @classmethod
    def from_config(cls, llm_cfg: Any, *,
                    cores: Optional[Sequence[Any]] = None,
                    multi_model: Any = None, slo_monitor: Any = None,
                    tenants: Any = None) -> Optional["WorkloadMonitor"]:
        """Build from ``llm.obs`` (None when disabled). Multi-model
        fleets get one fingerprinter per group (each judged against its
        OWN plan's provenance workload); single-model deployments get
        one for the whole engine."""
        obs_cfg = getattr(llm_cfg, "obs", None)
        if obs_cfg is None or not getattr(obs_cfg, "enabled", False):
            return None
        window_s = float(getattr(obs_cfg, "window_s", 300.0))
        max_samples = int(getattr(obs_cfg, "max_samples", 4096))
        fingerprinters: dict[str, WorkloadFingerprinter] = {}
        references: dict[str, tuple[dict[str, Any], str]] = {}
        if multi_model is not None:
            for name, group in multi_model.groups.items():
                fingerprinters[name] = WorkloadFingerprinter(
                    group.cores, model=name, window_s=window_s,
                    max_samples=max_samples)
                group_plan = getattr(group.llm_cfg, "plan", None) \
                    if group.llm_cfg is not None else None
                references[name] = reference_descriptor(
                    llm_cfg, plan_path=group_plan)
        else:
            model = getattr(llm_cfg, "model", None) or "default"
            fingerprinters[model] = WorkloadFingerprinter(
                list(cores or []), model=model, window_s=window_s,
                max_samples=max_samples)
            references[model] = reference_descriptor(
                llm_cfg, plan_path=getattr(llm_cfg, "plan", None))
        history = None
        if getattr(obs_cfg, "history_dir", None):
            history = FingerprintHistory(
                obs_cfg.history_dir,
                max_files=getattr(obs_cfg, "history_max_files", 64))
        return cls(
            fingerprinters, references,
            drift_threshold=getattr(obs_cfg, "drift_threshold",
                                    DEFAULT_DRIFT_THRESHOLD),
            slo_monitor=slo_monitor, tenants=tenants, history=history,
            history_interval_s=getattr(obs_cfg, "history_interval_s",
                                       60.0))


__all__ = ["FingerprintHistory", "WorkloadMonitor", "reference_descriptor",
           "replica_health"]
