"""Embedded telemetry time-series store: give every signal a history.

Every ``runbook_*`` series the platform exports is scrape-time-only —
incident bundles freeze a single instant, the detector and the feedback
controller re-derive trends ad hoc, and the ROADMAP's autoscaler /
retune-governor items both need saturation and drift *over time* before
they can act. :class:`MetricsTSDB` closes that gap in-process: a
bounded, injected-clock sampler walks the live metrics registry
(:mod:`runbookai_tpu.utils.metrics`) every ``llm.obs.tsdb.interval_s``
seconds and appends each exposed sample — counters, gauges, and every
histogram ``_bucket``/``_sum``/``_count`` series — into a per-series
ring pruned to ``retention_s`` seconds (and hard-capped in count), with
at most ``max_series`` distinct series process-wide.

Contracts:

- **absence-not-zero is preserved end to end**: the sampler stores what
  ``metric.samples()`` exposes and nothing else, so a series the
  registry drops (a labeled callback raising — the ``runbook_slo_*``
  contract) stores NO sample for that tick, never a zero. Queries over
  an absent window return an empty result, not zeros.
- **bounded**: ring retention + count caps, a ``max_series`` cap on
  distinct series (new series past the cap are dropped and counted),
  and self-accounting through ``runbook_tsdb_series`` /
  ``runbook_tsdb_samples_total`` / ``runbook_tsdb_memory_bytes``.
- **deterministic**: the clock is injected and ``sample_once(now)`` /
  ``ingest(now, ...)`` are public, so tests and bench drive the store
  without threads or sleeps; the query evaluator on top
  (:mod:`runbookai_tpu.obs.query`) is a pure function of (store
  contents, query, now).

Surfaces: ``GET /debug/query`` + the ``/healthz`` ``history`` block
(server/openai_api.py), ``runbook query`` (cli/main.py), incident-bundle
lookback history + store-derived detector readings (obs/incident.py),
and the soak gate's query-expressed invariants (bench.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from runbookai_tpu.utils import metrics as metrics_mod

# Estimated bytes per stored (ts, value) sample and per series ring —
# a deterministic accounting model (tuple of two floats + deque slot),
# not a profiler reading; runbook_tsdb_memory_bytes documents itself as
# an estimate.
_SAMPLE_BYTES = 16
_SERIES_OVERHEAD_BYTES = 160

# A series ring never holds more than this many samples regardless of
# retention math: callers may drive sample_once() faster than
# interval_s (the incident monitor aligns a sample to every poll), and
# the count cap keeps that bounded instead of trusting time pruning
# alone.
_RING_SLACK = 4


class MetricsTSDB:
    """Bounded in-process history over the live metrics registry."""

    def __init__(self, *, interval_s: float = 1.0,
                 retention_s: float = 600.0, max_series: int = 2048,
                 registry: Optional[metrics_mod.MetricsRegistry] = None,
                 clock: Callable[[], float] = time.time):
        if interval_s <= 0 or retention_s <= 0:
            raise ValueError("interval_s and retention_s must be > 0")
        self.interval_s = float(interval_s)
        self.retention_s = float(retention_s)
        self.max_series = max(1, int(max_series))
        self._registry = (registry if registry is not None
                          else metrics_mod.get_registry())
        self._clock = clock
        self._ring_cap = max(64, int(self.retention_s / self.interval_s)
                             * _RING_SLACK)
        # name -> labels-tuple -> ring of (ts, value). Guarded by
        # self._lock; the registry walk in sample_once runs OUTSIDE it
        # (scrape callbacks read live engine state and the store's own
        # self-metrics — holding the lock across them would deadlock
        # the sampler against its own accounting).
        self._series: dict[
            str, dict[tuple[tuple[str, str], ...],
                      deque[tuple[float, float]]]] = {}
        self._dropped_series = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = self._registry
        g_series = reg.gauge(
            "runbook_tsdb_series",
            "Distinct series held by the embedded time-series store "
            "(obs/tsdb.py; bounded by llm.obs.tsdb.max_series)")
        g_series.set_function(lambda: float(self._count_series()))
        self._c_samples = reg.counter(
            "runbook_tsdb_samples_total",
            "Samples appended to the embedded time-series store "
            "(registry sweeps + direct ingests; drops past the series "
            "cap are not counted)")
        g_mem = reg.gauge(
            "runbook_tsdb_memory_bytes",
            "Estimated bytes held by the embedded time-series store's "
            "rings (accounting model, not a profiler reading)")
        g_mem.set_function(lambda: float(self._estimate_bytes()))

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "MetricsTSDB":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="tsdb-sampler")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — history must survive a
                import logging  # scrape hiccup; the next tick retries

                logging.getLogger(__name__).exception("tsdb sample failed")
            self._stop.wait(self.interval_s)

    # ------------------------------------------------------------ sampling

    def sample_once(self, now: Optional[float] = None) -> int:
        """One registry sweep at ``now`` (public — bench and tests drive
        the store deterministically without the thread). Returns the
        number of samples appended. A series the registry exposes
        nothing for this tick stores nothing — absence, never zero."""
        now = float(self._clock() if now is None else now)
        scraped: list[tuple[str, tuple[tuple[str, str], ...], float]] = []
        for metric in self._registry:
            for suffix, labels, value in metric.samples():
                scraped.append((metric.name + suffix, labels, value))
        appended = 0
        with self._lock:
            for name, labels, value in scraped:
                if self._append_locked(now, name, labels, value):
                    appended += 1
        if appended:
            self._c_samples.inc(appended)
        return appended

    def ingest(self, now: float, name: str,
               labels: Any = (), value: float = 0.0) -> bool:
        """Append one sample directly (series the registry does not
        carry: the incident monitor's per-poll detector readings, test
        fixtures). ``labels`` is a dict or an iterable of (k, v)."""
        items = labels.items() if isinstance(labels, dict) else labels
        key = tuple(sorted((str(k), str(v)) for k, v in items))
        with self._lock:
            ok = self._append_locked(float(now), str(name), key,
                                     float(value))
        if ok:
            self._c_samples.inc()
        return ok

    def _append_locked(self, now: float, name: str,
                       labels: tuple[tuple[str, str], ...],
                       value: float) -> bool:
        labels = tuple(sorted(labels))
        by_labels = self._series.get(name)
        if by_labels is None:
            by_labels = self._series[name] = {}
        ring = by_labels.get(labels)
        if ring is None:
            if self._count_series_locked() >= self.max_series:
                self._dropped_series += 1
                if not by_labels:
                    del self._series[name]
                return False
            ring = by_labels[labels] = deque(maxlen=self._ring_cap)
        ring.append((now, float(value)))
        floor = now - self.retention_s
        while ring and ring[0][0] < floor:
            ring.popleft()
        return True

    # ------------------------------------------------------------- reading

    def select(self, name: str, start: Optional[float] = None,
               end: Optional[float] = None,
               ) -> list[tuple[dict[str, str],
                               list[tuple[float, float]]]]:
        """Every series named ``name`` restricted to the CLOSED window
        ``[start, end]`` — ``(labels, samples)`` pairs sorted by
        canonical labels; series with no sample in the window are
        omitted (absence-not-zero, end to end)."""
        out: list[tuple[dict[str, str], list[tuple[float, float]]]] = []
        with self._lock:
            for labels, ring in self._series.get(name, {}).items():
                pts = [(ts, v) for ts, v in ring
                       if (start is None or ts >= start)
                       and (end is None or ts <= end)]
                if pts:
                    out.append((dict(labels), pts))
        out.sort(key=lambda row: sorted(row[0].items()))
        return out

    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(n for n, d in self._series.items() if d)

    def _count_series_locked(self) -> int:
        return sum(len(d) for d in self._series.values())

    def _count_series(self) -> int:
        with self._lock:
            return self._count_series_locked()

    def _estimate_bytes(self) -> int:
        with self._lock:
            n_series = self._count_series_locked()
            n_samples = sum(len(r) for d in self._series.values()
                            for r in d.values())
        return n_samples * _SAMPLE_BYTES + n_series * _SERIES_OVERHEAD_BYTES

    def snapshot(self) -> dict[str, Any]:
        """The ``/healthz`` ``history`` block: store accounting, never
        sample payloads (those are what ``/debug/query`` is for)."""
        with self._lock:
            n_series = self._count_series_locked()
            n_samples = 0
            oldest: Optional[float] = None
            newest: Optional[float] = None
            for by_labels in self._series.values():
                for ring in by_labels.values():
                    if not ring:
                        continue
                    n_samples += len(ring)
                    first, last = ring[0][0], ring[-1][0]
                    oldest = first if oldest is None else min(oldest, first)
                    newest = last if newest is None else max(newest, last)
            dropped = self._dropped_series
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "retention_s": self.retention_s,
            "max_series": self.max_series,
            "series": n_series,
            "samples": n_samples,
            "dropped_series": dropped,
            "memory_bytes": (n_samples * _SAMPLE_BYTES
                             + n_series * _SERIES_OVERHEAD_BYTES),
            "oldest_ts": None if oldest is None else round(oldest, 3),
            "newest_ts": None if newest is None else round(newest, 3),
        }

    def clock(self) -> float:
        """The store's injected clock — evaluation 'now' defaults to it
        so queries and samples share one time base."""
        return float(self._clock())

    # ------------------------------------------------------------ factory

    @classmethod
    def from_config(cls, llm_cfg: Any,
                    registry: Optional[metrics_mod.MetricsRegistry] = None,
                    ) -> Optional["MetricsTSDB"]:
        """Build from ``llm.obs.tsdb``; None when the obs layer or the
        store is disabled — zero ``runbook_tsdb_*`` series, and every
        surface on top (``/debug/query``, the ``/healthz`` history
        block, bundle lookback history) reports itself absent."""
        obs_cfg = getattr(llm_cfg, "obs", None)
        if obs_cfg is None or not getattr(obs_cfg, "enabled", False):
            return None
        tsdb_cfg = getattr(obs_cfg, "tsdb", None)
        if tsdb_cfg is None or not getattr(tsdb_cfg, "enabled", True):
            return None
        return cls(
            interval_s=getattr(tsdb_cfg, "interval_s", 1.0),
            retention_s=getattr(tsdb_cfg, "retention_s", 600.0),
            max_series=getattr(tsdb_cfg, "max_series", 2048),
            registry=registry)


__all__ = ["MetricsTSDB"]
