"""Continuous workload observation (``runbookai_tpu/obs``).

The observation half of ROADMAP item 3's closed tuning loop: fold the
flight recorder's step records and the engine's request stream into the
autotuner's ``Workload`` schema, score the live fingerprint's drift
against the serving plan's provenance workload, and export a composite
per-replica health signal. Read-only by design — nothing here changes a
plan or moves traffic, so byte-identity with an unmonitored engine is
structural (pinned by tests/test_obs.py).
"""

from runbookai_tpu.obs.fingerprint import (
    DEFAULT_DRIFT_THRESHOLD,
    DESCRIPTOR_KEYS,
    RequestSample,
    WorkloadFingerprinter,
    build_fingerprint,
    descriptor_json,
    drift_score,
)
from runbookai_tpu.obs.monitor import (
    FingerprintHistory,
    WorkloadMonitor,
    reference_descriptor,
    replica_health,
)

__all__ = [
    "DEFAULT_DRIFT_THRESHOLD",
    "DESCRIPTOR_KEYS",
    "FingerprintHistory",
    "RequestSample",
    "WorkloadFingerprinter",
    "WorkloadMonitor",
    "build_fingerprint",
    "descriptor_json",
    "drift_score",
    "reference_descriptor",
    "replica_health",
]
