"""Continuous workload observation (``runbookai_tpu/obs``).

The observation half of ROADMAP item 3's closed tuning loop: fold the
flight recorder's step records and the engine's request stream into the
autotuner's ``Workload`` schema, score the live fingerprint's drift
against the serving plan's provenance workload, and export a composite
per-replica health signal. Read-only by design — nothing here changes a
plan or moves traffic, so byte-identity with an unmonitored engine is
structural (pinned by tests/test_obs.py).

Incident detection rides on top (``detect.py`` pure, ``incident.py``
live): the exported signals fold into an incident lifecycle with
hysteresis, and every open preserves a content-hashed black-box bundle
— the fleet writes its own postmortems (tests/test_incident.py).

Every signal also has a history (``tsdb.py`` store, ``query.py``
PromQL-lite): a bounded injected-clock ring samples the live metrics
registry, queries evaluate as pure functions of (store, expr, now),
incident bundles embed a pre-open lookback window, and the soak gate
asserts its invariants as queries (tests/test_tsdb.py).
"""

from runbookai_tpu.obs.detect import (
    COVERAGE_REQUIRED_KINDS,
    FAULT_SIGNAL_CLASSES,
    INCIDENT_SCHEMA_VERSION,
    INCIDENT_SIGNALS,
    IncidentDetector,
    SignalPolicy,
    default_policies,
    incidents_json,
)
from runbookai_tpu.obs.fingerprint import (
    DEFAULT_DRIFT_THRESHOLD,
    DESCRIPTOR_KEYS,
    RequestSample,
    WorkloadFingerprinter,
    build_fingerprint,
    descriptor_json,
    drift_score,
)
from runbookai_tpu.obs.incident import (
    BUNDLE_SCHEMA_VERSION,
    HISTORY_SCHEMA_VERSION,
    SIGNAL_SERIES,
    IncidentMonitor,
    bundle_hash,
    list_bundles,
    load_bundle,
    verify_bundle,
    write_bundle,
)
from runbookai_tpu.obs.query import (
    QueryError,
    evaluate,
    evaluate_json,
    result_json,
)
from runbookai_tpu.obs.tsdb import MetricsTSDB
from runbookai_tpu.obs.monitor import (
    FingerprintHistory,
    WorkloadMonitor,
    reference_descriptor,
    replica_health,
)

__all__ = [
    "BUNDLE_SCHEMA_VERSION",
    "COVERAGE_REQUIRED_KINDS",
    "DEFAULT_DRIFT_THRESHOLD",
    "DESCRIPTOR_KEYS",
    "FAULT_SIGNAL_CLASSES",
    "FingerprintHistory",
    "HISTORY_SCHEMA_VERSION",
    "INCIDENT_SCHEMA_VERSION",
    "INCIDENT_SIGNALS",
    "IncidentDetector",
    "IncidentMonitor",
    "MetricsTSDB",
    "QueryError",
    "SIGNAL_SERIES",
    "RequestSample",
    "SignalPolicy",
    "WorkloadFingerprinter",
    "WorkloadMonitor",
    "build_fingerprint",
    "bundle_hash",
    "default_policies",
    "descriptor_json",
    "drift_score",
    "evaluate",
    "evaluate_json",
    "incidents_json",
    "list_bundles",
    "load_bundle",
    "reference_descriptor",
    "replica_health",
    "result_json",
    "verify_bundle",
    "write_bundle",
]
