"""Live workload fingerprinting: what the fleet actually serves, in the
autotuner's own vocabulary.

The autotuner (PR 6) searches from a hand-written
:class:`~runbookai_tpu.autotune.cost_model.Workload` descriptor; the
flight recorder (PR 7) already observes the real traffic — this module is
the missing link of ROADMAP item 3's "virtuous cycle" (FlashInfer-Bench /
AIConfigurator, PAPERS.md): fold what the engine *observes* into what the
tuner *consumes*, continuously, so a serving plan's staleness becomes a
measured number instead of a slow throughput regression.

Three layers, deliberately separated so determinism is testable:

- **Pure functions** (``summarize_requests`` / ``summarize_steps`` /
  ``build_fingerprint`` / ``drift_score``): identical inputs produce
  byte-identical JSON (every float rounded at a fixed precision, keys
  emitted in one order) — flight-recorder fixtures double as fingerprint
  fixtures, pinned by ``tests/test_obs.py``.
- :class:`WorkloadFingerprinter`: the live accumulator. Engine request
  taps (``EngineCore.workload_tap`` — one O(1) deque append per finished
  request, never on the dispatch path) feed a bounded sliding window;
  ``fingerprint()`` joins the window's request samples with the flight
  recorder's step records and the engine metrics dict into one
  fingerprint whose ``workload`` block is a valid tuner descriptor.
- ``drift_score``: a bounded [0, 1] distance between a live descriptor
  and a reference one (the serving plan's provenance workload, or the
  configured descriptor when no plan is pinned). Scale dimensions
  (prompt/output length, concurrency) compare on a saturating log-ratio;
  share dimensions (guided, speculation) on absolute difference — so
  "2x the prompt length" and "guided traffic appeared" both move the
  score visibly while neither can swamp it past 1.

Empty/warmup windows fingerprint as ``None`` — absence, never a
reassuring drift of 0 (the same contract as ``runbook_slo_*``).
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence

from runbookai_tpu.utils.trace import _percentile

# Workload descriptor keys, in emission order (must stay exactly
# autotune.cost_model.Workload.to_dict()'s key set so an emitted
# descriptor feeds `runbook tune --workload` unchanged — pinned by test).
DESCRIPTOR_KEYS = ("prompt_len", "output_len", "concurrency",
                   "guided_share", "spec_hit_rate")

# Default "plan is stale" drift threshold (llm.obs.drift_threshold):
# roughly "one scale dimension doubled AND a share appeared", or any
# single dimension moving ~4x alone. Calibrated against the bench --shift
# scenario (short-chat -> long-context/guided crosses it; steady traffic
# against its own descriptor stays well under).
DEFAULT_DRIFT_THRESHOLD = 0.35


@dataclass(frozen=True)
class RequestSample:
    """One finished engine request, as the tap records it."""

    ts: float
    prompt_tokens: int
    output_tokens: int
    cached_tokens: int = 0
    guided: bool = False
    forced_sync: bool = False
    aborted: bool = False

    def to_dict(self) -> dict[str, Any]:
        return {"ts": self.ts, "prompt_tokens": self.prompt_tokens,
                "output_tokens": self.output_tokens,
                "cached_tokens": self.cached_tokens,
                "guided": self.guided, "forced_sync": self.forced_sync,
                "aborted": self.aborted}


# ------------------------------------------------------------ pure layer


def _round(value: float, digits: int = 4) -> float:
    """One rounding rule for every emitted float: byte-stable JSON."""
    return round(float(value), digits)


def summarize_requests(samples: Sequence[RequestSample],
                       t0: float, t1: float) -> Optional[dict[str, Any]]:
    """Distribution summary of the window's COMPLETED requests (aborted
    ones count toward the mix, never toward length stats). None when the
    window holds no completed request — the absence contract."""
    window = [s for s in samples if t0 <= s.ts <= t1]
    done = [s for s in window if not s.aborted]
    if not done:
        return None
    prompts = sorted(float(s.prompt_tokens) for s in done)
    outputs = sorted(float(s.output_tokens) for s in done)
    n = len(done)
    prompt_total = sum(s.prompt_tokens for s in done)
    cached_total = sum(min(s.cached_tokens, s.prompt_tokens) for s in done)
    return {
        "samples": n,
        "aborted": len(window) - n,
        "prompt_tokens": {
            "mean": _round(sum(prompts) / n, 2),
            "p50": _round(_percentile(prompts, 50), 2),
            "p95": _round(_percentile(prompts, 95), 2),
        },
        "output_tokens": {
            "mean": _round(sum(outputs) / n, 2),
            "p50": _round(_percentile(outputs, 50), 2),
            "p95": _round(_percentile(outputs, 95), 2),
        },
        "guided_share": _round(sum(1 for s in done if s.guided) / n),
        "forced_sync_share": _round(
            sum(1 for s in done if s.forced_sync) / n),
        "prefix_cache_share": _round(
            cached_total / prompt_total if prompt_total else 0.0),
    }


def summarize_steps(steps: Sequence[dict[str, Any]],
                    t0: float, t1: float) -> dict[str, Any]:
    """Concurrency summary from flight-recorder step records in the
    window: live decode-batch occupancy plus the queued backlog is the
    offered-concurrency estimate the tuner's ``concurrency`` knob means.
    Idle drain steps are excluded — a quiet engine ticking over must not
    dilute the concurrency the busy windows actually saw."""
    live = [r for r in steps
            if t0 <= float(r.get("ts", 0.0)) <= t1
            and r.get("kind") != "idle"]
    if not live:
        return {"steps": 0, "concurrency": None, "occupancy_p50": None}
    conc = sorted(float(r.get("batch", 0)) + float(r.get("queue_depth", 0))
                  for r in live)
    occ = sorted(float(r.get("occupancy", 0.0)) for r in live)
    return {
        "steps": len(live),
        "concurrency": {
            "mean": _round(sum(conc) / len(conc), 2),
            "p95": _round(_percentile(conc, 95), 2),
        },
        "occupancy_p50": _round(_percentile(occ, 50)),
    }


def build_fingerprint(samples: Sequence[RequestSample],
                      steps: Sequence[dict[str, Any]],
                      metrics: Optional[dict[str, Any]] = None, *,
                      model: str = "default",
                      window: tuple[float, float]) -> Optional[dict[str, Any]]:
    """The pure core: request samples + step records + the engine metrics
    dict -> one fingerprint whose ``workload`` block is a valid
    :class:`~runbookai_tpu.autotune.cost_model.Workload` descriptor.

    Deterministic by construction (identical inputs -> byte-identical
    ``descriptor_json``): no clocks, no randomness, fixed rounding.
    Returns None for an empty/warmup window — series absence, never a
    fingerprint of zeros that would score drift 0 against any plan.
    """
    t0, t1 = window
    req = summarize_requests(samples, t0, t1)
    if req is None:
        return None
    step = summarize_steps(steps, t0, t1)
    metrics = metrics or {}
    # Speculation hit rate in the tuner's unit: extra accepted tokens per
    # decode dispatch (engine-lifetime counters — speculation acceptance
    # moves slowly and a windowed ratio over few dispatches would be
    # noise dressed as signal).
    dispatches = float(metrics.get("decode_dispatches", 0) or 0)
    spec_rate = (float(metrics.get("spec_accepted", 0)) / dispatches
                 if dispatches else 0.0)
    if step["concurrency"] is not None:
        concurrency = max(1, int(math.ceil(step["concurrency"]["mean"])))
    else:
        # No non-idle step records in the window (recorder disabled, or
        # the ring aged out): there is NO concurrency evidence. Emit the
        # floor (1) — never the window's request COUNT, which would
        # overestimate a sequential workload by orders of magnitude and
        # false-trip runbook_plan_stale — and leave ``concurrency: None``
        # on the fingerprint so drift scoring can EXCLUDE the dimension
        # (``drift_score(..., skip=("concurrency",))``).
        concurrency = 1
    descriptor = {
        "prompt_len": max(1, int(round(req["prompt_tokens"]["p50"]))),
        "output_len": max(1, int(round(req["output_tokens"]["p50"]))),
        "concurrency": concurrency,
        "guided_share": req["guided_share"],
        "spec_hit_rate": _round(spec_rate),
    }
    return {
        "model": model,
        "window": {
            "from_ts": _round(t0, 3), "to_ts": _round(t1, 3),
            "span_s": _round(t1 - t0, 3),
            "samples": req["samples"], "aborted": req["aborted"],
            "steps": step["steps"],
        },
        "prompt_tokens": req["prompt_tokens"],
        "output_tokens": req["output_tokens"],
        "concurrency": step["concurrency"],
        "occupancy_p50": step["occupancy_p50"],
        "guided_share": req["guided_share"],
        "forced_sync_share": req["forced_sync_share"],
        "prefix_cache_share": req["prefix_cache_share"],
        "spec_hit_rate": _round(spec_rate),
        "workload": descriptor,
    }


def descriptor_json(fingerprint: dict[str, Any]) -> str:
    """Canonical JSON of a fingerprint's tuner descriptor — the bytes
    ``runbook workload --emit-descriptor`` writes and ``runbook tune
    --workload`` reads back unchanged."""
    return json.dumps(fingerprint["workload"], sort_keys=True, indent=2) + "\n"


def _scale_dist(live: float, ref: float) -> float:
    """Saturating log-ratio distance for scale dimensions: 0 when equal,
    ~0.41 at 2x, ~0.58 at 4x, asymptotically 1 — a 100x shift cannot
    swamp the composite past its bound."""
    live = max(float(live), 1e-9)
    ref = max(float(ref), 1e-9)
    d = abs(math.log(live / ref))
    return d / (d + 1.0)


def _share_dist(live: float, ref: float) -> float:
    return min(1.0, abs(float(live) - float(ref)))


# Drift weights per descriptor dimension (sum to 1.0 so the score is a
# bounded [0, 1] convex combination).
DRIFT_WEIGHTS = {
    "prompt_len": 0.25,
    "output_len": 0.15,
    "concurrency": 0.20,
    "guided_share": 0.25,
    "spec_hit_rate": 0.15,
}


_DRIFT_DIMS = (
    ("prompt_len", _scale_dist, 1),
    ("output_len", _scale_dist, 1),
    ("concurrency", _scale_dist, 1),
    ("guided_share", _share_dist, 0.0),
    ("spec_hit_rate", _share_dist, 0.0),
)


def drift_score(live: dict[str, Any], reference: dict[str, Any], *,
                skip: tuple[str, ...] = ()) -> float:
    """Bounded [0, 1] distance between a live descriptor and the
    reference (plan-provenance or configured) one. Deterministic: same
    inputs, same 6-decimal score. ``skip`` drops dimensions the live
    fingerprint has no evidence for (e.g. concurrency with the flight
    recorder disabled) — remaining weights re-normalize so the score
    stays a [0, 1] convex combination."""
    total_weight = 0.0
    score = 0.0
    for dim, dist, default in _DRIFT_DIMS:
        if dim in skip:
            continue
        weight = DRIFT_WEIGHTS[dim]
        total_weight += weight
        score += weight * dist(live.get(dim, default),
                               reference.get(dim, default))
    if total_weight <= 0:
        return 0.0
    return round(min(1.0, score / total_weight * sum(
        DRIFT_WEIGHTS.values())), 6)


# ------------------------------------------------------------ live layer


class WorkloadFingerprinter:
    """Sliding-window accumulator over one served model's cores.

    ``observe_request`` is the engine tap target: O(1) bounded-deque
    append under a private lock (finish paths run under each core's
    engine lock; a multi-replica group funnels several cores into one
    fingerprinter, so the deque needs its own). ``fingerprint()`` reads
    the cores' flight recorders and metrics dicts lock-free — the same
    torn-read tolerance as the scrape gauges.
    """

    def __init__(self, cores: Sequence[Any] = (), *,
                 model: str = "default", window_s: float = 300.0,
                 max_samples: int = 4096,
                 clock: Callable[[], float] = time.time):
        self.cores = list(cores)
        self.model = model
        self.window_s = float(window_s)
        # Injected clock seam (the supervisor's flap-damping pattern):
        # window math is a pure function of it, so interval/rotation
        # tests drive a fake clock instead of sleeping wall time.
        self._clock = clock
        self._samples: deque[RequestSample] = deque(maxlen=max(16,
                                                               max_samples))
        self._lock = threading.Lock()

    def install_taps(self) -> None:
        """Point every core's ``workload_tap`` at this fingerprinter."""
        for core in self.cores:
            core.workload_tap = self.observe_request

    def observe_request(self, req: Any) -> None:
        """Engine tap: one sample per finished request (any outcome)."""
        from runbookai_tpu.engine.request import FinishReason

        sampling = req.sampling
        sample = RequestSample(
            ts=self._clock(),
            prompt_tokens=len(req.prompt_ids),
            output_tokens=req.num_generated,
            cached_tokens=req.cached_tokens,
            guided=bool(sampling.guided),
            forced_sync=bool(sampling.forced_sync),
            aborted=req.finish_reason is FinishReason.ABORTED,
        )
        with self._lock:
            self._samples.append(sample)

    def reset(self) -> None:
        """Drop every sample (bench phase boundaries, warmup exclusion)."""
        with self._lock:
            self._samples.clear()

    @property
    def sample_count(self) -> int:
        return len(self._samples)

    def samples(self) -> list[RequestSample]:
        with self._lock:
            return list(self._samples)

    def _step_records(self, t0: float) -> list[dict[str, Any]]:
        records: list[dict[str, Any]] = []
        for core in self.cores:
            flight = getattr(core, "flight", None)
            if flight is None or not flight.enabled:
                continue
            records.extend(r for r in flight.snapshot()
                           if float(r.get("ts", 0.0)) >= t0)
        return records

    def _metrics(self) -> dict[str, float]:
        out: dict[str, float] = {}
        for core in self.cores:
            for key in ("spec_accepted", "spec_drafted",
                        "decode_dispatches"):
                out[key] = out.get(key, 0) + core.metrics.get(key, 0)
        return out

    def fingerprint(self, now: Optional[float] = None
                    ) -> Optional[dict[str, Any]]:
        """The window's fingerprint, or None while it is empty."""
        now = self._clock() if now is None else float(now)
        t0 = now - self.window_s
        return build_fingerprint(
            self.samples(), self._step_records(t0), self._metrics(),
            model=self.model, window=(t0, now))

    def descriptor(self, now: Optional[float] = None
                   ) -> Optional[dict[str, Any]]:
        fp = self.fingerprint(now)
        return None if fp is None else fp["workload"]


__all__ = [
    "DESCRIPTOR_KEYS", "DEFAULT_DRIFT_THRESHOLD", "DRIFT_WEIGHTS",
    "RequestSample", "WorkloadFingerprinter", "build_fingerprint",
    "descriptor_json", "drift_score", "summarize_requests",
    "summarize_steps",
]
