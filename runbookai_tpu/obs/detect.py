"""Streaming incident detection: the fleet decides "an incident is
happening" from the signals it already exports.

The serving stack measures SLO burn (utils/slo.py), workload drift and
replica health (obs/monitor.py), supervisor state transitions and chaos
fault provenance (runbookai_tpu/chaos), router sheds / stale rejections
(engine/fleet.py) and queue-wait percentiles (the PR 1 histograms) — but
until now nothing folded them into a verdict. This module is the PURE
half of that fold (AIBrix's self-healing-infrastructure argument and the
reference system's own incident-investigator framing both want the
serving layer to SAY when it is in an incident, not just export gauges):

- :data:`INCIDENT_SIGNALS` is the closed signal vocabulary — the
  ``signal`` metric label set, pre-created over this literal tuple
  (bounded-label contract, RBK010-clean with zero noqa sites).
- :class:`SignalPolicy` spells one signal's thresholds and hysteresis in
  both directions: a breach must PERSIST ``open_after_s`` before an
  incident opens (a one-poll blip is noise), and an open incident must
  stay CLEAR of ``resolve_at`` for ``resolve_after_s`` before it
  resolves (a reading inside the ``resolve_at``..``open_at`` band holds
  it open — flapping traffic cannot thrash open/resolve).
- :class:`IncidentDetector` folds ``(now, readings)`` observations into
  the incident lifecycle (open → update → resolve). Decisions are pure
  functions of the observed window: the clock is an input, readings are
  plain floats, ids are sequential — seeded fixtures replay to
  **byte-identical incident JSON** (:func:`incidents_json`, pinned by
  ``tests/test_incident.py``).

The live half — reading collection, bundle capture, metrics, the poll
thread — lives in :mod:`runbookai_tpu.obs.incident`; keeping it out of
this module is what makes detection replayable evidence.

Readings use the absence contract shared with ``runbook_slo_*`` /
``runbook_workload_*``: a signal with no evidence this poll (empty
histogram window, no workload monitor attached) is simply missing from
the reading — absence is never a breach, and for an OPEN incident it
counts toward resolution (the thing being measured went quiet).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Optional, Sequence

# The closed signal vocabulary. Metric children are pre-created over this
# tuple (obs/incident.py) and fault-coverage checks validate against it.
INCIDENT_SIGNALS = (
    "slo_burn",          # worst objective's current/target ratio
    "workload_drift",    # worst group's fingerprint drift score
    "replica_health",    # worst replica's composite health (low = bad)
    "replica_failure",   # replicas in failed/rebuilding/rejoining
    "router_shed",       # requests shed per poll (all replicas saturated)
    "router_stale",      # stale/rejected cross-replica pulls per poll
    "queue_wait",        # p95 submission→admission wait (s) this poll
)

# Incident JSON schema version (the bundle schema references it too).
INCIDENT_SCHEMA_VERSION = 1

# Which signal classes each injected fault kind is expected to surface
# as — the detection-coverage invariant's mapping (bench.py
# --soak-scenarios: every injected fault window must overlap a detected
# incident of a matching class). Kinds in COVERAGE_REQUIRED_KINDS are
# GATED (their detection path — supervisor transitions — is
# deterministic); the rest are reported in the coverage table but a miss
# does not fail the gate (a 10 ms kv_pull_delay legitimately detects as
# nothing).
FAULT_SIGNAL_CLASSES = {
    "replica_crash": ("replica_failure",),
    "replica_wedge": ("replica_failure",),
    "kv_pull_corrupt": ("router_stale",),
    "kv_pull_delay": ("router_stale", "queue_wait", "slo_burn"),
    "spill_pressure": ("queue_wait", "slo_burn", "replica_health"),
    "tenant_flood": ("router_shed", "queue_wait", "slo_burn"),
}
COVERAGE_REQUIRED_KINDS = ("replica_crash", "replica_wedge")


@dataclass(frozen=True)
class SignalPolicy:
    """Thresholds + two-way hysteresis for one signal.

    ``mode="gte"``: a reading >= ``open_at`` breaches, < ``resolve_at``
    clears (``resolve_at`` <= ``open_at``; between the two is the
    hysteresis band that holds an open incident open).
    ``mode="lte"`` inverts both for low-is-bad signals (replica_health).
    """

    signal: str
    open_at: float
    resolve_at: float
    mode: str = "gte"
    open_after_s: float = 0.0
    resolve_after_s: float = 5.0
    severity: str = "major"

    def __post_init__(self) -> None:
        if self.signal not in INCIDENT_SIGNALS:
            raise ValueError(f"unknown incident signal {self.signal!r}; "
                             f"valid: {INCIDENT_SIGNALS}")
        if self.mode not in ("gte", "lte"):
            raise ValueError(f"{self.signal}: mode must be gte or lte")
        band_ok = (self.resolve_at <= self.open_at if self.mode == "gte"
                   else self.resolve_at >= self.open_at)
        if not band_ok:
            raise ValueError(
                f"{self.signal}: resolve_at must sit on the clear side of "
                f"open_at (hysteresis band, not an inversion)")

    def breached(self, value: float) -> bool:
        return (value >= self.open_at if self.mode == "gte"
                else value <= self.open_at)

    def cleared(self, value: float) -> bool:
        return (value < self.resolve_at if self.mode == "gte"
                else value > self.resolve_at)

    def worse(self, value: float, than: float) -> bool:
        return value > than if self.mode == "gte" else value < than


def default_policies(*, drift_threshold: float = 0.6,
                     open_after_s: float = 5.0,
                     resolve_after_s: float = 10.0,
                     ) -> tuple[SignalPolicy, ...]:
    """The stock policy set. ``open_after_s``/``resolve_after_s`` scale
    the level-signal hysteresis (``llm.obs.incident_open_s`` /
    ``incident_resolve_s``); event-shaped signals keep their own
    constants where a single observation IS the incident (a replica in
    ``failed`` needs no persistence proof — the supervisor already
    debounced it)."""
    return (
        # Sustained burn past 1.5x target; clears under 1.1x.
        SignalPolicy("slo_burn", 1.5, 1.1, open_after_s=open_after_s,
                     resolve_after_s=resolve_after_s, severity="major"),
        # The plan-staleness threshold, held long enough to be traffic
        # and not a window artifact. Minor: drift is a retune trigger,
        # not an outage.
        SignalPolicy("workload_drift", drift_threshold,
                     0.8 * drift_threshold, open_after_s=open_after_s,
                     resolve_after_s=resolve_after_s, severity="minor"),
        # A replica pinned near zero composite health.
        SignalPolicy("replica_health", 0.1, 0.25, mode="lte",
                     open_after_s=open_after_s,
                     resolve_after_s=resolve_after_s, severity="major"),
        # Any replica the supervisor holds in failed/rebuilding/
        # rejoining: open immediately (the supervisor's own state machine
        # is the debounce), resolve once the fleet is whole again.
        SignalPolicy("replica_failure", 1.0, 1.0, open_after_s=0.0,
                     resolve_after_s=resolve_after_s, severity="critical"),
        # Sheds sustained for a full second = real saturation; a single
        # raced shed is load-shedding doing its job.
        SignalPolicy("router_shed", 1.0, 1.0, open_after_s=1.0,
                     resolve_after_s=resolve_after_s, severity="major"),
        # A rejected (stale/corrupt) pull is incident-worthy on sight —
        # digest mismatches especially are evidence to preserve.
        SignalPolicy("router_stale", 1.0, 1.0, open_after_s=0.0,
                     resolve_after_s=resolve_after_s, severity="major"),
        # p95 queue wait in whole-seconds territory, sustained.
        SignalPolicy("queue_wait", 10.0, 5.0, open_after_s=open_after_s,
                     resolve_after_s=resolve_after_s, severity="minor"),
    )


@dataclass
class _SignalState:
    breach_since: Optional[float] = None
    clear_since: Optional[float] = None


class IncidentDetector:
    """Fold ``(now, readings)`` into the incident lifecycle.

    NOT thread-safe: the caller (obs/incident.IncidentMonitor) serializes
    ``observe`` under its own lock; fixtures drive it single-threaded.
    At most one open incident per signal — concurrent breaches of one
    signal are one incident with updates, which is what an operator wants
    paged about once.
    """

    def __init__(self, policies: Optional[Sequence[SignalPolicy]] = None):
        policies = tuple(policies) if policies is not None \
            else default_policies()
        self.policies = {p.signal: p for p in policies}
        if len(self.policies) != len(policies):
            raise ValueError("duplicate signal policies")
        self._state = {s: _SignalState() for s in self.policies}
        self._open: dict[str, dict[str, Any]] = {}
        self.resolved: list[dict[str, Any]] = []
        self._seq = 0

    # ------------------------------------------------------------- fold

    def observe(self, now: float, readings: dict[str, Any],
                ) -> list[tuple[str, dict[str, Any]]]:
        """One detection fold: returns ``[(event, incident), ...]`` where
        event is ``open`` / ``update`` / ``resolve``. Pure in
        ``(now, readings, prior folds)`` — same sequence in, same events
        and byte-identical incident docs out."""
        now = float(now)
        events: list[tuple[str, dict[str, Any]]] = []
        for signal, policy in self.policies.items():
            value = readings.get(signal)
            value = None if value is None else float(value)
            st = self._state[signal]
            inc = self._open.get(signal)
            breaching = value is not None and policy.breached(value)
            if inc is None:
                if not breaching:
                    st.breach_since = None
                    continue
                if st.breach_since is None:
                    st.breach_since = now
                if now - st.breach_since >= policy.open_after_s:
                    inc = self._open_incident(signal, policy, now, value,
                                              st.breach_since)
                    st.clear_since = None
                    events.append(("open", inc))
                continue
            # Open incident: track peak / last breach, or progress the
            # resolve hysteresis. A reading inside the band (cleared by
            # neither test) resets the resolve clock without counting as
            # a fresh breach.
            if breaching:
                st.clear_since = None
                inc["last_breach_ts"] = round(now, 3)
                if policy.worse(value, inc["peak"]):
                    inc["peak"] = round(value, 6)
                    events.append(("update", inc))
            elif value is None or policy.cleared(value):
                if st.clear_since is None:
                    st.clear_since = now
                if now - st.clear_since >= policy.resolve_after_s:
                    self._resolve(inc, now)
                    st.breach_since = None
                    st.clear_since = None
                    events.append(("resolve", inc))
            else:
                st.clear_since = None
        return events

    def _open_incident(self, signal: str, policy: SignalPolicy,
                       now: float, value: float,
                       breach_since: float) -> dict[str, Any]:
        self._seq += 1
        inc = {
            "schema_version": INCIDENT_SCHEMA_VERSION,
            "id": f"inc-{self._seq:04d}",
            "signal": signal,
            "severity": policy.severity,
            "status": "open",
            "threshold": round(policy.open_at, 6),
            "mode": policy.mode,
            "breach_started_ts": round(breach_since, 3),
            "opened_ts": round(now, 3),
            "value_at_open": round(value, 6),
            "peak": round(value, 6),
            "last_breach_ts": round(now, 3),
            "resolved_ts": None,
            "duration_s": None,
            "context": {},
        }
        self._open[signal] = inc
        return inc

    def _resolve(self, inc: dict[str, Any], now: float) -> None:
        inc["status"] = "resolved"
        inc["resolved_ts"] = round(now, 3)
        inc["duration_s"] = round(now - inc["opened_ts"], 3)
        del self._open[inc["signal"]]
        self.resolved.append(inc)

    # ---------------------------------------------------------- surface

    def open_incidents(self) -> list[dict[str, Any]]:
        """Open incidents, oldest first (id order)."""
        return sorted(self._open.values(), key=lambda i: i["id"])

    def incidents(self) -> list[dict[str, Any]]:
        """Every incident this detector ever opened, in id order."""
        return sorted([*self.resolved, *self._open.values()],
                      key=lambda i: i["id"])


def incidents_json(incidents: Sequence[dict[str, Any]]) -> str:
    """Canonical JSON of a detector's incident list — the byte-identity
    surface the determinism tests pin (fixed key order, fixed rounding
    already applied at emission)."""
    return json.dumps(list(incidents), sort_keys=True, indent=2) + "\n"


__all__ = [
    "COVERAGE_REQUIRED_KINDS", "FAULT_SIGNAL_CLASSES",
    "INCIDENT_SCHEMA_VERSION", "INCIDENT_SIGNALS", "IncidentDetector",
    "SignalPolicy", "default_policies", "incidents_json",
]
