"""PromQL-lite over the embedded time-series store (obs/tsdb.py).

A deliberately small query language so the soak gate, the incident
monitor, ``GET /debug/query`` and ``runbook query`` all speak ONE
dialect that transfers to real Prometheus (docs/observability.md
"Metric history & query" has the grammar and the mapping table):

- instant selector          ``runbook_kv_pages_in_use{replica="0"}``
- label matchers            ``=``, ``!=``, ``=~``, ``!~`` (full-match)
- ``rate(sel[5m])``         per-second increase, counter-reset aware
- ``increase(sel[5m])``     total increase, counter-reset aware
- ``avg/min/max_over_time(sel[5m])``
- ``histogram_quantile(0.95, runbook_ttft_seconds_bucket[5m])``
  over bucket-snapshot increases (the shared
  :func:`~runbookai_tpu.utils.metrics.percentile_from_counts`
  interpolation — the same math as the feedback controller's burn
  windows and the incident monitor's queue-wait reading).

Evaluation is a **pure function of (store contents, query, now)**: no
wall clock, no randomness, values rounded at emission, results sorted
by canonical labels — the same fixture, query and ``now`` produce
byte-identical :func:`result_json` output (pinned by
tests/test_tsdb.py). Windows are CLOSED ``[now - range, now]``;
``rate``/``increase`` need at least two samples in the window and a
window with too little data yields an EMPTY result — absence, never
zero (the ``runbook_slo_*`` contract, carried through the store).
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional, Sequence

from runbookai_tpu.utils.metrics import percentile_from_counts

# Default window when a range function's selector carries no explicit
# [d] (the server's ?range= and the CLI's --range override it).
DEFAULT_RANGE_S = 300.0

_RANGE_FUNCS = ("rate", "increase", "avg_over_time", "min_over_time",
                "max_over_time")

_DURATION_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(ms|s|m|h|d)\s*$")
_DURATION_UNITS = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0,
                   "d": 86400.0}

_SELECTOR_RE = re.compile(
    r"^\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)"
    r"(?:\{(?P<matchers>[^}]*)\})?"
    r"(?:\[(?P<range>[^\]]+)\])?\s*$")

_MATCHER_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!~|!=|=)\s*"((?:[^"\\]|\\.)*)"\s*')


class QueryError(ValueError):
    """Unparseable expression / bad operand — surfaces as HTTP 400."""


def parse_duration(text: str) -> float:
    m = _DURATION_RE.match(str(text))
    if m is None:
        raise QueryError(f"bad duration {text!r} (want e.g. 30s, 5m, 1h)")
    return float(m.group(1)) * _DURATION_UNITS[m.group(2)]


def _parse_matchers(body: str) -> list[tuple[str, str, str]]:
    matchers: list[tuple[str, str, str]] = []
    pos = 0
    body = body.strip()
    while pos < len(body):
        m = _MATCHER_RE.match(body, pos)
        if m is None:
            raise QueryError(f"bad label matcher near {body[pos:]!r}")
        label, op, value = m.group(1), m.group(2), m.group(3)
        value = value.replace('\\"', '"').replace("\\\\", "\\")
        if op in ("=~", "!~"):
            try:
                re.compile(value)
            except re.error as e:
                raise QueryError(
                    f"bad regex {value!r} for {label}: {e}") from e
        matchers.append((label, op, value))
        pos = m.end()
        if pos < len(body):
            if body[pos] != ",":
                raise QueryError(f"bad label matcher near {body[pos:]!r}")
            pos += 1
    return matchers


def _parse_selector(text: str) -> dict[str, Any]:
    m = _SELECTOR_RE.match(text)
    if m is None:
        raise QueryError(f"bad selector {text!r}")
    range_s = (parse_duration(m.group("range"))
               if m.group("range") is not None else None)
    matchers = (_parse_matchers(m.group("matchers"))
                if m.group("matchers") else [])
    return {"name": m.group("name"), "matchers": matchers,
            "range_s": range_s}


def parse(expr: str) -> dict[str, Any]:
    """Expression AST: ``{"fn", "q", "selector"}`` — ``fn`` is None for
    a bare (instant) selector, ``q`` only for histogram_quantile."""
    expr = str(expr).strip()
    if not expr:
        raise QueryError("empty expression")
    m = re.match(r"^([a-z_]+)\s*\((.*)\)\s*$", expr, re.DOTALL)
    if m is None:
        return {"fn": None, "q": None, "selector": _parse_selector(expr)}
    fn, args = m.group(1), m.group(2).strip()
    if fn == "histogram_quantile":
        head, sep, rest = args.partition(",")
        if not sep:
            raise QueryError(
                "histogram_quantile wants (q, name_bucket[range])")
        try:
            q = float(head.strip())
        except ValueError as e:
            raise QueryError(f"bad quantile {head.strip()!r}") from e
        if not 0.0 < q <= 1.0:
            raise QueryError(f"quantile must be in (0, 1], got {q}")
        selector = _parse_selector(rest.strip())
        if not selector["name"].endswith("_bucket"):
            raise QueryError(
                "histogram_quantile wants a _bucket selector, got "
                f"{selector['name']!r}")
        return {"fn": fn, "q": q, "selector": selector}
    if fn not in _RANGE_FUNCS:
        raise QueryError(
            f"unknown function {fn!r}; supported: "
            f"{', '.join((*_RANGE_FUNCS, 'histogram_quantile'))}")
    return {"fn": fn, "q": None, "selector": _parse_selector(args)}


# ---------------------------------------------------------------- matching


def _label_match(labels: dict[str, str],
                 matchers: Sequence[tuple[str, str, str]]) -> bool:
    for label, op, value in matchers:
        have = labels.get(label, "")
        if op == "=":
            ok = have == value
        elif op == "!=":
            ok = have != value
        elif op == "=~":
            ok = re.fullmatch(value, have) is not None
        else:  # !~
            ok = re.fullmatch(value, have) is None
        if not ok:
            return False
    return True


def match_series(series: Sequence[tuple[dict[str, str], list]],
                 matchers: Sequence[tuple[str, str, str]],
                 ) -> list[tuple[dict[str, str], list]]:
    """Filter ``store.select`` rows by label matchers."""
    return [(labels, pts) for labels, pts in series
            if _label_match(labels, matchers)]


# -------------------------------------------------------------- evaluation


def counter_increase(samples: Sequence[tuple[float, float]],
                     ) -> Optional[float]:
    """Total increase across ``samples``, counter-reset aware: a value
    going backwards means the counter restarted from zero, so the
    post-reset value itself is the contribution (the Prometheus
    ``increase`` reset rule, without its window extrapolation). None
    below two samples — one point carries no derivative."""
    if len(samples) < 2:
        return None
    inc = 0.0
    prev = samples[0][1]
    for _, value in samples[1:]:
        inc += (value - prev) if value >= prev else value
        prev = value
    return inc


def bucket_quantile(series: Sequence[tuple[dict[str, str], list]],
                    q: float) -> list[tuple[dict[str, str], float]]:
    """``histogram_quantile`` core over ``_bucket`` series rows: group
    by labels minus ``le``, diff each bucket's cumulative count across
    its window (reset-aware), convert to per-bucket counts and
    interpolate with the shared ``percentile_from_counts``. ``q`` is a
    ratio in (0, 1]. Groups whose window carries no observation are
    omitted (absence)."""
    groups: dict[tuple[tuple[str, str], ...],
                 list[tuple[float, Optional[float]]]] = {}
    for labels, pts in series:
        if "le" not in labels:
            continue
        le_raw = labels["le"]
        le = float("inf") if le_raw == "+Inf" else float(le_raw)
        key = tuple(sorted((k, v) for k, v in labels.items()
                           if k != "le"))
        groups.setdefault(key, []).append((le, counter_increase(pts)))
    out: list[tuple[dict[str, str], float]] = []
    for key, rows in sorted(groups.items()):
        rows = [(le, inc) for le, inc in rows if inc is not None]
        if not rows:
            continue
        rows.sort()
        cumulative = [max(0.0, inc) for _, inc in rows]
        counts = [cumulative[0]]
        counts += [max(0.0, b - a)
                   for a, b in zip(cumulative, cumulative[1:])]
        bounds = [le for le, _ in rows if le != float("inf")]
        if not bounds:
            continue
        if rows[-1][0] != float("inf"):
            counts.append(0.0)  # no +Inf series sampled: empty overflow
        value = percentile_from_counts(bounds, counts, q * 100.0)
        if value is not None:
            out.append((dict(key), value))
    return out


def _over_time(fn: str, values: Sequence[float]) -> float:
    if fn == "avg_over_time":
        return sum(values) / len(values)
    if fn == "min_over_time":
        return min(values)
    return max(values)


def evaluate(store: Any, expr: str, *, now: Optional[float] = None,
             default_range_s: float = DEFAULT_RANGE_S) -> dict[str, Any]:
    """Evaluate ``expr`` against ``store`` at ``now`` (store clock when
    None). Pure: same store contents + expr + now ⇒ the same document,
    and :func:`result_json` makes that byte-identical. Instant
    selectors return each series' LATEST sample inside the window
    (staleness bound = the window)."""
    ast = parse(expr)
    now = float(store.clock() if now is None else now)
    selector = ast["selector"]
    range_s = (selector["range_s"] if selector["range_s"] is not None
               else float(default_range_s))
    if range_s <= 0:
        raise QueryError(f"range must be > 0, got {range_s}")
    series = match_series(
        store.select(selector["name"], now - range_s, now),
        selector["matchers"])
    fn = ast["fn"]
    rows: list[tuple[dict[str, str], float]] = []
    if fn is None:
        for labels, pts in series:
            rows.append(({"__name__": selector["name"], **labels},
                         pts[-1][1]))
    elif fn in ("rate", "increase"):
        for labels, pts in series:
            inc = counter_increase(pts)
            if inc is None:
                continue
            if fn == "rate":
                span = pts[-1][0] - pts[0][0]
                if span <= 0:
                    continue
                rows.append((labels, inc / span))
            else:
                rows.append((labels, inc))
    elif fn in ("avg_over_time", "min_over_time", "max_over_time"):
        for labels, pts in series:
            rows.append((labels, _over_time(fn, [v for _, v in pts])))
    else:  # histogram_quantile
        rows = bucket_quantile(series, ast["q"])
    rows.sort(key=lambda row: sorted(row[0].items()))
    return {
        "expr": expr,
        "now": round(now, 3),
        "range_s": round(range_s, 3),
        "result": [{"metric": dict(sorted(labels.items())),
                    "value": round(float(value), 9)}
                   for labels, value in rows],
    }


def result_json(doc: dict[str, Any]) -> str:
    """Canonical bytes of an :func:`evaluate` document — THE form the
    determinism pin compares and ``GET /debug/query`` serves."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def evaluate_json(store: Any, expr: str, *, now: Optional[float] = None,
                  default_range_s: float = DEFAULT_RANGE_S) -> str:
    return result_json(evaluate(store, expr, now=now,
                                default_range_s=default_range_s))


__all__ = [
    "DEFAULT_RANGE_S", "QueryError", "bucket_quantile",
    "counter_increase", "evaluate", "evaluate_json", "match_series",
    "parse", "parse_duration", "result_json",
]
