"""HLO byte accounting: perf claims falsifiable without hardware.

Decode is HBM-bandwidth-bound: per generated token the program must read
each weight matrix once at its STORED width (int8 for quantized leaves)
plus the live KV pages — nothing else of that magnitude. The r3 on-chip
measurement (209.9 tok/s at ~27% of its own roofline) had the signature
of an unfused dequantization: XLA materializing a bf16 copy of each int8
weight, tripling the bytes (read int8 + write bf16 + read bf16). This
module turns that diagnosis from an argument into assertions on the
COMPILED program (VERDICT r4 next-round #2):

- :func:`wide_weight_materializations` scans optimized HLO for any
  instruction materializing a wide-dtype tensor exactly the size of a
  quantized weight (full stacked tensor or per-layer slice) — the
  smoking gun, mechanically detected. Fusion-body lines are excluded:
  values inside a fusion computation are virtual; only fusion roots and
  top-level/loop-body instructions own buffers.
- :func:`lower_decode` lowers+compiles the engine's REAL decode dispatch
  (the same jitted ``_decode_step`` serving uses) without executing it,
  so the analysis covers the program that runs, not a proxy.
- :func:`decode_accounting` reports the compiled program's
  ``memory_analysis()`` / ``cost_analysis()`` next to the mechanical
  expectation (weight bytes at stored width + KV pool + small operands),
  and :func:`check_plan` cross-checks :mod:`~runbookai_tpu.engine.
  memory_plan` arithmetic against a live engine's actual allocations
  (VERDICT r4 weak #4: plans were hand arithmetic, never validated).

The reference has no counterpart (it calls hosted LLM APIs —
SURVEY.md §2.2); this is the TPU serving stack's self-audit.
"""

from __future__ import annotations

import math
import re
from typing import Any, Iterable

import jax
import jax.numpy as jnp

# Dtype widths as HLO spells them; int8/u8/fp8 (1 byte) are the stored
# widths — materializing THOSE is fine, the hazard is 2+ byte copies.
_WIDE_DTYPES = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8}

# `%name = dtype[dims]{layout} op(...)` — optimized HLO instruction line.
_INSTR = re.compile(r"^(?:ROOT\s+)?%?[\w.\-]+\s*=\s*([a-z0-9]+)\[([\d,]*)\]")
_COMPUTATION = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?\s*->.*\{")


def quantized_weight_shapes(params: Any) -> set[tuple[int, ...]]:
    """Dims of every quantized weight tensor, its per-layer slice, and
    the slice's keep-dims form — the exact shapes a materialized dequant
    would take in the compiled program. Matching on full dims tuples
    (not element counts) keeps activation tensors that happen to share a
    product out of the hunt."""
    shapes: set[tuple[int, ...]] = set()

    def visit(node: Any) -> None:
        if isinstance(node, dict):
            if "q" in node and "s" in node and hasattr(node["q"], "shape"):
                q = node["q"]
                shapes.add(tuple(q.shape))
                if q.ndim >= 3:
                    shapes.add(tuple(q.shape[1:]))
                    shapes.add((1,) + tuple(q.shape[1:]))
            else:
                for v in node.values():
                    visit(v)

    visit(params)
    return shapes


def wide_weight_materializations(
    hlo_text: str, weight_shapes: Iterable[tuple[int, ...]]
) -> list[str]:
    """Offending lines: instructions in optimized HLO whose result is a
    wide-dtype (>= 2 byte) buffer with exactly a quantized weight's dims
    (full stacked tensor, per-layer slice, or keep-dims slice). Lines
    inside fusion computations are skipped (virtual values); fusion
    ROOTS appear at their call sites and are caught."""
    targets = {tuple(s) for s in weight_shapes}
    bad: list[str] = []
    in_fused_body = False
    depth = 0
    for raw in hlo_text.splitlines():
        line = raw.strip()
        comp = _COMPUTATION.match(line)
        if comp is not None and line.endswith("{"):
            name = comp.group(1)
            # ONLY fusion computations hold virtual values. Loop/scan
            # bodies and reduction combinators are scanned too: while-body
            # instructions own buffers (a per-layer dequant inside the
            # scan over layers is exactly the hazard), and combinator
            # regions are scalar so they can never match a weight shape.
            in_fused_body = "fused" in name
            depth = 1
            continue
        if depth:
            depth += line.count("{") - line.count("}")
            if depth <= 0:
                in_fused_body = False
                depth = 0
                continue
        if in_fused_body:
            continue
        m = _INSTR.match(line)
        if m is None or "parameter(" in line:
            continue
        dtype, dims = m.group(1), m.group(2)
        if dtype not in _WIDE_DTYPES or not dims:
            continue
        if tuple(int(d) for d in dims.split(",")) in targets:
            bad.append(line[:200])
    return bad


def lower_decode(core, *, qmm_impl: str | None = None,
                 attn_impl: str | None = None):
    """Lower + compile the engine's single-token decode dispatch — the
    exact jitted function and argument shapes ``EngineCore._run_decode``
    uses — WITHOUT executing it (donation only applies on execute, so
    the live pool buffers are safe to pass)."""
    from runbookai_tpu.engine.engine import _decode_step

    b = core.ecfg.max_batch_slots
    tables = jnp.zeros((b, core.kv.max_pages_per_seq + 1), jnp.int32)
    return _decode_step.lower(
        core.params, core.cfg,
        jnp.zeros((b, 1), jnp.int32), jnp.zeros((b, 1), jnp.int32),
        core._kv_k, core._kv_v, tables,
        jnp.ones((b,), jnp.int32),
        jnp.zeros((b,), jnp.float32), jnp.ones((b,), jnp.float32),
        jnp.zeros((b,), jnp.int32), jax.random.PRNGKey(0), None,
        jnp.zeros((b,), jnp.int32),
        page_size=core.ecfg.page_size, block_pages=core.ecfg.block_pages,
        attn_impl=attn_impl if attn_impl is not None else core.ecfg.attn_impl,
        mesh=core.mesh,
        qmm_impl=qmm_impl if qmm_impl is not None else core.ecfg.qmm_impl,
    ).compile()


def param_nbytes(params: Any) -> int:
    return sum(leaf.nbytes for leaf in jax.tree.leaves(params))


def kv_pool_nbytes(core) -> int:
    # int8 pools are (values, scales) tuples — sum the pytree leaves.
    return sum(leaf.nbytes
               for leaf in jax.tree.leaves((core._kv_k, core._kv_v)))


def decode_accounting(core, compiled=None) -> dict[str, float]:
    """Mechanical byte accounting of the compiled decode program.

    ``arguments_expected`` is what the program's resident inputs must be
    (weights at stored width + KV pool + O(batch) operands);
    ``bytes_accessed`` is XLA's own traffic estimate for one step. A
    fused program accesses roughly arguments + outputs once; a program
    that materializes weight dequants accesses a multiple of that."""
    compiled = compiled if compiled is not None else lower_decode(core)
    ma = compiled.memory_analysis()
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):  # older jaxlib: list of per-program dicts
        ca = ca[0] if ca else {}
    weights = param_nbytes(core.params)
    kv = kv_pool_nbytes(core)
    return {
        "weights_nbytes": weights,
        "kv_pool_nbytes": kv,
        "arguments_expected": weights + kv,
        "argument_size_in_bytes": ma.argument_size_in_bytes,
        "temp_size_in_bytes": ma.temp_size_in_bytes,
        "output_size_in_bytes": ma.output_size_in_bytes,
        # Renamed across jaxlib versions (CompiledMemoryStats); absent on
        # some builds — NaN rather than AttributeError, the accounting
        # contract is the argument/temp/output split above.
        "peak_memory_in_bytes": float(getattr(
            ma, "peak_memory_in_bytes", float("nan"))),
        "bytes_accessed": float(ca.get("bytes accessed", float("nan"))),
        "flops": float(ca.get("flops", float("nan"))),
    }


def check_plan(core, plan, *, tol: float = 0.15) -> dict[str, float]:
    """Cross-check :func:`~runbookai_tpu.engine.memory_plan.plan_serving`
    arithmetic against the live engine's ACTUAL allocations (single-chip
    plans: tp=1). ``tol`` governs the WEIGHT comparison only (the plan
    approximates scale rows); KV bytes/token is pure layout arithmetic
    with no approximation, so it must match the allocated pool exactly.
    Raises AssertionError with the numbers on divergence."""
    actual_w = param_nbytes(core.params)
    kv_vals = core._kv_k[0] if isinstance(core._kv_k, tuple) else core._kv_k
    pool_tokens = kv_vals.shape[1]
    actual_kv_tok = kv_pool_nbytes(core) / pool_tokens
    got = {
        "plan_weight_bytes": plan.weight_bytes_per_chip,
        "actual_weight_bytes": actual_w,
        "plan_kv_bytes_per_token": plan.kv_bytes_per_token_per_chip,
        "actual_kv_bytes_per_token": actual_kv_tok,
    }
    w_err = abs(plan.weight_bytes_per_chip - actual_w) / max(actual_w, 1)
    kv_err = (abs(plan.kv_bytes_per_token_per_chip - actual_kv_tok)
              / max(actual_kv_tok, 1e-9))
    assert w_err <= tol, (
        f"memory plan weight arithmetic diverges from the allocated tree "
        f"by {w_err:.1%} (> {tol:.0%}): {got}")
    assert kv_err <= 1e-6, (
        f"memory plan KV bytes/token diverges from the allocated pool: {got}")
    return got
