"""Serving memory planner: does (model, context, batch) fit the chips?

SURVEY §5.7 / r3 VERDICT weak #7: long-context serving must be *planned*,
not defaulted — KV bytes scale linearly with context and dominate HBM long
before compute becomes a problem. This module is the RESIDENCY arithmetic
(with the KV-split factorization of :mod:`runbookai_tpu.parallel.kv_split`
folded in so plans stay correct past the GQA head count); it is no longer
the only planning layer: the serving-plan autotuner
(:mod:`runbookai_tpu.autotune`) composes these numbers with an HLO-bytes
roofline to search the full knob space, and its cost model delegates every
residency figure here (pinned equal by tests/test_autotune.py) — engine,
bench, docs, and tuner all quote ONE arithmetic.

The headline numbers it encodes (v5e, 16 GB/chip):

- Llama-3.1-8B int8 + fp8 KV on ONE chip: a 32k context costs ~2.1 GB of
  pool — serving it fits with room for several concurrent sequences; 128k
  costs ~8.4 GB and does NOT leave honest headroom next to ~8.5 GB of
  weights → 128k is a tp≥4 plan.
- Llama-3-70B int8 on v5e-16 (tp16 = kv8 × pg2): ~5 GB weights/chip and
  20 KB/token/chip (bf16 KV) → a 128k context is ~2.6 GB/chip; fp8 KV
  halves it.
"""

from __future__ import annotations

from dataclasses import dataclass

GiB = 1024**3


@dataclass(frozen=True)
class ServingPlan:
    model: str
    tp: int
    kv_shards: int
    pg_shards: int
    hbm_bytes: int
    weight_bytes_per_chip: int
    kv_bytes_per_token_per_chip: float
    pool_budget_bytes: int  # HBM left for the KV pool after weights+headroom
    max_seq_len: int
    batch: int
    # Host-RAM spill tier (EngineConfig.kv_spill_pages): bytes the tier
    # pins in HOST memory, not HBM — it never competes with the pool
    # budget above, but an operator sizing a box must still see it.
    host_spill_bytes: int = 0

    @property
    def context_bytes_per_chip(self) -> float:
        return self.kv_bytes_per_token_per_chip * self.max_seq_len

    @property
    def max_concurrent_contexts(self) -> int:
        if self.context_bytes_per_chip <= 0:
            return 0
        return int(self.pool_budget_bytes // self.context_bytes_per_chip)

    @property
    def fits(self) -> bool:
        return self.max_concurrent_contexts >= self.batch

    def validate_live(self, core, tol: float = 0.15) -> dict[str, float]:
        """Cross-check this plan's arithmetic against a live engine's
        ACTUAL allocations (weights tree + KV pool) via
        :func:`runbookai_tpu.engine.hlo_bytes.check_plan` — plans are
        asserted against compiled memory accounting, not trusted as hand
        arithmetic (VERDICT r4 weak #4)."""
        from runbookai_tpu.engine.hlo_bytes import check_plan

        return check_plan(core, self, tol=tol)

    def explain(self) -> str:
        spill = (f"; host spill tier {self.host_spill_bytes / GiB:.2f} GiB "
                 f"(host RAM)" if self.host_spill_bytes else "")
        return (
            f"{self.model} tp{self.tp} (kv{self.kv_shards}×pg"
            f"{self.pg_shards}): weights {self.weight_bytes_per_chip / GiB:.2f}"
            f" GiB/chip, KV {self.kv_bytes_per_token_per_chip / 1024:.1f}"
            f" KiB/token/chip → {self.max_seq_len} ctx = "
            f"{self.context_bytes_per_chip / GiB:.2f} GiB; pool budget "
            f"{self.pool_budget_bytes / GiB:.2f} GiB holds "
            f"{self.max_concurrent_contexts} concurrent (need {self.batch})"
            f" → {'FITS' if self.fits else 'DOES NOT FIT'}" + spill
        )


def plan_serving(
    cfg,
    max_seq_len: int,
    batch: int = 1,
    tp: int = 1,
    weights: str = "int8",
    kv_dtype_bytes: int = 2,
    kv_scale_bytes: int = 0,
    hbm_bytes: int = 16 * GiB,
    headroom_bytes: int = int(1.5 * GiB),
    kv_spill_pages: int = 0,
    page_size: int = 16,
) -> ServingPlan:
    """Arithmetic plan for serving ``cfg`` at ``max_seq_len`` × ``batch``.

    ``weights``: "int8" (1B/param + f32 scales, embeddings/head bf16) or
    "bf16". KV shards by the full tp via :func:`plan_kv_split` (heads as
    far as they divide, pages for the rest). ``kv_scale_bytes``: extra
    bytes per (token, kv head) — 4 for the int8 KV pool's f32 absmax
    scales, 0 for raw-dtype pools. ``kv_spill_pages`` × ``page_size``
    tokens of UNSHARDED KV are additionally pinned in host RAM (the spill
    tier holds full-width pages regardless of the device sharding) and
    reported as ``host_spill_bytes`` — host budget, never HBM.
    """
    from runbookai_tpu.parallel.kv_split import plan_kv_split

    plan = plan_kv_split(cfg, tp)

    layer_matmul = cfg.matmul_params - cfg.dim * cfg.vocab_size
    wkv = cfg.n_layers * 2 * cfg.dim * cfg.n_kv_heads * cfg.head_dim
    emb_head = 2 * cfg.vocab_size * cfg.dim  # embed + lm head (or tied x2)
    if weights == "int8":
        # wk/wv shard kv_shards-way only; everything else full-tp.
        per_chip = ((layer_matmul - wkv) / max(tp, 1)
                    + wkv / max(plan.kv_shards, 1)
                    + layer_matmul / cfg.dim * 4 / max(tp, 1)  # scales
                    + emb_head * 2 / max(tp, 1))  # bf16
    else:
        per_chip = ((layer_matmul - wkv) * 2 / max(tp, 1)
                    + wkv * 2 / max(plan.kv_shards, 1)
                    + emb_head * 2 / max(tp, 1))
    per_chip += (cfg.n_layers * 2 + 1) * cfg.dim * 4  # norms, replicated

    kv_per_token = (cfg.n_layers * 2
                    * (cfg.n_kv_heads / max(plan.kv_shards, 1))
                    * (cfg.head_dim * kv_dtype_bytes + kv_scale_bytes)
                    / max(plan.pg_shards, 1))
    budget = max(0, hbm_bytes - int(per_chip) - headroom_bytes)
    spill_token = (cfg.n_layers * 2 * cfg.n_kv_heads
                   * (cfg.head_dim * kv_dtype_bytes + kv_scale_bytes))
    return ServingPlan(
        model=cfg.name, tp=tp, kv_shards=plan.kv_shards,
        pg_shards=plan.pg_shards, hbm_bytes=hbm_bytes,
        weight_bytes_per_chip=int(per_chip),
        kv_bytes_per_token_per_chip=kv_per_token,
        pool_budget_bytes=budget, max_seq_len=max_seq_len, batch=batch,
        host_spill_bytes=int(kv_spill_pages * page_size * spill_token),
    )
