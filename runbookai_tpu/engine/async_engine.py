"""Async facade over :class:`EngineCore` — the host program's serving loop.

The agent's hot loop alternates LLM decode and tool I/O (SURVEY.md §7 hard
part 3): ``generate`` awaits a completion event while the engine loop task
keeps stepping the device for *other* live sequences, so eval DP batches and
concurrent investigations overlap tool latency with decode throughput.

Device work runs in a worker thread (``asyncio.to_thread``) so the event loop
stays free for tool HTTP/subprocess I/O; a lock serializes core mutation
between ``submit`` and ``step``.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from runbookai_tpu.engine.engine import EngineCore
from runbookai_tpu.engine.request import (
    EngineOutput,
    EngineRequest,
    FinishReason,
    SamplingParams,
)


class AsyncEngine:
    def __init__(self, core: EngineCore):
        self.core = core
        self._lock = threading.Lock()
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        # Monotonic count of engine-loop crashes (step exceptions). The
        # fleet supervisor reads this as its STICKY crash signal: a
        # caller's start() may restart a crashed loop before the
        # supervisor's next poll, but the count never un-bumps.
        self.crash_count = 0

    async def start(self) -> None:
        # A done task means the loop that owned it was torn down (e.g. a
        # caller drives each turn with its own asyncio.run) — restart on
        # the current loop, along with the loop-bound wake event, or every
        # later request would enqueue forever with nothing stepping.
        if self._task is not None and self._task.done():
            # Retrieve the crashed task's exception so asyncio doesn't log
            # "Task exception was never retrieved" at GC (the crash itself
            # was already reported by _fail_live_requests).
            try:
                self._task.exception()
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._task is None:
            self._wake = asyncio.Event()
            self._stopped = False
            self._task = asyncio.create_task(self._loop(), name="engine-loop")

    async def stop(self) -> None:
        self._stopped = True
        if self._wake:
            self._wake.set()
        task, self._task = self._task, None
        if task is not None:
            try:
                await task
            except asyncio.CancelledError:
                if not task.cancelled():
                    raise  # the cancellation targeted stop() itself, not the loop
            except Exception:  # noqa: BLE001
                pass  # step crash — already reported by _fail_live_requests
        # Drain the overlapped decode pipeline: a window dispatched on the
        # loop's final step would otherwise strand its tokens on device and
        # leave streams/done_events waiting on a drain that never comes.
        def _flush() -> None:
            with self._lock:
                self.core.flush()

        try:
            await asyncio.to_thread(_flush)
        except Exception:  # noqa: BLE001 — a poisoned core must not block stop
            pass

    async def _loop(self) -> None:
        while not self._stopped:
            with self._lock:
                has_work = self.core.has_work
            if not has_work:
                self._wake.clear()
                await self._wake.wait()
                continue
            try:
                await asyncio.to_thread(self._locked_step)
            except Exception:  # noqa: BLE001 — step blew up (e.g. device error)
                # Fail every live request NOW: letting the loop task die
                # would leave their done_events unset and every pending
                # generate()/generate_stream() awaiting forever. The next
                # caller's start() clears the done task and restarts.
                self.crash_count += 1
                self._fail_live_requests()
                raise

    def _fail_live_requests(self) -> None:
        import logging

        logging.getLogger(__name__).exception(
            "engine step failed; aborting live requests")
        with self._lock:
            for req in list(self.core.waiting) + list(self.core.prefilling) \
                    + list(self.core.decoding):
                try:
                    self.core.abort(req.request_id)
                except Exception:  # noqa: BLE001 — core state corrupted
                    # abort()'s own cleanup failed: force-finish so a
                    # restarted loop doesn't re-step a zombie and the
                    # awaiter unblocks.
                    self.core.force_finish(req)
            # Drop (don't drain) any in-flight decode window: fetching from
            # a poisoned device would raise again on every restarted loop's
            # first step, wedging has_work true forever.
            self.core.discard_inflight()

    def _locked_step(self) -> None:
        with self._lock:
            self.core.step()

    @property
    def loop_crashed(self) -> bool:
        """True when the engine-loop task died on an exception (a step
        blew up) and no stop() was requested — the fleet supervisor's
        replica-crash signal. Reading ``Task.done()`` from a foreign
        thread is safe (it's a plain state check); the exception itself
        stays unretrieved until start() clears the task."""
        task = self._task
        if self._stopped or task is None or not task.done():
            return False
        if task.cancelled():
            return False
        return task.exception() is not None

    def debug_steps(self, last_n: Optional[int] = None,
                    lock_timeout: float = 0.5) -> dict:
        """Flight-recorder snapshot for ``GET /debug/steps``.

        Taken under the step lock (bounded wait, same contract as the
        ``/healthz`` snapshot: a step busy compiling can hold the lock
        for tens of seconds and a debug probe must not hang that long —
        a torn-by-one-record snapshot beats a wedged prober)."""
        locked = self._lock.acquire(timeout=lock_timeout)
        try:
            flight = self.core.flight
            return {
                "capacity": flight.capacity,
                "steps_total": flight.total_steps,
                "steps": flight.snapshot(last_n),
            }
        finally:
            if locked:
                self._lock.release()

    async def run_locked(self, fn):
        """Run ``fn()`` under the step lock in a worker thread and return
        its result. The seam the fleet's KV page transfers go through:
        export/import must see a quiesced core (no step mid-flight
        mutating the pool arrays), and the lock wait happens off the
        event loop so every in-flight stream keeps draining while a slow
        step finishes."""

        def _locked():
            with self._lock:
                return fn()

        return await asyncio.to_thread(_locked)

    async def refresh_lora(self) -> None:
        """Swap in the registry's latest stacked adapters between steps.
        The lock wait happens in a worker thread so the event loop (and
        every in-flight stream) stays live while a step finishes."""

        def _locked_refresh() -> None:
            with self._lock:
                self.core.refresh_lora()

        await asyncio.to_thread(_locked_refresh)

    async def generate(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        timeout_s: Optional[float] = None,
        priority: int = 0,
        adapter: Optional[str] = None,
        request_id: Optional[str] = None,
        arrival_time: Optional[float] = None,
    ) -> EngineOutput:
        """Submit one request and await its completion.

        With ``timeout_s``, a stalled generation is ABORTED in the engine
        (slot + KV pages freed) before ``TimeoutError`` propagates — a
        caller-side timeout alone would leave the request decoding to
        max_new_tokens for nobody. ``request_id`` (the server's
        x-request-id) rides into the engine's tracer records for
        trace-to-request correlation. ``arrival_time`` (a perf_counter
        reading) backdates the TTFT clock to when the request entered
        the SYSTEM — the fleet passes its routing-entry time so disagg
        warm prefills and page pulls stay inside the measured TTFT."""
        await self.start()  # idempotent; restarts after a torn-down loop
        req = EngineRequest(prompt_ids=prompt_ids,
                            sampling=sampling or SamplingParams(),
                            priority=priority, adapter=adapter,
                            trace_id=request_id)
        if arrival_time is not None:
            req.arrival_time = arrival_time
        req.done_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        # done_event.set() happens on a worker thread; bridge it safely.
        done = loop.create_future()

        class _Event:
            def set(self_inner) -> None:  # noqa: N805
                loop.call_soon_threadsafe(
                    lambda: done.done() or done.set_result(True)
                )

        req.done_event = _Event()  # type: ignore[assignment]
        with self._lock:
            self.core.submit(req)
        self._wake.set()
        # No liveness re-check needed: there is no await between start()
        # and this point, so a loop crash can only be delivered once we
        # suspend below — and its abort sweep then sees this request in
        # the pools and resolves our future.
        if timeout_s is None:
            await done
        else:
            try:
                await asyncio.wait_for(done, timeout_s)
            except asyncio.TimeoutError:
                with self._lock:
                    aborted = self.core.abort(req.request_id)
                # Race: the request can finish in the window between
                # wait_for timing out and the abort taking the lock. abort
                # returns False for already-finished requests — a completed
                # generation must not be reported as a timeout.
                if not aborted and req.finish_reason not in (None, "aborted"):
                    return self.core.output_for(req)
                raise TimeoutError(
                    f"generation exceeded {timeout_s}s (request aborted)")
        return self.core.output_for(req)

    async def generate_stream(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        priority: int = 0,
        adapter: Optional[str] = None,
        request_sink: Optional[list] = None,
        request_id: Optional[str] = None,
        arrival_time: Optional[float] = None,
    ):
        """Async iterator of token ids as the engine samples them.

        Token callbacks fire on the engine's worker thread and bridge to
        the caller's loop through an asyncio queue; ``None`` is the
        completion sentinel. Stop tokens ARE yielded (callers that render
        text should skip ids in their stop set, as ``output_for`` does) —
        see ``JaxTpuClient.chat_stream`` for the text-level wrapper.
        """
        await self.start()  # idempotent; restarts after a torn-down loop
        req = EngineRequest(prompt_ids=prompt_ids,
                            sampling=sampling or SamplingParams(),
                            priority=priority, adapter=adapter,
                            trace_id=request_id)
        if arrival_time is not None:
            req.arrival_time = arrival_time
        if request_sink is not None:
            # Streaming consumers that need per-token request state
            # (logprob entries accumulate on the engine worker thread;
            # CPython list appends are atomic, so index reads are safe).
            request_sink.append(req)
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def on_token(tok: int) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, tok)

        class _Event:
            def set(self_inner) -> None:  # noqa: N805
                loop.call_soon_threadsafe(queue.put_nowait, None)

        req.on_token = on_token
        req.done_event = _Event()  # type: ignore[assignment]
        with self._lock:
            self.core.submit(req)
        self._wake.set()
        try:
            while True:
                tok = await queue.get()
                if tok is None:
                    break
                yield tok
        finally:
            # Early exit (consumer break / exception): free the slot + KV
            # pages instead of decoding to max_new_tokens for nobody.
            if req.finish_reason is None:
                with self._lock:
                    self.core.abort(req.request_id)
