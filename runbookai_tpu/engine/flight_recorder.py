"""Engine flight recorder: a bounded in-memory ring of per-step records.

PR 1's histograms answer "how slow is the tail"; this module answers the
question that follows — "what was the engine *doing* on the slow steps?"
(FlashInfer-Bench's thesis: a serving stack improves only when every
measured run leaves a machine-readable record of what actually executed.)

One :class:`StepRecord`-shaped dict is appended per
:meth:`EngineCore.step`: step index, dispatch kind (the PR 4 counters:
prefill / decode / mixed — plus ``prefill+decode`` for a split step that
ran both, and ``idle`` for a drain-only step), real tokens this dispatch,
batch occupancy (total AND per priority class — the scheduler-fairness
picture), queue depth, KV-pool free pages, the dispatch/host/overlap wall
split, preemptions, and the replica index when fleeted.

Design constraints (pinned by ``tests/test_observability.py``):

- **O(1) append, no lock**: the buffer is preallocated and the writer is
  the engine step thread (already serialized by the AsyncEngine lock);
  a slot assignment + cursor bump is the entire hot-path cost. Readers
  (``/debug/steps`` scrapes) snapshot under that same engine lock — or
  tolerate a one-record tear when they cannot afford to wait, exactly
  like the scrape gauges.
- **Bounded**: ``capacity`` records, oldest overwritten. A 1800s soak at
  ~50 steps/s stays a few MB regardless of run length.
- **Dumpable**: :meth:`snapshot` (newest-last dicts) for ``/debug/steps``
  and the AsyncFleet aggregation, :meth:`dump_jsonl` for offline diffing,
  :meth:`summary` for bench's ``flight_summary`` provenance block.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

from runbookai_tpu.utils.trace import _percentile

# The per-step record keys, in emission order (documentation + the
# /debug/steps shape test import this so the wire contract is pinned).
STEP_RECORD_FIELDS = (
    "step", "ts", "kind", "classes", "tokens", "batch", "occupancy",
    "queue_depth", "kv_free_pages", "kv_utilization", "dispatch_s",
    "host_s", "overlap_s", "wall_s", "preemptions", "kv_imported",
    "kv_exported", "replica",
)


class FlightRecorder:
    """Preallocated ring of the last ``capacity`` step records."""

    __slots__ = ("capacity", "_buf", "_next")

    def __init__(self, capacity: int):
        self.capacity = max(0, int(capacity))
        self._buf: list[Optional[dict[str, Any]]] = [None] * self.capacity
        self._next = 0  # monotonically increasing step cursor

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    @property
    def total_steps(self) -> int:
        """Steps recorded since construction (including overwritten ones)."""
        return self._next

    def __len__(self) -> int:
        return min(self._next, self.capacity)

    def append(self, rec: dict[str, Any]) -> None:
        """O(1), allocation-free beyond the caller's dict; no lock (the
        engine step thread is the only writer)."""
        if not self.capacity:
            return
        rec["step"] = self._next
        self._buf[self._next % self.capacity] = rec
        self._next += 1

    def reset(self) -> None:
        """Drop every record and restart the step cursor (bench warmup:
        the measured window's provenance must exclude compile traffic)."""
        self._buf = [None] * self.capacity
        self._next = 0

    def snapshot(self, last_n: Optional[int] = None) -> list[dict[str, Any]]:
        """Oldest→newest copies of the retained records (at most
        ``last_n``). Each record is shallow-copied so callers can JSON-
        serialize outside the engine lock without racing the writer."""
        n = len(self)
        if last_n is not None:
            n = min(n, max(0, int(last_n)))
        start = self._next - n
        return [dict(self._buf[i % self.capacity])
                for i in range(start, self._next)
                if self._buf[i % self.capacity] is not None]

    def dump_jsonl(self, path: str | Path) -> int:
        """Write the retained records as JSONL; returns the record count."""
        records = self.snapshot()
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "w") as fh:
            for rec in records:
                fh.write(json.dumps(rec) + "\n")
        return len(records)

    @staticmethod
    def merge_summaries(summaries: list[dict[str, Any]]) -> dict[str, Any]:
        """Fleet-wide roll-up of per-replica :meth:`summary` blocks:
        dispatch kinds and tokens sum, pressure peaks take the max, and
        occupancy percentiles report the worst replica (the one whose
        batch ran fullest — the capacity-planning signal)."""
        kinds: dict[str, int] = {}
        classes: dict[str, int] = {}
        merged: dict[str, Any] = {
            "steps_recorded": 0, "steps_total": 0, "capacity": 0,
            "tokens": 0, "occupancy_p50": 0.0, "occupancy_p95": 0.0,
            "kv_utilization_peak": 0.0, "queue_depth_peak": 0,
        }
        for s in summaries:
            for kind, count in s.get("dispatch_kinds", {}).items():
                kinds[kind] = kinds.get(kind, 0) + count
            for cls, count in s.get("class_slot_steps", {}).items():
                classes[cls] = classes.get(cls, 0) + count
            for key in ("steps_recorded", "steps_total", "capacity",
                        "tokens"):
                merged[key] += s.get(key, 0)
            for key in ("occupancy_p50", "occupancy_p95",
                        "kv_utilization_peak", "queue_depth_peak"):
                merged[key] = max(merged[key], s.get(key, 0))
        merged["dispatch_kinds"] = dict(sorted(kinds.items()))
        merged["class_slot_steps"] = dict(sorted(classes.items()))
        return merged

    def summary(self) -> dict[str, Any]:
        """Step-level provenance for a measured run (bench
        ``flight_summary``): per-dispatch-kind step counts, occupancy
        p50/p95, and the KV-pressure peak over the retained window."""
        records = self.snapshot()
        kinds: dict[str, int] = {}
        classes: dict[str, int] = {}
        occ: list[float] = []
        kv_peak = 0.0
        queue_peak = 0
        tokens = 0
        for rec in records:
            kinds[str(rec.get("kind", "?"))] = (
                kinds.get(str(rec.get("kind", "?")), 0) + 1)
            for cls, n in (rec.get("classes") or {}).items():
                # Slot-steps per priority class: who actually occupied
                # the decode batch over the window (the scheduler's
                # fairness evidence in bench flight summaries).
                classes[str(cls)] = classes.get(str(cls), 0) + int(n)
            occ.append(float(rec.get("occupancy", 0.0)))
            kv_peak = max(kv_peak, float(rec.get("kv_utilization", 0.0)))
            queue_peak = max(queue_peak, int(rec.get("queue_depth", 0)))
            tokens += int(rec.get("tokens", 0))
        occ.sort()
        return {
            "steps_recorded": len(records),
            "steps_total": self.total_steps,
            "capacity": self.capacity,
            "dispatch_kinds": dict(sorted(kinds.items())),
            "class_slot_steps": dict(sorted(classes.items())),
            "tokens": tokens,
            "occupancy_p50": round(_percentile(occ, 50), 4),
            "occupancy_p95": round(_percentile(occ, 95), 4),
            "kv_utilization_peak": round(kv_peak, 4),
            "queue_depth_peak": queue_peak,
        }
